// Native FFD solver core.
//
// The compiled host-side implementation of solver/SPEC.md's FFD semantics
// over the SAME encoded int32 tensors the TPU kernel consumes
// (karpenter_tpu/solver/encode.py). Role in the framework:
//
//   * the fast CPU fallback when the device is unavailable or the input is
//     below the device-dispatch threshold — matching the compiled-language
//     performance class of the reference's Go scheduler rather than the
//     Python oracle's;
//   * a third leg for differential testing (python-oracle == C++ == TPU).
//
// Pure C ABI (ctypes-loaded, no pybind11 in this image). Single-threaded by
// design: one solve is inherently sequential; parallelism lives above
// (batched candidate simulation) and below (vectorized device kernel).
//
// Algorithm: identical semantics to solver/tpu/ffd.py. Runs of identical
// pods pour first-fit over existing nodes, then open claims, then
// closed-form new-node opening per pool in priority order with limit
// accounting. Hostname constraints (Q axis: per-target matching-pod caps)
// bound every pour. Zone constraints (V axis: spread skew, (anti-)affinity)
// switch the run to PER-POD placement — the sequential core doesn't need the
// device's closed-form event batching, it just walks pods applying the
// joint allowed-zone set and the commit rules of solver/SPEC.md ("Topology
// spread", "Inter-pod affinity", joint narrowing).

#include <cstdint>
#include <cstring>
#include <algorithm>
#include <vector>

namespace {

constexpr int32_t BIG = 1 << 30;

inline int32_t fit_count_row(const int32_t* alloc, const int32_t* cum,
                             const int32_t* req, int32_t R) {
  int32_t k = BIG;
  for (int32_t r = 0; r < R; ++r) {
    if (req[r] > 0) {
      int32_t rem = alloc[r] - cum[r];
      int32_t kr = rem >= 0 ? rem / req[r] : -1;
      k = std::min(k, kr);
    }
  }
  return std::max(k, 0);
}

// Per-target additional-pod allowance under the hostname sigs (ffd.py
// _hostname_allowance; SPEC.md hostname floor-0 rule).
inline int32_t hostname_allow(const int32_t* cm, const int32_t* co,
                              const int32_t* q_kind, const int32_t* q_cap,
                              const uint8_t* member_g, const uint8_t* owner_g,
                              int32_t Q) {
  int32_t allow = BIG;
  for (int32_t q = 0; q < Q; ++q) {
    const bool member = member_g[q], owner = owner_g[q];
    const int32_t kind = q_kind[q];
    const bool relevant = owner || (kind == 1 && member);
    if (!relevant) continue;
    int32_t a;
    if (kind == 0) {
      a = member ? (q_cap[q] - cm[q]) : (cm[q] + 1 <= q_cap[q] ? BIG : 0);
    } else if (kind == 2) {
      // positive hostname affinity: join only member-holding targets;
      // the fresh-claim bootstrap is a claim-count budget at the caller
      a = (cm[q] > 0) ? BIG : 0;
    } else if (owner) {
      a = (cm[q] == 0) ? (member ? 1 : BIG) : 0;
    } else {  // anti, member only
      a = (co[q] == 0) ? BIG : 0;
    }
    allow = std::min(allow, a);
  }
  return std::max(allow, 0);
}

}  // namespace

extern "C" {

// Returns 0 on success, 1 on claim-slot overflow.
// Outputs: take_e [S,E], take_c [S,M], leftover [S], c_mask [M,T] u8,
//          c_zone [M,Z] u8, c_ct [M,C] u8, c_gmask [M,G] u8, c_pool [M],
//          c_cum [M,R], used [1].
int ffd_solve_native(
    // dims. DD = total V-domain columns: Z for single-axis solves (the
    // historical layout; the ct-granular case arrives pre-swapped by the
    // marshaler), Z + C for MIXED solves — zone columns first, then
    // capacity-type columns in the marshaler's lex order (the C axis itself
    // is permuted to lex order in that mode, so ct index == domain rank).
    int32_t S, int32_t G, int32_t T, int32_t E, int32_t P, int32_t R,
    int32_t Z, int32_t C, int32_t M, int32_t Q, int32_t V, int32_t DD,
    // runs
    const int32_t* run_group, const int32_t* run_count,
    // groups
    const int32_t* group_req,       // [G,R]
    const uint8_t* group_compat_t,  // [G,T]
    const uint8_t* group_zone,      // [G,Z]
    const uint8_t* group_ct,        // [G,C]
    const uint8_t* group_pool,      // [G,P]
    const uint8_t* group_pair,      // [G,G]
    const uint8_t* group_device,    // [G] (1 = handle here)
    // types
    const int32_t* type_alloc,      // [T,R]
    const int32_t* type_charge,     // [T,R]
    const uint8_t* offer_avail,     // [T,Z,C]
    // pools
    const uint8_t* pool_type,       // [P,T]
    const uint8_t* pool_zone,       // [P,Z]
    const uint8_t* pool_ct,         // [P,C]
    const int32_t* pool_daemon,     // [P,R]
    const int32_t* pool_limit,      // [P,R]
    const int32_t* pool_usage0,     // [P,R]
    // existing nodes
    const int32_t* node_free,       // [E,R]
    const uint8_t* node_compat,     // [G,E]
    const int32_t* node_zone,       // [E] (-1 unknown)
    // hostname constraint sigs (Q axis)
    const uint8_t* q_member,        // [G,Q]
    const uint8_t* q_owner,         // [G,Q]
    const int32_t* q_kind,          // [Q]
    const int32_t* q_cap,           // [Q]
    const int32_t* node_q_member,   // [E,Q]
    const int32_t* node_q_owner,    // [E,Q]
    // zone constraint sigs (V axis)
    const uint8_t* v_member,        // [G,V]
    const uint8_t* v_owner,         // [G,V]
    const int32_t* v_kind,          // [V]
    const int32_t* v_cap,           // [V]
    const int32_t* v_primary,       // [G] owned zone-TSC sig (-1)
    const int32_t* v_aff,           // [G] owned positive-affinity sig (-1)
    const int32_t* v_count0,        // [V,DD]
    const int32_t* sig_axis,        // [V] 0 = zone axis, 1 = ct axis
    const int32_t* group_daxis,     // [G] axis a constrained group binds to
    const int32_t* node_ct,         // [E] ct domain column (-1 unknown)
    // outputs
    int32_t* take_e, int32_t* take_c, int32_t* leftover,
    uint8_t* c_mask, uint8_t* c_zone, uint8_t* c_ct, uint8_t* c_gmask,
    int32_t* c_pool, int32_t* c_cum, int32_t* used_out) {
  // Kind-3 (admission-only weighted-anti) sigs are not implemented here —
  // the v_kind==1 guards below would silently drop their admission
  // semantics. Refuse loudly so the caller falls back to the oracle.
  for (int32_t v = 0; v < V; ++v)
    if (v_kind[v] == 3) return 2;
  std::vector<int32_t> e_cum(static_cast<size_t>(E) * R, 0);
  std::vector<int32_t> p_usage(pool_usage0, pool_usage0 + static_cast<size_t>(P) * R);
  std::memset(take_e, 0, sizeof(int32_t) * S * E);
  std::memset(take_c, 0, sizeof(int32_t) * S * M);
  std::memset(leftover, 0, sizeof(int32_t) * S);
  std::memset(c_mask, 0, static_cast<size_t>(M) * T);
  std::memset(c_zone, 0, static_cast<size_t>(M) * Z);
  std::memset(c_ct, 0, static_cast<size_t>(M) * C);
  std::memset(c_gmask, 0, static_cast<size_t>(M) * G);
  std::memset(c_cum, 0, sizeof(int32_t) * M * R);
  for (int32_t m = 0; m < M; ++m) c_pool[m] = -1;
  int32_t used = 0;
  bool overflow = false;

  // hostname (Q) counts per target
  std::vector<int32_t> e_cm(node_q_member, node_q_member + static_cast<size_t>(E) * Q);
  std::vector<int32_t> e_co(node_q_owner, node_q_owner + static_cast<size_t>(E) * Q);
  std::vector<int32_t> c_cm(static_cast<size_t>(M) * Q, 0);
  std::vector<int32_t> c_co(static_cast<size_t>(M) * Q, 0);
  // domain (V) state — stride DD (zone cols, then ct cols under mixed)
  const bool mixed = DD > Z;
  std::vector<int32_t> v_count(v_count0, v_count0 + static_cast<size_t>(V) * DD);
  std::vector<uint8_t> v_owner_z(static_cast<size_t>(V) * DD, 0);
  std::vector<int32_t> c_vm(static_cast<size_t>(M) * V, 0);
  std::vector<uint8_t> c_vo(static_cast<size_t>(M) * V, 0);

  std::vector<int32_t> k_t(T);  // per-type capacity scratch
  std::vector<uint8_t> fit_t(T);
  const int32_t NDmax = std::max(Z, C);
  std::vector<uint8_t> A(NDmax), A_base(NDmax), inter(NDmax);
  std::vector<int32_t> charge_one(R);

  auto claim_zone_count = [&](int32_t m) {
    int32_t n = 0;
    for (int32_t z = 0; z < Z; ++z) n += c_zone[static_cast<size_t>(m) * Z + z] ? 1 : 0;
    return n;
  };
  auto claim_ct_count = [&](int32_t m) {
    int32_t n = 0;
    for (int32_t c = 0; c < C; ++c) n += c_ct[static_cast<size_t>(m) * C + c] ? 1 : 0;
    return n;
  };
  // record one placed pod (or `take` pods) of group g onto a target whose
  // determined domains are zone_col (or -1) / ct_col (or -1): member counts
  // accrue on EVERY determined axis (the oracle records every determined
  // topology key); owned-anti registration keys on the TERM's axis.
  auto record_target = [&](const uint8_t* member_v_g, const uint8_t* owner_v_g,
                           int32_t zone_col, int32_t ct_col, int32_t take) {
    for (int32_t v = 0; v < V; ++v) {
      if (member_v_g[v]) {
        if (zone_col >= 0)
          v_count[static_cast<size_t>(v) * DD + zone_col] += take;
        if (mixed && ct_col >= 0)
          v_count[static_cast<size_t>(v) * DD + Z + ct_col] += take;
      }
      if (owner_v_g[v] && v_kind[v] == 1 && take > 0) {
        const int32_t col = (mixed && sig_axis[v] == 1) ? (ct_col >= 0 ? Z + ct_col : -1)
                                                        : zone_col;
        if (col >= 0) v_owner_z[static_cast<size_t>(v) * DD + col] = 1;
      }
    }
  };

  for (int32_t s = 0; s < S; ++s) {
    const int32_t g = run_group[s];
    int32_t remaining = group_device[g] ? run_count[s] : 0;
    const int32_t* req = group_req + static_cast<size_t>(g) * R;
    const uint8_t* gz = group_zone + static_cast<size_t>(g) * Z;
    const uint8_t* gc = group_ct + static_cast<size_t>(g) * C;
    const uint8_t* member_q = q_member + static_cast<size_t>(g) * Q;
    const uint8_t* owner_q = q_owner + static_cast<size_t>(g) * Q;
    const uint8_t* member_v_g = v_member + static_cast<size_t>(g) * V;
    const uint8_t* owner_v_g = v_owner + static_cast<size_t>(g) * V;

    bool zone_constrained = false;
    bool has_owned = false;
    for (int32_t v = 0; v < V; ++v) {
      if (owner_v_g[v]) { zone_constrained = true; has_owned = true; }
      if (member_v_g[v] && v_kind[v] == 1) zone_constrained = true;
    }

    // kind-2 (positive hostname affinity) bookkeeping: owner mask with
    // kind-2 columns cleared (fresh allowance + bootstrap pour ignore them),
    // plus the one-claim bootstrap budget — while no members of every owned
    // kind-2 sig exist anywhere, the group lands FIRST-FIT on a single
    // target (first node, else first claim, else one fresh claim) and
    // co-locates there; once members exist, only member-holding targets
    // admit and no fresh claims open (ffd.py fast() mirror).
    std::vector<uint8_t> owner_nb(static_cast<size_t>(std::max(Q, 1)));
    bool any2 = false, boot_all = true;
    for (int32_t q = 0; q < Q; ++q) {
      owner_nb[q] = (owner_q[q] && q_kind[q] != 2) ? 1 : 0;
      if (owner_q[q] && q_kind[q] == 2) {
        any2 = true;
        long long tot = 0;
        for (int32_t e = 0; e < E; ++e) tot += e_cm[static_cast<size_t>(e) * Q + q];
        for (int32_t m = 0; m < used; ++m) tot += c_cm[static_cast<size_t>(m) * Q + q];
        if (!member_q[q] || tot > 0) boot_all = false;
      }
    }
    const bool boot2 = any2 && boot_all;
    const uint8_t* owner_eff = boot2 ? owner_nb.data() : owner_q;
    int32_t new_claim_cap = any2 ? (boot2 ? 1 : 0) : BIG;
    bool boot_done = false;

    const int32_t fresh_allow = hostname_allow(
        std::vector<int32_t>(Q, 0).data(), std::vector<int32_t>(Q, 0).data(),
        q_kind, q_cap, member_q, owner_nb.data(), Q);

    // run-level domain-count contribution bookkeeping (fast path): which
    // claims received pods this run, and per-domain node takes PER AXIS
    std::vector<int32_t> node_take_z(Z, 0);
    std::vector<int32_t> node_take_c(C, 0);
    std::vector<int32_t> claim_take(M, 0);

    auto record_v_counts_fast = [&]() {
      if (V == 0) return;
      std::vector<int32_t> contrib(DD, 0);
      for (int32_t z = 0; z < Z; ++z) contrib[z] = node_take_z[z];
      if (mixed)
        for (int32_t c = 0; c < C; ++c) contrib[Z + c] = node_take_c[c];
      for (int32_t m = 0; m < used; ++m) {
        if (claim_take[m] <= 0) continue;
        // per-axis singleness: a claim records on every axis where its
        // domain is determined (multi-valued on an axis: no count there)
        if (claim_zone_count(m) == 1)
          for (int32_t z = 0; z < Z; ++z)
            if (c_zone[static_cast<size_t>(m) * Z + z]) contrib[z] += claim_take[m];
        if (mixed && claim_ct_count(m) == 1)
          for (int32_t c = 0; c < C; ++c)
            if (c_ct[static_cast<size_t>(m) * C + c]) contrib[Z + c] += claim_take[m];
      }
      for (int32_t v = 0; v < V; ++v) {
        if (!member_v_g[v]) continue;
        for (int32_t d = 0; d < DD; ++d)
          v_count[static_cast<size_t>(v) * DD + d] += contrib[d];
      }
    };

    if (!zone_constrained) {
      // ================= FAST path: run-granular pours ====================
      // ---- 1. existing nodes --------------------------------------------
      for (int32_t e = 0; e < E && remaining > 0; ++e) {
        if (!node_compat[static_cast<size_t>(g) * E + e]) continue;
        int32_t cap = fit_count_row(node_free + static_cast<size_t>(e) * R,
                                    e_cum.data() + static_cast<size_t>(e) * R, req, R);
        cap = std::min(cap, hostname_allow(
            e_cm.data() + static_cast<size_t>(e) * Q,
            e_co.data() + static_cast<size_t>(e) * Q,
            q_kind, q_cap, member_q, owner_eff, Q));
        int32_t take = std::min(cap, remaining);
        if (take > 0) {
          take_e[static_cast<size_t>(s) * E + e] = take;
          for (int32_t r = 0; r < R; ++r)
            e_cum[static_cast<size_t>(e) * R + r] += take * req[r];
          for (int32_t q = 0; q < Q; ++q) {
            if (member_q[q]) e_cm[static_cast<size_t>(e) * Q + q] += take;
            if (owner_q[q] && q_kind[q] == 1) e_co[static_cast<size_t>(e) * Q + q] += 1;
          }
          if (node_zone[e] >= 0) node_take_z[node_zone[e]] += take;
          if (mixed && node_ct[e] >= 0) node_take_c[node_ct[e]] += take;
          remaining -= take;
          if (boot2) { boot_done = true; break; }  // single bootstrap target
        }
      }

      // ---- 2. open claims -------------------------------------------------
      for (int32_t m = 0; m < used && remaining > 0 && !boot_done; ++m) {
        const int32_t p = c_pool[m];
        if (p < 0 || !group_pool[static_cast<size_t>(g) * P + p]) continue;
        bool pair_ok = true;
        for (int32_t g2 = 0; g2 < G && pair_ok; ++g2)
          if (c_gmask[static_cast<size_t>(m) * G + g2] &&
              !group_pair[static_cast<size_t>(g) * G + g2])
            pair_ok = false;
        if (!pair_ok) continue;
        int32_t cap = 0;
        for (int32_t t = 0; t < T; ++t) {
          fit_t[t] = 0;
          if (!c_mask[static_cast<size_t>(m) * T + t]) continue;
          if (!group_compat_t[static_cast<size_t>(g) * T + t]) continue;
          bool off_ok = false;
          for (int32_t z = 0; z < Z && !off_ok; ++z) {
            if (!(c_zone[static_cast<size_t>(m) * Z + z] && gz[z])) continue;
            for (int32_t c = 0; c < C; ++c)
              if (c_ct[static_cast<size_t>(m) * C + c] && gc[c] &&
                  offer_avail[(static_cast<size_t>(t) * Z + z) * C + c]) {
                off_ok = true;
                break;
              }
          }
          if (!off_ok) continue;
          int32_t kt = fit_count_row(type_alloc + static_cast<size_t>(t) * R,
                                     c_cum + static_cast<size_t>(m) * R, req, R);
          k_t[t] = kt;
          fit_t[t] = 1;
          cap = std::max(cap, kt);
        }
        cap = std::min(cap, hostname_allow(
            c_cm.data() + static_cast<size_t>(m) * Q,
            c_co.data() + static_cast<size_t>(m) * Q,
            q_kind, q_cap, member_q, owner_eff, Q));
        int32_t take = std::min(cap, remaining);
        if (take > 0) {
          take_c[static_cast<size_t>(s) * M + m] += take;
          claim_take[m] += take;
          for (int32_t r = 0; r < R; ++r)
            c_cum[static_cast<size_t>(m) * R + r] += take * req[r];
          for (int32_t t = 0; t < T; ++t)
            c_mask[static_cast<size_t>(m) * T + t] =
                (fit_t[t] && k_t[t] >= take) ? 1 : 0;
          for (int32_t z = 0; z < Z; ++z)
            c_zone[static_cast<size_t>(m) * Z + z] &= gz[z];
          for (int32_t c = 0; c < C; ++c)
            c_ct[static_cast<size_t>(m) * C + c] &= gc[c];
          c_gmask[static_cast<size_t>(m) * G + g] = 1;
          for (int32_t q = 0; q < Q; ++q) {
            if (member_q[q]) c_cm[static_cast<size_t>(m) * Q + q] += take;
            if (owner_q[q] && q_kind[q] == 1) c_co[static_cast<size_t>(m) * Q + q] += 1;
          }
          for (int32_t v = 0; v < V; ++v)
            if (member_v_g[v]) c_vm[static_cast<size_t>(m) * V + v] += take;
          remaining -= take;
          if (boot2) { boot_done = true; break; }  // single bootstrap target
        }
      }
      if (boot_done) new_claim_cap = 0;  // bootstrap target found: no opens

      // ---- 3. new claims, pool by pool ------------------------------------
      for (int32_t p = 0; p < P && remaining > 0 && new_claim_cap > 0; ++p) {
        if (!group_pool[static_cast<size_t>(g) * P + p]) continue;
        bool over = false;
        for (int32_t r = 0; r < R; ++r)
          if (p_usage[static_cast<size_t>(p) * R + r] >= pool_limit[static_cast<size_t>(p) * R + r])
            over = true;
        if (over) continue;
        const int32_t* daemon = pool_daemon + static_cast<size_t>(p) * R;
        int32_t kmax = 0;
        for (int32_t t = 0; t < T; ++t) {
          fit_t[t] = 0;
          if (!group_compat_t[static_cast<size_t>(g) * T + t]) continue;
          if (!pool_type[static_cast<size_t>(p) * T + t]) continue;
          bool off_ok = false;
          for (int32_t z = 0; z < Z && !off_ok; ++z) {
            if (!(pool_zone[static_cast<size_t>(p) * Z + z] && gz[z])) continue;
            for (int32_t c = 0; c < C; ++c)
              if (pool_ct[static_cast<size_t>(p) * C + c] && gc[c] &&
                  offer_avail[(static_cast<size_t>(t) * Z + z) * C + c]) {
                off_ok = true;
                break;
              }
          }
          if (!off_ok) continue;
          int32_t k = BIG;
          for (int32_t r = 0; r < R; ++r)
            if (req[r] > 0) {
              int32_t rem = type_alloc[static_cast<size_t>(t) * R + r] - daemon[r];
              k = std::min(k, rem >= 0 ? rem / req[r] : -1);
            }
          k = std::max(k, 0);
          k_t[t] = k;
          fit_t[t] = 1;
          kmax = std::max(kmax, k);
        }
        const int32_t full_take = std::min(kmax, fresh_allow);
        if (full_take <= 0) continue;

        for (int32_t r = 0; r < R; ++r) {
          int32_t mn = BIG;
          for (int32_t t = 0; t < T; ++t)
            if (fit_t[t] && k_t[t] >= 1)
              mn = std::min(mn, type_charge[static_cast<size_t>(t) * R + r]);
          charge_one[r] = (mn == BIG) ? 0 : mn;
        }

        while (remaining > 0 && new_claim_cap > 0) {
          bool blocked = false;
          for (int32_t r = 0; r < R; ++r)
            if (p_usage[static_cast<size_t>(p) * R + r] >=
                pool_limit[static_cast<size_t>(p) * R + r])
              blocked = true;
          if (blocked) break;
          if (used >= M) { overflow = true; break; }
          const int32_t m = used++;
          const int32_t take = std::min(full_take, remaining);
          take_c[static_cast<size_t>(s) * M + m] = take;
          claim_take[m] = take;
          c_pool[m] = p;
          for (int32_t r = 0; r < R; ++r)
            c_cum[static_cast<size_t>(m) * R + r] = daemon[r] + take * req[r];
          for (int32_t t = 0; t < T; ++t)
            c_mask[static_cast<size_t>(m) * T + t] = (fit_t[t] && k_t[t] >= take) ? 1 : 0;
          for (int32_t z = 0; z < Z; ++z)
            c_zone[static_cast<size_t>(m) * Z + z] =
                pool_zone[static_cast<size_t>(p) * Z + z] && gz[z];
          for (int32_t c = 0; c < C; ++c)
            c_ct[static_cast<size_t>(m) * C + c] =
                pool_ct[static_cast<size_t>(p) * C + c] && gc[c];
          c_gmask[static_cast<size_t>(m) * G + g] = 1;
          for (int32_t q = 0; q < Q; ++q) {
            if (member_q[q]) c_cm[static_cast<size_t>(m) * Q + q] = take;
            if (owner_q[q] && q_kind[q] == 1 && take > 0)
              c_co[static_cast<size_t>(m) * Q + q] = 1;
          }
          for (int32_t v = 0; v < V; ++v)
            if (member_v_g[v]) c_vm[static_cast<size_t>(m) * V + v] = take;
          for (int32_t r = 0; r < R; ++r)
            p_usage[static_cast<size_t>(p) * R + r] += charge_one[r];
          remaining -= take;
          if (new_claim_cap != BIG) --new_claim_cap;  // kind-2 budget
        }
        if (overflow) break;
      }
      record_v_counts_fast();
      leftover[s] = remaining;
      if (overflow) break;
      continue;
    }

    // ================= ZONE path: per-pod placement =======================
    // (solver/tpu/ffd.py zoned branch semantics, walked one pod at a time.
    // Under mixed-axis solves the group's engine runs over ITS axis's
    // domain columns — ax=1 swaps the zone-role arrays for the ct ones;
    // encode guarantees a device group's owned/anti sigs are single-axis.)
    const int32_t ax = mixed ? group_daxis[g] : 0;
    const int32_t ND = ax ? C : Z;   // domains on the group's axis
    const int32_t D0 = ax ? Z : 0;   // column offset into the v tables
    const uint8_t* g_dom = ax ? gc : gz;
    auto node_dom = [&](int32_t e) { return ax ? node_ct[e] : node_zone[e]; };
    auto c_dom = [&](int32_t m, int32_t d) -> bool {
      return ax ? (c_ct[static_cast<size_t>(m) * C + d] != 0)
                : (c_zone[static_cast<size_t>(m) * Z + d] != 0);
    };
    auto pool_dom = [&](int32_t p, int32_t d) -> bool {
      return ax ? (pool_ct[static_cast<size_t>(p) * C + d] != 0)
                : (pool_zone[static_cast<size_t>(p) * Z + d] != 0);
    };
    // claim recording: determined-domain column per axis (-1 when multi)
    auto record_claim = [&](int32_t m, int32_t take) {
      int32_t zcol = -1, ccol = -1;
      if (claim_zone_count(m) == 1)
        for (int32_t z = 0; z < Z; ++z)
          if (c_zone[static_cast<size_t>(m) * Z + z]) zcol = z;
      if (mixed && claim_ct_count(m) == 1)
        for (int32_t c = 0; c < C; ++c)
          if (c_ct[static_cast<size_t>(m) * C + c]) ccol = c;
      record_target(member_v_g, owner_v_g, zcol, ccol, take);
    };
    const int32_t psig = v_primary[g];
    const bool has_tsc = psig >= 0;
    const int32_t cap_p = has_tsc ? v_cap[psig] : 0;
    const int32_t asig = v_aff[g];
    const bool has_affs = asig >= 0;
    bool is_member_a = has_affs && member_v_g[asig];
    bool has_anti = false;
    for (int32_t v = 0; v < V; ++v)
      if (owner_v_g[v] && v_kind[v] == 1) has_anti = true;

    while (remaining > 0) {
      // ---- allowed domain set A (group's axis columns) -----------------
      int32_t m1 = BIG;
      const int32_t* cnt_p =
          has_tsc ? v_count.data() + static_cast<size_t>(psig) * DD + D0 : nullptr;
      if (has_tsc)
        for (int32_t d = 0; d < ND; ++d)
          if (g_dom[d]) m1 = std::min(m1, cnt_p[d]);
      bool any_present = false;
      const int32_t* cnt_a =
          has_affs ? v_count.data() + static_cast<size_t>(asig) * DD + D0 : nullptr;
      if (has_affs)
        for (int32_t d = 0; d < ND; ++d)
          if (cnt_a[d] > 0) any_present = true;
      for (int32_t d = 0; d < ND; ++d) {
        bool a = g_dom[d];
        if (a && has_tsc) a = (cnt_p[d] + 1 - m1 <= cap_p);
        if (a)
          for (int32_t v = 0; v < V && a; ++v) {
            if (v_kind[v] != 1) continue;
            if (owner_v_g[v] && v_count[static_cast<size_t>(v) * DD + D0 + d] > 0)
              a = false;
            if (member_v_g[v] && v_owner_z[static_cast<size_t>(v) * DD + D0 + d])
              a = false;
          }
        A_base[d] = a ? 1 : 0;
        if (has_affs) {
          if (any_present) a = a && (cnt_a[d] > 0);
          else if (!is_member_a) a = false;  // bootstrap only for members
        }
        A[d] = a ? 1 : 0;
      }

      bool placed = false;

      // ---- 1. existing nodes, in order ---------------------------------
      for (int32_t e = 0; e < E && !placed; ++e) {
        if (!node_compat[static_cast<size_t>(g) * E + e]) continue;
        const int32_t dn = node_dom(e);
        const bool nz_ok = (dn >= 0) ? (A[dn] != 0) : !has_owned;
        if (!nz_ok) continue;
        if (fit_count_row(node_free + static_cast<size_t>(e) * R,
                          e_cum.data() + static_cast<size_t>(e) * R, req, R) < 1)
          continue;
        if (hostname_allow(e_cm.data() + static_cast<size_t>(e) * Q,
                           e_co.data() + static_cast<size_t>(e) * Q,
                           q_kind, q_cap, member_q, owner_q, Q) < 1)
          continue;
        // place one pod on node e
        take_e[static_cast<size_t>(s) * E + e] += 1;
        for (int32_t r = 0; r < R; ++r)
          e_cum[static_cast<size_t>(e) * R + r] += req[r];
        for (int32_t q = 0; q < Q; ++q) {
          if (member_q[q]) e_cm[static_cast<size_t>(e) * Q + q] += 1;
          if (owner_q[q] && q_kind[q] == 1) e_co[static_cast<size_t>(e) * Q + q] += 1;
        }
        record_target(member_v_g, owner_v_g, node_zone[e],
                      mixed ? node_ct[e] : -1, 1);
        placed = true;
      }

      // ---- 2. open claims, in order -------------------------------------
      for (int32_t m = 0; m < used && !placed; ++m) {
        const int32_t p = c_pool[m];
        if (p < 0 || !group_pool[static_cast<size_t>(g) * P + p]) continue;
        bool pair_ok = true;
        for (int32_t g2 = 0; g2 < G && pair_ok; ++g2)
          if (c_gmask[static_cast<size_t>(m) * G + g2] &&
              !group_pair[static_cast<size_t>(g) * G + g2])
            pair_ok = false;
        if (!pair_ok) continue;
        // claim-local anti checks
        bool anti_ok = true;
        for (int32_t v = 0; v < V && anti_ok; ++v) {
          if (v_kind[v] != 1) continue;
          if (owner_v_g[v] && c_vm[static_cast<size_t>(m) * V + v] > 0) anti_ok = false;
          if (member_v_g[v] && c_vo[static_cast<size_t>(m) * V + v]) anti_ok = false;
        }
        if (!anti_ok) continue;
        if (hostname_allow(c_cm.data() + static_cast<size_t>(m) * Q,
                           c_co.data() + static_cast<size_t>(m) * Q,
                           q_kind, q_cap, member_q, owner_q, Q) < 1)
          continue;
        // effective allowed set for this claim: a co-located matching pod
        // satisfies the positive term (local_aff -> pre-affinity set)
        const bool local_aff =
            has_affs && c_vm[static_cast<size_t>(m) * V + asig] > 0;
        const uint8_t* Am = local_aff ? A_base.data() : A.data();
        int32_t n_inter = 0;
        for (int32_t d = 0; d < ND; ++d) {
          inter[d] = (c_dom(m, d) && Am[d] && g_dom[d]) ? 1 : 0;
          n_inter += inter[d];
        }
        if (n_inter == 0) continue;
        // commit rule (SPEC.md joint narrowing)
        const bool commit =
            has_tsc || (has_affs && any_present && !local_aff) || has_anti;
        int32_t d_star = -1;
        if (commit) {
          int32_t best = BIG + 1;
          for (int32_t d = 0; d < ND; ++d) {
            if (!inter[d]) continue;
            int32_t score;
            if (has_tsc) score = cnt_p[d] * 64 + d;
            else if (has_affs && any_present && !local_aff) score = -cnt_a[d] * 64 + d;
            else score = d;
            if (score < best) { best = score; d_star = d; }
          }
        }
        // surviving types under the effective domain bits: the group's
        // axis restricts to the committed/allowed columns, the OTHER axis
        // keeps the claim's bits ∧ the group's admission
        int32_t kmax = 0;
        for (int32_t t = 0; t < T; ++t) {
          fit_t[t] = 0;
          if (!c_mask[static_cast<size_t>(m) * T + t]) continue;
          if (!group_compat_t[static_cast<size_t>(g) * T + t]) continue;
          bool off_ok = false;
          for (int32_t z = 0; z < Z && !off_ok; ++z) {
            if (!(c_zone[static_cast<size_t>(m) * Z + z] && gz[z])) continue;
            if (ax == 0 && !(commit ? (z == d_star) : (inter[z] != 0))) continue;
            for (int32_t c = 0; c < C; ++c) {
              if (!(c_ct[static_cast<size_t>(m) * C + c] && gc[c])) continue;
              if (ax == 1 && !(commit ? (c == d_star) : (inter[c] != 0))) continue;
              if (offer_avail[(static_cast<size_t>(t) * Z + z) * C + c]) {
                off_ok = true;
                break;
              }
            }
          }
          if (!off_ok) continue;
          int32_t kt = fit_count_row(type_alloc + static_cast<size_t>(t) * R,
                                     c_cum + static_cast<size_t>(m) * R, req, R);
          if (kt < 1) continue;
          k_t[t] = kt;
          fit_t[t] = 1;
          kmax = std::max(kmax, kt);
        }
        if (kmax < 1) continue;
        // place one pod on claim m
        take_c[static_cast<size_t>(s) * M + m] += 1;
        for (int32_t r = 0; r < R; ++r)
          c_cum[static_cast<size_t>(m) * R + r] += req[r];
        for (int32_t t = 0; t < T; ++t)
          c_mask[static_cast<size_t>(m) * T + t] = (fit_t[t] && k_t[t] >= 1) ? 1 : 0;
        if (ax == 0) {
          for (int32_t z = 0; z < Z; ++z)
            c_zone[static_cast<size_t>(m) * Z + z] =
                (commit ? (z == d_star) : (inter[z] != 0)) ? 1 : 0;
          for (int32_t c = 0; c < C; ++c)
            c_ct[static_cast<size_t>(m) * C + c] &= gc[c];
        } else {
          for (int32_t c = 0; c < C; ++c)
            c_ct[static_cast<size_t>(m) * C + c] =
                (commit ? (c == d_star) : (inter[c] != 0)) ? 1 : 0;
          for (int32_t z = 0; z < Z; ++z)
            c_zone[static_cast<size_t>(m) * Z + z] &= gz[z];
        }
        c_gmask[static_cast<size_t>(m) * G + g] = 1;
        for (int32_t q = 0; q < Q; ++q) {
          if (member_q[q]) c_cm[static_cast<size_t>(m) * Q + q] += 1;
          if (owner_q[q] && q_kind[q] == 1) c_co[static_cast<size_t>(m) * Q + q] += 1;
        }
        for (int32_t v = 0; v < V; ++v) {
          if (member_v_g[v]) c_vm[static_cast<size_t>(m) * V + v] += 1;
          if (owner_v_g[v] && v_kind[v] == 1) c_vo[static_cast<size_t>(m) * V + v] = 1;
        }
        // domain-count recording: per-axis determined columns (SPEC.md)
        record_claim(m, 1);
        placed = true;
      }

      // ---- 3. new claim, pool by pool ------------------------------------
      for (int32_t p = 0; p < P && !placed; ++p) {
        if (!group_pool[static_cast<size_t>(g) * P + p]) continue;
        bool over = false;
        for (int32_t r = 0; r < R; ++r)
          if (p_usage[static_cast<size_t>(p) * R + r] >= pool_limit[static_cast<size_t>(p) * R + r])
            over = true;
        if (over) continue;
        if (fresh_allow < 1) continue;
        if (used >= M) { overflow = true; break; }
        const int32_t* daemon = pool_daemon + static_cast<size_t>(p) * R;
        // pool's admissible domains intersect A; commit like open claims
        int32_t n_inter = 0;
        for (int32_t d = 0; d < ND; ++d) {
          inter[d] = (pool_dom(p, d) && g_dom[d] && A[d]) ? 1 : 0;
          n_inter += inter[d];
        }
        if (n_inter == 0) continue;
        const bool commit = has_tsc || (has_affs && any_present) || has_anti;
        int32_t d_star = -1;
        if (commit) {
          int32_t best = BIG + 1;
          for (int32_t d = 0; d < ND; ++d) {
            if (!inter[d]) continue;
            int32_t score;
            if (has_tsc) score = cnt_p[d] * 64 + d;
            else if (has_affs && any_present) score = -cnt_a[d] * 64 + d;
            else score = d;
            if (score < best) { best = score; d_star = d; }
          }
        }
        int32_t kmax = 0;
        for (int32_t t = 0; t < T; ++t) {
          fit_t[t] = 0;
          if (!group_compat_t[static_cast<size_t>(g) * T + t]) continue;
          if (!pool_type[static_cast<size_t>(p) * T + t]) continue;
          bool off_ok = false;
          for (int32_t z = 0; z < Z && !off_ok; ++z) {
            if (!(pool_zone[static_cast<size_t>(p) * Z + z] && gz[z])) continue;
            if (ax == 0 && !(commit ? (z == d_star) : (inter[z] != 0))) continue;
            for (int32_t c = 0; c < C; ++c) {
              if (!(pool_ct[static_cast<size_t>(p) * C + c] && gc[c])) continue;
              if (ax == 1 && !(commit ? (c == d_star) : (inter[c] != 0))) continue;
              if (offer_avail[(static_cast<size_t>(t) * Z + z) * C + c]) {
                off_ok = true;
                break;
              }
            }
          }
          if (!off_ok) continue;
          int32_t k = BIG;
          for (int32_t r = 0; r < R; ++r)
            if (req[r] > 0) {
              int32_t rem = type_alloc[static_cast<size_t>(t) * R + r] - daemon[r];
              k = std::min(k, rem >= 0 ? rem / req[r] : -1);
            }
          if (k < 1) continue;
          k_t[t] = k;
          fit_t[t] = 1;
          kmax = std::max(kmax, k);
        }
        if (kmax < 1) continue;
        const int32_t m = used++;
        take_c[static_cast<size_t>(s) * M + m] += 1;
        c_pool[m] = p;
        for (int32_t r = 0; r < R; ++r)
          c_cum[static_cast<size_t>(m) * R + r] = daemon[r] + req[r];
        for (int32_t t = 0; t < T; ++t)
          c_mask[static_cast<size_t>(m) * T + t] = fit_t[t];
        if (ax == 0) {
          for (int32_t z = 0; z < Z; ++z)
            c_zone[static_cast<size_t>(m) * Z + z] =
                (commit ? (z == d_star) : (inter[z] != 0)) ? 1 : 0;
          for (int32_t c = 0; c < C; ++c)
            c_ct[static_cast<size_t>(m) * C + c] =
                pool_ct[static_cast<size_t>(p) * C + c] && gc[c];
        } else {
          for (int32_t c = 0; c < C; ++c)
            c_ct[static_cast<size_t>(m) * C + c] =
                (commit ? (c == d_star) : (inter[c] != 0)) ? 1 : 0;
          for (int32_t z = 0; z < Z; ++z)
            c_zone[static_cast<size_t>(m) * Z + z] =
                pool_zone[static_cast<size_t>(p) * Z + z] && gz[z];
        }
        c_gmask[static_cast<size_t>(m) * G + g] = 1;
        for (int32_t q = 0; q < Q; ++q) {
          if (member_q[q]) c_cm[static_cast<size_t>(m) * Q + q] = 1;
          if (owner_q[q] && q_kind[q] == 1) c_co[static_cast<size_t>(m) * Q + q] = 1;
        }
        for (int32_t v = 0; v < V; ++v) {
          if (member_v_g[v]) c_vm[static_cast<size_t>(m) * V + v] = 1;
          if (owner_v_g[v] && v_kind[v] == 1) c_vo[static_cast<size_t>(m) * V + v] = 1;
        }
        for (int32_t r = 0; r < R; ++r) {
          int32_t mn = BIG;
          for (int32_t t = 0; t < T; ++t)
            if (fit_t[t] && k_t[t] >= 1)
              mn = std::min(mn, type_charge[static_cast<size_t>(t) * R + r]);
          p_usage[static_cast<size_t>(p) * R + r] += (mn == BIG) ? 0 : mn;
        }
        record_claim(m, 1);
        placed = true;
      }

      if (overflow) break;
      if (!placed) break;  // this pod (and its identical peers) can't place
      remaining -= 1;
    }
    leftover[s] = remaining;
    if (overflow) break;
  }
  *used_out = used;
  return overflow ? 1 : 0;
}

}  // extern "C"
