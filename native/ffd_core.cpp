// Native FFD solver core.
//
// The compiled host-side implementation of solver/SPEC.md's FFD semantics
// over the SAME encoded int32 tensors the TPU kernel consumes
// (karpenter_tpu/solver/encode.py). Role in the framework:
//
//   * the fast CPU fallback when the device is unavailable or the input is
//     below the device-dispatch threshold — matching the compiled-language
//     performance class of the reference's Go scheduler rather than the
//     Python oracle's;
//   * a third leg for differential testing (python-oracle == C++ == TPU).
//
// Pure C ABI (ctypes-loaded, no pybind11 in this image). Single-threaded by
// design: one solve is inherently sequential; parallelism lives above
// (batched candidate simulation) and below (vectorized device kernel).
//
// Algorithm: identical to solver/tpu/ffd.py — runs of identical pods pour
// first-fit over existing nodes, then open claims, then closed-form new-node
// opening per pool in priority order with limit accounting. Arrays are
// row-major int32/uint8 exactly as encode.py lays them out (unpadded).

#include <cstdint>
#include <cstring>
#include <algorithm>
#include <vector>

namespace {

constexpr int32_t BIG = 1 << 30;

struct Dims {
  int32_t S, G, T, E, P, R, Z, C, M;
};

inline int32_t fit_count_row(const int32_t* alloc, const int32_t* cum,
                             const int32_t* req, int32_t R) {
  int32_t k = BIG;
  for (int32_t r = 0; r < R; ++r) {
    if (req[r] > 0) {
      int32_t rem = alloc[r] - cum[r];
      int32_t kr = rem >= 0 ? rem / req[r] : -1;
      k = std::min(k, kr);
    }
  }
  return std::max(k, 0);
}

}  // namespace

extern "C" {

// Returns 0 on success, 1 on claim-slot overflow.
// Outputs: take_e [S,E], take_c [S,M], leftover [S], c_mask [M,T] u8,
//          c_zone [M,Z] u8, c_ct [M,C] u8, c_gmask [M,G] u8, c_pool [M],
//          c_cum [M,R], used [1].
int ffd_solve_native(
    // dims
    int32_t S, int32_t G, int32_t T, int32_t E, int32_t P, int32_t R,
    int32_t Z, int32_t C, int32_t M,
    // runs
    const int32_t* run_group, const int32_t* run_count,
    // groups
    const int32_t* group_req,       // [G,R]
    const uint8_t* group_compat_t,  // [G,T]
    const uint8_t* group_zone,      // [G,Z]
    const uint8_t* group_ct,        // [G,C]
    const uint8_t* group_pool,      // [G,P]
    const uint8_t* group_pair,      // [G,G]
    const uint8_t* group_device,    // [G] (1 = handle here)
    // types
    const int32_t* type_alloc,      // [T,R]
    const int32_t* type_charge,     // [T,R]
    const uint8_t* offer_avail,     // [T,Z,C]
    // pools
    const uint8_t* pool_type,       // [P,T]
    const uint8_t* pool_zone,       // [P,Z]
    const uint8_t* pool_ct,         // [P,C]
    const int32_t* pool_daemon,     // [P,R]
    const int32_t* pool_limit,      // [P,R]
    const int32_t* pool_usage0,     // [P,R]
    // existing nodes
    const int32_t* node_free,       // [E,R]
    const uint8_t* node_compat,     // [G,E]
    // outputs
    int32_t* take_e, int32_t* take_c, int32_t* leftover,
    uint8_t* c_mask, uint8_t* c_zone, uint8_t* c_ct, uint8_t* c_gmask,
    int32_t* c_pool, int32_t* c_cum, int32_t* used_out) {
  std::vector<int32_t> e_cum(static_cast<size_t>(E) * R, 0);
  std::vector<int32_t> p_usage(pool_usage0, pool_usage0 + static_cast<size_t>(P) * R);
  std::memset(take_e, 0, sizeof(int32_t) * S * E);
  std::memset(take_c, 0, sizeof(int32_t) * S * M);
  std::memset(leftover, 0, sizeof(int32_t) * S);
  std::memset(c_mask, 0, static_cast<size_t>(M) * T);
  std::memset(c_zone, 0, static_cast<size_t>(M) * Z);
  std::memset(c_ct, 0, static_cast<size_t>(M) * C);
  std::memset(c_gmask, 0, static_cast<size_t>(M) * G);
  std::memset(c_cum, 0, sizeof(int32_t) * M * R);
  for (int32_t m = 0; m < M; ++m) c_pool[m] = -1;
  int32_t used = 0;
  bool overflow = false;

  std::vector<int32_t> k_t(T);          // per-type capacity scratch
  std::vector<uint8_t> fit_t(T);

  for (int32_t s = 0; s < S; ++s) {
    const int32_t g = run_group[s];
    int32_t remaining = group_device[g] ? run_count[s] : 0;
    const int32_t* req = group_req + static_cast<size_t>(g) * R;
    const uint8_t* gz = group_zone + static_cast<size_t>(g) * Z;
    const uint8_t* gc = group_ct + static_cast<size_t>(g) * C;

    // ---- 1. existing nodes ----------------------------------------------
    for (int32_t e = 0; e < E && remaining > 0; ++e) {
      if (!node_compat[static_cast<size_t>(g) * E + e]) continue;
      int32_t cap = fit_count_row(node_free + static_cast<size_t>(e) * R,
                                  e_cum.data() + static_cast<size_t>(e) * R, req, R);
      int32_t take = std::min(cap, remaining);
      if (take > 0) {
        take_e[static_cast<size_t>(s) * E + e] = take;
        for (int32_t r = 0; r < R; ++r)
          e_cum[static_cast<size_t>(e) * R + r] += take * req[r];
        remaining -= take;
      }
    }

    // ---- 2. open claims --------------------------------------------------
    for (int32_t m = 0; m < used && remaining > 0; ++m) {
      const int32_t p = c_pool[m];
      if (p < 0 || !group_pool[static_cast<size_t>(g) * P + p]) continue;
      // pairwise compat with everything already on the node
      bool pair_ok = true;
      for (int32_t g2 = 0; g2 < G && pair_ok; ++g2)
        if (c_gmask[static_cast<size_t>(m) * G + g2] &&
            !group_pair[static_cast<size_t>(g) * G + g2])
          pair_ok = false;
      if (!pair_ok) continue;
      // per-type fit under node+group zone/ct masks with joint (z,c) check
      int32_t cap = 0;
      for (int32_t t = 0; t < T; ++t) {
        fit_t[t] = 0;
        if (!c_mask[static_cast<size_t>(m) * T + t]) continue;
        if (!group_compat_t[static_cast<size_t>(g) * T + t]) continue;
        bool off_ok = false;
        for (int32_t z = 0; z < Z && !off_ok; ++z) {
          if (!(c_zone[static_cast<size_t>(m) * Z + z] && gz[z])) continue;
          for (int32_t c = 0; c < C; ++c) {
            if (c_ct[static_cast<size_t>(m) * C + c] && gc[c] &&
                offer_avail[(static_cast<size_t>(t) * Z + z) * C + c]) {
              off_ok = true;
              break;
            }
          }
        }
        if (!off_ok) continue;
        int32_t kt = fit_count_row(type_alloc + static_cast<size_t>(t) * R,
                                   c_cum + static_cast<size_t>(m) * R, req, R);
        k_t[t] = kt;
        fit_t[t] = 1;
        cap = std::max(cap, kt);
      }
      int32_t take = std::min(cap, remaining);
      if (take > 0) {
        take_c[static_cast<size_t>(s) * M + m] = take;
        for (int32_t r = 0; r < R; ++r)
          c_cum[static_cast<size_t>(m) * R + r] += take * req[r];
        for (int32_t t = 0; t < T; ++t)
          c_mask[static_cast<size_t>(m) * T + t] =
              (fit_t[t] && k_t[t] >= take) ? 1 : 0;
        for (int32_t z = 0; z < Z; ++z)
          c_zone[static_cast<size_t>(m) * Z + z] &= gz[z];
        for (int32_t c = 0; c < C; ++c)
          c_ct[static_cast<size_t>(m) * C + c] &= gc[c];
        c_gmask[static_cast<size_t>(m) * G + g] = 1;
        remaining -= take;
      }
    }

    // ---- 3. new claims, pool by pool ------------------------------------
    for (int32_t p = 0; p < P && remaining > 0; ++p) {
      if (!group_pool[static_cast<size_t>(g) * P + p]) continue;
      // limit gate: blocked if any resource already at/over limit
      bool over = false;
      for (int32_t r = 0; r < R; ++r)
        if (p_usage[static_cast<size_t>(p) * R + r] >= pool_limit[static_cast<size_t>(p) * R + r])
          over = true;
      if (over) continue;
      const int32_t* daemon = pool_daemon + static_cast<size_t>(p) * R;
      int32_t kmax = 0;
      for (int32_t t = 0; t < T; ++t) {
        fit_t[t] = 0;
        if (!group_compat_t[static_cast<size_t>(g) * T + t]) continue;
        if (!pool_type[static_cast<size_t>(p) * T + t]) continue;
        bool off_ok = false;
        for (int32_t z = 0; z < Z && !off_ok; ++z) {
          if (!(pool_zone[static_cast<size_t>(p) * Z + z] && gz[z])) continue;
          for (int32_t c = 0; c < C; ++c)
            if (pool_ct[static_cast<size_t>(p) * C + c] && gc[c] &&
                offer_avail[(static_cast<size_t>(t) * Z + z) * C + c]) {
              off_ok = true;
              break;
            }
        }
        if (!off_ok) continue;
        int32_t k = BIG;
        for (int32_t r = 0; r < R; ++r)
          if (req[r] > 0) {
            int32_t rem = type_alloc[static_cast<size_t>(t) * R + r] - daemon[r];
            k = std::min(k, rem >= 0 ? rem / req[r] : -1);
          }
        k = std::max(k, 0);
        k_t[t] = k;
        fit_t[t] = 1;
        kmax = std::max(kmax, k);
      }
      if (kmax <= 0) continue;

      // per-claim charge for limit accounting: min charge among the
      // at-creation surviving set (after the claim's FIRST pod) — the oracle
      // charges right after the opening pod lands
      std::vector<int32_t> charge_one(R, 0);
      for (int32_t r = 0; r < R; ++r) {
        int32_t mn = BIG;
        for (int32_t t = 0; t < T; ++t)
          if (fit_t[t] && k_t[t] >= 1)
            mn = std::min(mn, type_charge[static_cast<size_t>(t) * R + r]);
        charge_one[r] = (mn == BIG) ? 0 : mn;
      }

      while (remaining > 0) {
        // limit check before EACH claim creation
        bool blocked = false;
        for (int32_t r = 0; r < R; ++r)
          if (p_usage[static_cast<size_t>(p) * R + r] >=
              pool_limit[static_cast<size_t>(p) * R + r])
            blocked = true;
        if (blocked) break;
        if (used >= M) {
          overflow = true;
          break;
        }
        const int32_t m = used++;
        const int32_t take = std::min(kmax, remaining);
        take_c[static_cast<size_t>(s) * M + m] = take;
        c_pool[m] = p;
        for (int32_t r = 0; r < R; ++r)
          c_cum[static_cast<size_t>(m) * R + r] = daemon[r] + take * req[r];
        for (int32_t t = 0; t < T; ++t)
          c_mask[static_cast<size_t>(m) * T + t] = (fit_t[t] && k_t[t] >= take) ? 1 : 0;
        for (int32_t z = 0; z < Z; ++z)
          c_zone[static_cast<size_t>(m) * Z + z] =
              pool_zone[static_cast<size_t>(p) * Z + z] && gz[z];
        for (int32_t c = 0; c < C; ++c)
          c_ct[static_cast<size_t>(m) * C + c] =
              pool_ct[static_cast<size_t>(p) * C + c] && gc[c];
        c_gmask[static_cast<size_t>(m) * G + g] = 1;
        // charge: every claim charges its at-creation (1-pod survivor) min
        for (int32_t r = 0; r < R; ++r)
          p_usage[static_cast<size_t>(p) * R + r] += charge_one[r];
        remaining -= take;
      }
      if (overflow) break;
    }
    leftover[s] = remaining;
    if (overflow) break;
  }
  *used_out = used;
  return overflow ? 1 : 0;
}

}  // extern "C"
