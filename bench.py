"""Headline benchmark: Solve() at 50k pending pods x ~700 instance types.

BASELINE.md target: p99 < 100 ms on one TPU v5e chip (the reference publishes
no numbers; 100 ms is the north-star bound from BASELINE.json, and the
qualitative bar is "retry in milliseconds", concepts/_index.md:89).

Prints ONE JSON line:
  {"metric": ..., "value": p99_ms, "unit": "ms", "vs_baseline": 100/p99}
(vs_baseline > 1 means better than the 100 ms target.)

Runs on the real chip (does NOT force cpu — the axon site hook's
"axon,cpu" platform order stands). Diagnostics go to stderr.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

import numpy as np

# ---------------------------------------------------------------------------
# Backend availability probe (round-4 lesson: the axon TPU tunnel can be down
# OR can HANG jax backend init indefinitely — BENCH_r04.json was an rc=1
# traceback because of it). Probe in a subprocess with a hard timeout, retry
# with backoff, and if the chip never appears emit a parseable JSON line with
# a backend_unavailable marker instead of hanging or crashing the driver.
# ---------------------------------------------------------------------------

_PROBE_SRC = (
    "import jax, sys; d = jax.devices()[0]; "
    "x = jax.numpy.ones((8, 8)); jax.block_until_ready(x @ x); "
    "print(d.platform + '/' + d.device_kind)"
)

_MESH_PROBE_SRC = "import jax; print(len(jax.devices()))"


def _run_probe(argv: list, timeout_s: float, env: dict = None):
    """Run a probe/suite subprocess with a HARD kill on timeout.

    subprocess.run(timeout=...) only SIGKILLs the direct child; a hung jax
    backend init spawns tunnel helper processes that inherit the pipe, so
    .run() then blocks forever draining stdout from the orphan (the round-4
    hang moved from the bench into the probe). Start the child in its own
    session and kill the WHOLE process group, so nothing the tunnel forked
    can outlive the timeout. Returns (rc, stdout, stderr); rc is None on
    timeout."""
    import signal

    proc = subprocess.Popen(
        argv, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        start_new_session=True, env=env,
    )
    try:
        out, err = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError, OSError):
            proc.kill()
        try:  # reap; the group is SIGKILLed so this cannot block long
            proc.communicate(timeout=10)
        except Exception:  # noqa: BLE001 — already killed; nothing to salvage
            pass
        return None, "", ""
    return proc.returncode, out or "", err or ""


def probe_backend(timeout_s: float = 150.0):
    """Returns 'platform/kind' if a usable accelerator answers within
    timeout_s, else None. Runs in a subprocess so a hung tunnel cannot hang
    the bench itself."""
    rc, out, err = _run_probe([sys.executable, "-c", _PROBE_SRC], timeout_s)
    if rc is None:
        print("[bench] backend probe timed out (tunnel hang); "
              "process group killed", file=sys.stderr)
        return None
    if rc != 0:
        tail = err.strip().splitlines()[-1:] or ["?"]
        print(f"[bench] backend probe failed: {tail[0]}", file=sys.stderr)
        return None
    return out.strip() or None


def probe_mesh_devices(timeout_s: float = 60.0) -> int:
    """Device COUNT the current env's jax would see — decides whether the
    sharded suite can run on real hardware or must fall back to the
    host-side virtual mesh. 0 on probe failure/timeout (same hard-kill
    semantics as probe_backend)."""
    rc, out, _err = _run_probe(
        [sys.executable, "-c", _MESH_PROBE_SRC], timeout_s
    )
    if rc != 0:
        return 0
    try:
        return int(out.strip())
    except ValueError:
        return 0


def wait_for_backend(attempts: int = None, timeout_s: float = None,
                     backoff_s: float = None):
    """Retry the probe with linear backoff. ~13 min worst case — long enough
    to ride out a tunnel blip, short enough not to eat the driver's budget.

    The schedule is env-tunable: KTPU_BENCH_PROBE_RETRIES (attempt count)
    and KTPU_BENCH_PROBE_BASE_S (linear-backoff base). The older names
    KTPU_BENCH_PROBE_ATTEMPTS / KTPU_BENCH_PROBE_BACKOFF_S remain as
    fallbacks so existing driver configs keep working."""
    attempts = attempts or int(
        os.environ.get("KTPU_BENCH_PROBE_RETRIES",
                       os.environ.get("KTPU_BENCH_PROBE_ATTEMPTS", "4")))
    timeout_s = timeout_s or float(os.environ.get("KTPU_BENCH_PROBE_TIMEOUT_S", "150"))
    backoff_s = backoff_s or float(
        os.environ.get("KTPU_BENCH_PROBE_BASE_S",
                       os.environ.get("KTPU_BENCH_PROBE_BACKOFF_S", "60")))
    last = None
    for i in range(attempts):
        plat = probe_backend(timeout_s)
        if plat and not plat.startswith("cpu"):
            return plat
        # cpu/* means the axon hook fell back to host (tunnel down-but-not-
        # hung) — the most common outage mode; keep retrying it too. Track
        # the FINAL attempt's state so main() reports how we actually ended.
        last = plat
        if i < attempts - 1:
            wait = backoff_s * (i + 1)
            print(f"[bench] got {plat!r}; retry {i + 1}/{attempts - 1} "
                  f"in {wait:.0f}s", file=sys.stderr)
            time.sleep(wait)
    return last


def build_input(num_pods: int = 50_000):
    from karpenter_tpu.api import wellknown as wk
    from karpenter_tpu.api.objects import ObjectMeta, Pod
    from karpenter_tpu.catalog.catalog import generate
    from karpenter_tpu.provisioning.scheduler import NodePoolSpec, SolverInput
    from karpenter_tpu.scheduling.requirements import IN, Requirement, Requirements
    from karpenter_tpu.utils.resources import Resources

    catalog = generate()
    pools = [
        NodePoolSpec(
            name="general",
            weight=10,
            requirements=Requirements.of(
                Requirement.create(wk.NODEPOOL_LABEL, IN, ["general"])
            ),
            taints=[],
            instance_types=catalog,
        ),
        NodePoolSpec(
            name="spot",
            weight=50,
            requirements=Requirements.of(
                Requirement.create(wk.NODEPOOL_LABEL, IN, ["spot"]),
                Requirement.create(wk.CAPACITY_TYPE_LABEL, IN, ["spot"]),
            ),
            taints=[],
            instance_types=catalog,
        ),
    ]
    # ~40 distinct pod specs (deployments), heterogeneous sizes + selectors —
    # the shape of a production pending-pod surge.
    sizes = [
        ("100m", "128Mi"), ("250m", "256Mi"), ("250m", "512Mi"), ("500m", "512Mi"),
        ("500m", "1Gi"), ("1", "1Gi"), ("1", "2Gi"), ("2", "2Gi"), ("2", "4Gi"),
        ("4", "8Gi"), ("500m", "2Gi"), ("1500m", "3Gi"), ("3", "6Gi"), ("8", "16Gi"),
    ]
    selectors = [
        {},
        {},
        {},
        {wk.ARCH_LABEL: "arm64"},
        {},
        {wk.CAPACITY_TYPE_LABEL: "on-demand"},
        {},
        {wk.ZONE_LABEL: "zone-1b"},
    ]
    pods = []
    spec_id = 0
    for i in range(num_pods):
        spec = spec_id % (len(sizes) * 3)
        cpu, mem = sizes[spec % len(sizes)]
        sel = selectors[spec % len(selectors)]
        pods.append(
            Pod(
                meta=ObjectMeta(name=f"p{i:06d}", uid=f"p{i:06d}"),
                requests=Resources.parse({"cpu": cpu, "memory": mem}),
                node_selector=dict(sel),
            )
        )
        if i % 1250 == 1249:
            spec_id += 1
    return SolverInput(
        pods=pods, nodes=[], nodepools=pools, zones=("zone-1a", "zone-1b", "zone-1c")
    )


def build_e2e_input(num_pods: int = 50_000, num_nodes: int = 200):
    """The end-to-end seam's input: same pod surge PLUS existing capacity
    (E > 0 exercises the existing-node pour path, VERDICT r1 'what's weak' #3)."""
    from karpenter_tpu.api import wellknown as wk
    from karpenter_tpu.provisioning.scheduler import ExistingNode
    from karpenter_tpu.utils.resources import Resources

    inp = build_input(num_pods)
    nodes = []
    for j in range(num_nodes):
        free = Resources.parse({"cpu": "8", "memory": "32Gi"})
        free["pods"] = 110
        nodes.append(
            ExistingNode(
                id=f"node-{j:04d}",
                labels={
                    wk.ZONE_LABEL: f"zone-1{'abc'[j % 3]}",
                    wk.CAPACITY_TYPE_LABEL: "on-demand",
                    wk.HOSTNAME_LABEL: f"node-{j:04d}",
                    wk.ARCH_LABEL: "amd64",
                    wk.OS_LABEL: "linux",
                },
                taints=[],
                free=free,
            )
        )
    inp.nodes = nodes
    return inp


def build_config3_input(num_pods: int = 50_000):
    """BASELINE config 3: topologySpreadConstraints across 3 AZs."""
    from karpenter_tpu.api import wellknown as wk
    from karpenter_tpu.api.objects import TopologySpreadConstraint

    inp = build_input(num_pods)
    for i, p in enumerate(inp.pods):
        app = f"app-{(i // 1250) % 40}"
        p.meta.labels["app"] = app
        p.topology_spread = [
            TopologySpreadConstraint(
                max_skew=1,
                topology_key=wk.ZONE_LABEL,
                label_selector={"app": app},
            )
        ]
        p.node_selector = {}  # pure spread config
    return inp


def build_config4_input(num_pods: int = 50_000):
    """BASELINE config 4: inter-pod affinity/anti-affinity. A third of the
    pods follow a leader label into one zone; a few anti singletons spread
    one-per-zone; the rest are plain."""
    from karpenter_tpu.api import wellknown as wk
    from karpenter_tpu.api.objects import PodAffinityTerm

    inp = build_input(num_pods)
    for i, p in enumerate(inp.pods):
        p.node_selector = {}
        if i % 3 == 0:
            p.meta.labels["svc"] = "web"
            p.affinity_terms = [
                PodAffinityTerm(
                    label_selector={"svc": "web"},
                    topology_key=wk.ZONE_LABEL,
                    anti=False,
                )
            ]
        elif i < 9:
            p.meta.labels["svc"] = f"lock-{i}"
            p.affinity_terms = [
                PodAffinityTerm(
                    label_selector={"svc": f"lock-{i}"},
                    topology_key=wk.ZONE_LABEL,
                    anti=True,
                )
            ]
    return inp


def build_config5_universe(n_nodes: int = 10_000, n_candidates: int = 2_000):
    """BASELINE config 5: multi-node consolidation at 10k nodes.

    Fleet: `n_candidates` underutilized nodes (one small pod each, the
    disruption candidates, cost-ordered first) + absorbers with exactly
    one pod worth of free capacity + fully-loaded nodes. The largest
    consolidatable prefix sits strictly inside [2, n_candidates] (absorber
    capacity + the <=1-replacement rule bound it), so the tiered prefix
    search has a real boundary to find."""
    from karpenter_tpu.api import wellknown as wk
    from karpenter_tpu.api.objects import ObjectMeta, Pod
    from karpenter_tpu.provisioning.scheduler import ExistingNode
    from karpenter_tpu.utils.resources import Resources

    inp = build_input(0)  # pools + catalog only
    n_absorbers = 1500
    nodes = []

    def mknode(j, kind, free_cpu, free_mem, pods_free):
        free = Resources.parse({"cpu": free_cpu, "memory": free_mem})
        free["pods"] = pods_free
        return ExistingNode(
            id=f"{kind}-{j:05d}",
            labels={
                wk.ZONE_LABEL: f"zone-1{'abc'[j % 3]}",
                wk.CAPACITY_TYPE_LABEL: "on-demand",
                wk.HOSTNAME_LABEL: f"{kind}-{j:05d}",
                wk.ARCH_LABEL: "amd64",
                wk.OS_LABEL: "linux",
            },
            taints=[],
            free=free,
        )

    candidate_pods = {}
    candidate_node = {}
    sizes = [("500m", "512Mi"), ("500m", "1Gi"), ("250m", "512Mi"), ("750m", "768Mi")]
    for j in range(n_candidates):
        nodes.append(mknode(j, "cand", "7", "30Gi", 100))
        cpu, mem = sizes[j % len(sizes)]
        candidate_pods[j] = [
            Pod(
                meta=ObjectMeta(name=f"cp{j:05d}", uid=f"cp{j:05d}"),
                requests=Resources.parse({"cpu": cpu, "memory": mem}),
            )
        ]
        candidate_node[j] = f"cand-{j:05d}"
    for j in range(n_absorbers):
        nodes.append(mknode(j, "abs", "800m", "1Gi", 1))
    for j in range(n_nodes - n_candidates - n_absorbers):
        free = Resources.parse({"cpu": "0", "memory": "0"})
        free["pods"] = 0
        nodes.append(
            ExistingNode(
                id=f"full-{j:05d}",
                labels={
                    wk.ZONE_LABEL: f"zone-1{'abc'[j % 3]}",
                    wk.CAPACITY_TYPE_LABEL: "on-demand",
                    wk.HOSTNAME_LABEL: f"full-{j:05d}",
                    wk.ARCH_LABEL: "amd64",
                    wk.OS_LABEL: "linux",
                },
                taints=[],
                free=free,
            )
        )
    inp.nodes = nodes
    return inp, candidate_pods, candidate_node


def _accept_consolidation(k, v, cand_price=1.0):
    """The controller's acceptance rule: feasible AND (no replacement, or the
    replacement is strictly cheaper than the k nodes it consolidates)."""
    if not v.ok:
        return False
    if v.has_replacement and (
        v.replacement_price is None or v.replacement_price >= k * cand_price
    ):
        return False
    return True


def _prefix_search(ev, prep, n_candidates, cand_price=1.0):
    """The controller's consolidation-prefix search, via the SAME shared loop
    the controller runs (batched.speculative_binary_search) with the same
    acceptance rule. Returns (k_best, dispatches, prefixes_evaluated,
    seq_probes) where seq_probes is the round-trip count a sequential binary
    search would have issued for the IDENTICAL decision (replayed host-side
    from the probed verdicts)."""
    from karpenter_tpu.disruption.batched import speculative_binary_search

    best, probed, dispatches = speculative_binary_search(
        lambda ks: ev.evaluate_prepared(prep, [list(range(kk)) for kk in ks]),
        2,
        n_candidates,
        lambda k, v: _accept_consolidation(k, v, cand_price),
    )
    # sequential replay over the same verdicts: every mid it consults was
    # probed (the speculative search replays the identical decisions), so
    # this counts the device round-trips batching collapsed
    lo, hi, seq_probes, seq_best = 2, n_candidates, 0, None
    while lo <= hi:
        mid = (lo + hi) // 2
        seq_probes += 1
        if _accept_consolidation(mid, probed[mid], cand_price):
            seq_best = mid
            lo = mid + 1
        else:
            hi = mid - 1
    assert seq_best == best, "speculative search diverged from sequential replay"
    return (best or 1), dispatches, len(probed), seq_probes


def bench_config5():
    import sys
    import time

    from karpenter_tpu.disruption.batched import BatchedConsolidationEvaluator
    from karpenter_tpu.solver.backend import TPUSolver

    n_nodes, n_candidates = 10_000, 2_000
    t0 = time.perf_counter()
    inp, cpods, cnode = build_config5_universe(n_nodes, n_candidates)
    build_s = time.perf_counter() - t0
    ev = BatchedConsolidationEvaluator(TPUSolver())
    t0 = time.perf_counter()
    prep = ev.prepare(inp, cpods, cnode)
    prep_s = time.perf_counter() - t0
    assert prep is not None, "config5 universe fell off the device path"

    t0 = time.perf_counter()
    k, disp, probed, seq = _prefix_search(ev, prep, n_candidates)
    first_s = time.perf_counter() - t0
    print(
        f"[bench] config5 build={build_s:.1f}s prepare={prep_s:.1f}s "
        f"first search={first_s:.1f}s -> prefix k={k} ({disp} dispatches, "
        f"{probed} prefixes probed; sequential would issue {seq})",
        file=sys.stderr,
    )
    assert k >= 100, f"expected a large consolidatable prefix, got {k}"
    # ISSUE 4 acceptance: the consolidation decision issues <=2 device
    # dispatches where a sequential binary search over the same interval
    # would have issued O(log n) >= 6 round-trips, with identical decisions
    # (the sequential replay inside _prefix_search asserts decision parity)
    assert disp <= 2, f"speculative search took {disp} dispatches"
    assert seq >= 6, f"sequential baseline only needed {seq} probes"

    times = []
    for _ in range(5):
        t0 = time.perf_counter()
        k2, _d, probed2, _s = _prefix_search(ev, prep, n_candidates)
        times.append((time.perf_counter() - t0) * 1000)
        assert k2 == k
    p50 = float(np.percentile(np.asarray(times), 50))
    cand_per_s = probed2 / (p50 / 1000.0)
    print(
        f"[bench] config5 10k-node multi-consolidation: search p50={p50:.0f}ms "
        f"({cand_per_s:.0f} full-fleet subset evals/s, prefix={k} nodes, "
        f"{disp} dispatches vs {seq} sequential)",
        file=sys.stderr,
    )
    return p50, cand_per_s, k, disp, seq


def build_mixed_input(num_pods: int = 50_000):
    """Mixed zone+ct domain constraints (round-5 device class): the bulk of
    the surge spreads across zones, a slice spreads across capacity types —
    previously this mix fell back whole-solve to the Python oracle (the
    'one ct pod poisons the solve' cliff); now it runs in ONE device
    dispatch with concatenated domain columns."""
    from karpenter_tpu.api import wellknown as wk
    from karpenter_tpu.api.objects import TopologySpreadConstraint

    inp = build_config3_input(num_pods)
    for i, p in enumerate(inp.pods):
        if i % 50 == 0:  # 2% of pods are ct-spread deployments
            app = f"ct-{(i // 1250) % 40}"
            p.meta.labels = {"tier": app}
            p.topology_spread = [
                TopologySpreadConstraint(
                    max_skew=1,
                    topology_key=wk.CAPACITY_TYPE_LABEL,
                    label_selector={"tier": app},
                )
            ]
    return inp


def bench_fallback_cliff(num_pods: int = 1_000):
    """Quantify the REMAINING oracle cliff (VERDICT r4 next #3): one pod
    genuinely constrained on both domain axes routes the whole solve to the
    Python oracle. Measured once at a bounded size. Round-5 oracle hot-path
    work (allocation-free offering/intersects checks, changed-key-only
    claim re-filtering) cut this ~70x — from ~50 ms/pod to ~2-3 ms/pod on
    topology shapes — so even the classes still off-device (two-axis pods,
    Respect-mode preferred node affinity / weighted antis, custom topology
    keys) degrade gently instead of falling off a cliff."""
    from karpenter_tpu.api import wellknown as wk
    from karpenter_tpu.api.objects import TopologySpreadConstraint
    from karpenter_tpu.solver.backend import TPUSolver

    inp = build_config3_input(num_pods)
    p = inp.pods[0]
    p.topology_spread = list(p.topology_spread) + [
        TopologySpreadConstraint(
            max_skew=1,
            topology_key=wk.CAPACITY_TYPE_LABEL,
            label_selector={"app": p.meta.labels["app"]},
        )
    ]
    solver = TPUSolver(max_claims=8192)
    t0 = time.perf_counter()
    res = solver.solve(inp)
    cliff_ms = (time.perf_counter() - t0) * 1000
    assert solver.stats["fallback_solves"] == 1, solver.stats
    print(
        f"[bench] fallback cliff ({num_pods} pods, 2-axis pod -> oracle): "
        f"{cliff_ms:.0f}ms — claims={len(res.claims)}",
        file=sys.stderr,
    )
    return cliff_ms


def build_s_stress_input(num_pods: int = 50_000, n_specs: int = 2_000):
    """Scan-axis stress: ~n_specs DISTINCT pod specs (runs), the kernel's
    only sequential axis. The headline configs collapse 50k pods to a few
    dozen runs; production surges are far more heterogeneous, so the
    headline number is only honest if S ≳ 1000 holds up too."""
    from karpenter_tpu.utils.resources import Resources

    inp = build_input(num_pods)
    per = max(1, num_pods // n_specs)
    for i, p in enumerate(inp.pods):
        k = i // per
        cpu_m = 100 + (k % 500) * 7
        mem_mi = 64 + (k // 500) * 128 + (k % 11) * 16
        p.requests = Resources.parse({"cpu": f"{cpu_m}m", "memory": f"{mem_mi}Mi"})
        p.node_selector = {}
    return inp


def _bench_config(tag, inp, iters=5):
    import sys
    import time

    from karpenter_tpu.solver.backend import TPUSolver

    solver = TPUSolver(max_claims=8192)
    t0 = time.perf_counter()
    res = solver.solve(inp)
    first = time.perf_counter() - t0
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        res = solver.solve(inp)
        times.append((time.perf_counter() - t0) * 1000)
    p50 = float(np.percentile(np.asarray(times), 50))
    print(
        f"[bench] {tag}: first={first:.1f}s p50={p50:.0f}ms — claims={len(res.claims)} "
        f"errors={len(res.errors)} device_solves={solver.stats['device_solves']}",
        file=sys.stderr,
    )
    assert solver.stats["device_solves"] > 0, f"{tag} fell back off-device"
    return p50


# metrics this process emitted (marker line or full record) — the
# --baseline compare mode gates these against a prior BENCH_rNN.json
EMITTED: dict = {}


def _emit_unavailable(reason: str, extra: dict = None) -> None:
    """One parseable JSON line the driver can record even with no chip
    (VERDICT r4 'next round' #1): rc=0, explicit marker, no traceback.
    `extra` merges host-measurable metrics (transfer accounting) into the
    marker line so a chipless run still reports them."""
    record = {
        "metric": "solve_p99_50k_pods_x_700_types",
        "value": -1,
        "unit": "ms",
        "vs_baseline": 0.0,
        "backend_unavailable": True,
        "reason": reason,
        **(extra or {}),
    }
    EMITTED.update(record)
    print(json.dumps(record))


def _host_only_metrics(num_pods: int = 2_000) -> dict:
    """Transfer-accounting numbers measured on the host backend. The arena/
    ledger semantics are platform-independent — an exact encode-cache hit
    uploads ZERO bytes whether the 'device' is a chip or the CPU — so a
    host-only run (JAX_PLATFORMS=cpu) still reports upload_bytes_per_solve
    and arena_hit_rate instead of dropping them with the latency metrics."""
    try:
        import dataclasses as _dc

        from karpenter_tpu.solver.backend import TPUSolver
        from karpenter_tpu.solver.encode import encode, quantize_input

        inp = build_input(num_pods)
        solver = TPUSolver(max_claims=1024)
        solver.solve(inp)  # cold: full packed upload into the arena
        solver.solve(inp)  # warm: exact encode-cache hit -> zero upload
        led = solver.ledger
        snap = led.snapshot()
        # steady-state host encode (pod-delta patches off the warm core
        # cache) — the per-tick host cost is a pure-CPU number, so a
        # chipless run reports it at full fidelity
        etimes = []
        for k in range(1, 4):
            sub = _dc.replace(inp, pods=inp.pods[: num_pods - 5 * k])
            t0 = time.perf_counter()
            encode(quantize_input(sub))
            etimes.append((time.perf_counter() - t0) * 1000)
        encode_ms = float(np.percentile(np.asarray(etimes), 50))
        print(
            f"[bench] host-only arena ({num_pods} pods): "
            f"upload_bytes_per_solve={led.upload_bytes_per_solve:.0f} "
            f"arena_hit_rate={led.arena_hit_rate:.2f} "
            f"encode_ms={encode_ms:.1f} "
            f"outcomes={snap['outcomes']}",
            file=sys.stderr,
        )
        return {
            "upload_bytes_per_solve": round(led.upload_bytes_per_solve, 1),
            "arena_hit_rate": round(led.arena_hit_rate, 3),
            "encode_ms": round(encode_ms, 2),
            "host_only_metrics": True,
        }
    except Exception as e:  # noqa: BLE001 — the marker line must still emit
        print(f"[bench] host-only arena metrics failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return {}


def _trace_stage_metrics(num_pods: int = 2_000) -> dict:
    """ISSUE 10 span-derived stage breakdown + tracing-cost guards.

    (a) Tracing OFF (the production default until --solver-tracing wires it)
        must be inert: 10k span() entries allocate NOTHING — the off path is
        one module-global read returning a shared null context — guarded
        with sys.getallocatedblocks, gc paused so collector churn can't
        alias the count.
    (b) The stage-breakdown keys (encode/upload/dispatch/fetch/decode/
        stitch splits) come from the solve's own span tree, not ad-hoc
        perf_counter pairs around call sites — one instrumentation source
        for bench, /debug/trace, and karpenter_solver_stage_seconds. The
        span-derived whole-solve duration must agree with a legacy
        perf_counter wall timer around the same solves within 10%.
    (c) trace_overhead_pct: per-span cost (measured attached, the expensive
        path) x spans-per-solve, relative to the solve wall — asserted
        < 2% so tracing stays affordable enough to leave on.
    """
    try:
        import gc
        from collections import defaultdict

        from karpenter_tpu.obs import trace as obstrace
        from karpenter_tpu.solver.backend import TPUSolver

        # -- (a) off-path inertness ----------------------------------------
        obstrace.configure(enabled=False)
        for _ in range(64):  # warm bytecode/inline caches out of the window
            with obstrace.span("bench.noop"):
                pass
        gc.collect()
        gc.disable()
        try:
            b0 = sys.getallocatedblocks()
            for _ in range(10_000):
                with obstrace.span("bench.noop"):
                    pass
            alloc_blocks = sys.getallocatedblocks() - b0
        finally:
            gc.enable()
        assert alloc_blocks < 50, (
            f"tracing-off span() allocated {alloc_blocks} blocks over 10k calls"
        )

        inp = build_input(num_pods)
        solver = TPUSolver(max_claims=1024)
        solver.solve(inp)  # cold: compile + arena upload off the window

        obstrace.configure(enabled=True, ring=64)
        try:
            # -- (b) span tree vs legacy wall timer, same solves -----------
            iters = 5
            legacy_ms = []
            for _ in range(iters):
                tr = obstrace.begin("bench")
                t0 = time.perf_counter()
                with obstrace.attached(tr):
                    solver.solve(inp)
                legacy_ms.append((time.perf_counter() - t0) * 1000)
                obstrace.finish(tr, "ok")
            stage_samples = defaultdict(list)
            solve_ms = []
            spans_per_solve = 0
            for tr in obstrace.recent(iters):
                snap = tr.snapshot()
                spans_per_solve = max(spans_per_solve, len(snap["spans"]))
                for sp in snap["spans"]:
                    if sp["t1"] is None:
                        continue
                    dur = (sp["t1"] - sp["t0"]) * 1000
                    if sp["name"] == "solve":
                        solve_ms.append(dur)
                    else:
                        stage_samples[sp["name"]].append(dur)
            legacy_p50 = float(np.percentile(np.asarray(legacy_ms), 50))
            span_p50 = float(np.percentile(np.asarray(solve_ms), 50))
            assert abs(span_p50 - legacy_p50) <= 0.10 * legacy_p50, (
                f"span-derived solve {span_p50:.2f}ms vs legacy timer "
                f"{legacy_p50:.2f}ms diverged > 10%"
            )
            stages = {
                f"stage_{name.split('.')[-1]}_ms": round(
                    float(np.percentile(np.asarray(v), 50)), 3
                )
                for name, v in sorted(stage_samples.items())
            }

            # -- (c) tracing overhead, analytic upper bound ----------------
            # per-span cost noise-free beats differencing two solve p50s
            # whose run-to-run jitter dwarfs a <2% effect
            tr = obstrace.begin("bench")
            with obstrace.attached(tr):
                t0 = time.perf_counter()
                for _ in range(5_000):
                    with obstrace.span("bench.tick"):
                        pass
                span_cost_ms = (time.perf_counter() - t0) / 5_000 * 1000
            obstrace.finish(tr, "ok")
            overhead_pct = 100.0 * spans_per_solve * span_cost_ms / legacy_p50
            assert overhead_pct < 2.0, (
                f"tracing overhead {overhead_pct:.2f}% >= 2% "
                f"({spans_per_solve} spans x {span_cost_ms * 1000:.1f}us "
                f"over a {legacy_p50:.1f}ms solve)"
            )
        finally:
            obstrace.configure(enabled=False)
        print(
            f"[bench] trace stages ({num_pods} pods): "
            + " ".join(f"{k[6:-3]}={v}ms" for k, v in stages.items())
            + f" | solve span={span_p50:.1f}ms legacy={legacy_p50:.1f}ms "
            f"overhead={overhead_pct:.3f}% off-path-allocs={alloc_blocks}",
            file=sys.stderr,
        )
        return {
            **stages,
            "solve_span_p50_ms": round(span_p50, 2),
            "trace_overhead_pct": round(overhead_pct, 4),
            "trace_spans_per_solve": spans_per_solve,
            "trace_off_alloc_blocks": int(alloc_blocks),
        }
    except Exception as e:  # noqa: BLE001 — the marker line must still emit
        print(f"[bench] trace stage metrics failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return {}


def _telemetry_metrics(num_pods: int = 2_000) -> dict:
    """ISSUE 14 runtime-health-plane cost guards.

    (a) Telemetry OFF must be inert like trace-off: the kernel hook's
        disabled path is one module-global read + tail call — 10k hook
        dispatches allocate NOTHING (sys.getallocatedblocks, gc paused).
    (b) telemetry_overhead_pct: the ON-path cost is one signature build +
        set lookup per kernel dispatch. Measured per-check on a
        36-array ARG_SPEC-arity call (the worst real arity), multiplied
        by the checks-per-solve a real warm solve performs, relative to
        the solve wall — asserted < 1% (analytic upper bound, same
        rationale as trace_overhead_pct: run-to-run jitter dwarfs it).
    """
    try:
        import gc

        from karpenter_tpu.obs import telemetry as obstelemetry
        from karpenter_tpu.solver.backend import TPUSolver

        def _probe(*args, **kwargs):
            return 0

        _probe.__wrapped__ = _probe
        hook = obstelemetry.instrument("bench_telemetry_probe", _probe)
        arg36 = tuple(np.zeros((4, 4), np.int32) for _ in range(36))

        # -- (a) off-path inertness ----------------------------------------
        obstelemetry.configure(enabled=False)
        gc.collect()
        gc.disable()
        try:
            # full-length warm pass AFTER the collect (which clears
            # freelists): a 38-slot call tuple + kwargs dict re-grows
            # allocator pools on the first window; the steady state is what
            # the guard is about (the second window measures 0 net blocks)
            for _ in range(10_000):
                hook(*arg36, max_claims=1024, zone_engine=False)
            b0 = sys.getallocatedblocks()
            for _ in range(10_000):
                hook(*arg36, max_claims=1024, zone_engine=False)
            alloc_blocks = sys.getallocatedblocks() - b0
        finally:
            gc.enable()
        assert alloc_blocks < 50, (
            f"telemetry-off hook allocated {alloc_blocks} blocks over 10k calls"
        )

        # -- (b) on-path overhead, analytic upper bound --------------------
        obstelemetry.configure(enabled=True)
        inp = build_input(num_pods)
        solver = TPUSolver(max_claims=1024)
        solver.solve(inp)  # cold: compile + upload off the window
        c0 = obstelemetry.stats["checks"]
        iters = 5
        legacy_ms = []
        for _ in range(iters):
            t0 = time.perf_counter()
            solver.solve(inp)
            legacy_ms.append((time.perf_counter() - t0) * 1000)
        checks_per_solve = max(
            1, -(-(obstelemetry.stats["checks"] - c0) // iters))  # ceil
        legacy_p50 = float(np.percentile(np.asarray(legacy_ms), 50))
        hook(*arg36, max_claims=1024, zone_engine=False)  # register the sig
        t0 = time.perf_counter()
        for _ in range(5_000):
            hook(*arg36, max_claims=1024, zone_engine=False)
        check_cost_ms = (time.perf_counter() - t0) / 5_000 * 1000
        overhead_pct = 100.0 * checks_per_solve * check_cost_ms / legacy_p50
        assert overhead_pct < 1.0, (
            f"telemetry overhead {overhead_pct:.3f}% >= 1% "
            f"({checks_per_solve} checks x {check_cost_ms * 1000:.1f}us "
            f"over a {legacy_p50:.1f}ms solve)"
        )
        # wipe the probe kernel's signatures out of the compile counters
        obstelemetry.configure(enabled=True)
        print(
            f"[bench] telemetry ({num_pods} pods): "
            f"checks/solve={checks_per_solve} "
            f"check_cost={check_cost_ms * 1000:.1f}us "
            f"overhead={overhead_pct:.4f}% off-path-allocs={alloc_blocks}",
            file=sys.stderr,
        )
        return {
            "telemetry_overhead_pct": round(overhead_pct, 4),
            "telemetry_checks_per_solve": int(checks_per_solve),
            "telemetry_off_alloc_blocks": int(alloc_blocks),
        }
    except Exception as e:  # noqa: BLE001 — the marker line must still emit
        print(f"[bench] telemetry metrics failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return {}


def _host_only_pipeline_metrics(n_nodes: int = 400, n_candidates: int = 100) -> dict:
    """ISSUE-4 pipeline/probe metrics measured on the host backend. Dispatch
    counts, decision parity, and coalescing semantics are platform-
    independent — the speculative frontier issues the same <=2 dispatches
    whether the 'device' is a chip or the CPU — so a chipless run still
    proves the sequential-vs-batched collapse and reports the pipeline
    numbers (the ms value is a host number, flagged by the marker line)."""
    try:
        from karpenter_tpu.disruption.batched import BatchedConsolidationEvaluator
        from karpenter_tpu.solver.backend import TPUSolver
        from karpenter_tpu.solver.pipeline import (
            DISRUPTION,
            PROVISIONING,
            SolveService,
            Superseded,
        )

        inp, cpods, cnode = build_config5_universe(n_nodes, n_candidates)
        ev = BatchedConsolidationEvaluator(TPUSolver())
        prep = ev.prepare(inp, cpods, cnode)
        assert prep is not None, "config5 universe fell off the solver path"
        t0 = time.perf_counter()
        k, disp, _probed, _seq = _prefix_search(ev, prep, n_candidates)
        decision_ms = (time.perf_counter() - t0) * 1000
        # parity proof: the REAL sequential loop, one solver dispatch per
        # probe — must land on the same prefix while issuing >=6 round-trips
        # where the speculative search needed <=2 batched dispatches
        lo, hi, seq_best, seq_disp = 2, n_candidates, None, 0
        while lo <= hi:
            mid = (lo + hi) // 2
            v = ev.evaluate_prepared(prep, [list(range(mid))])[0]
            seq_disp += 1
            if _accept_consolidation(mid, v):
                seq_best = mid
                lo = mid + 1
            else:
                hi = mid - 1
        assert (seq_best or 1) == k, f"sequential {seq_best} != speculative {k}"
        assert disp <= 2, f"speculative search took {disp} dispatches"
        assert seq_disp >= 6, f"sequential baseline only needed {seq_disp}"

        # the production pipeline seam: a disruption-class run for sustained
        # occupancy, then a provisioning burst submitted behind it whose
        # stale snapshots coalesce (newer revision supersedes queued ones)
        svc = SolveService(TPUSolver(), depth=2)
        small = build_input(300)
        tickets = [svc.submit(small, kind=DISRUPTION) for _ in range(6)]
        pticks = [svc.submit(small, kind=PROVISIONING, rev=i) for i in range(4)]
        for t in tickets:
            t.result()
        for t in pticks:
            try:
                t.result()
            except Superseded:
                pass
        occ, coalesced = svc.occupancy(), svc.stats["coalesced"]
        svc.close()
        print(
            f"[bench] host-only pipeline: decision={decision_ms:.0f}ms "
            f"prefix k={k} dispatches={disp} (sequential: {seq_disp}) "
            f"occupancy={occ:.2f} coalesced={coalesced}",
            file=sys.stderr,
        )
        return {
            "consolidation_decision_ms": round(decision_ms, 2),
            "probe_dispatches_per_decision": disp,
            "sequential_probe_solves": seq_disp,
            "pipeline_occupancy": round(occ, 3),
            "coalesced_solves_total": coalesced,
        }
    except Exception as e:  # noqa: BLE001 — the marker line must still emit
        print(f"[bench] host-only pipeline metrics failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return {}


def _resume_metrics(num_pods: int = 250, n_specs: int = 32) -> dict:
    """Checkpointed-scan resume proof (ISSUE 5): a warm append-tail re-solve
    must execute strictly fewer scan steps than a cold solve of the same
    mutated fleet, with identical decisions. Runs-skipped accounting and
    decision identity are platform-independent, so this measures on whatever
    backend jax initialized (chip or host) and belongs to the host-only
    suite too.

    Fleet shape matters: the ring snapshots every ckpt_every scan steps
    across the PADDED run axis (padded steps are no-ops, so late slots
    saturate at full-scan coverage), which means (n_ckpt-1)*ckpt_every must
    exceed the padding for a mid-scan slot to survive — n_specs distinct
    sizes give ~n_specs runs and ckpt_every=8 leaves slots at 24 and 32 of
    the ~36 real runs. The mutation appends replicas of the SMALLEST spec,
    which is the LAST run in FFD's descending size order, so only the final
    run's count changes and the valid prefix is S-1 runs deep."""
    try:
        import copy as _copy
        import dataclasses as _dc

        from karpenter_tpu.solver.backend import TPUSolver

        inp = build_s_stress_input(num_pods, n_specs)
        clones = []
        for j in range(3):
            p = _copy.deepcopy(inp.pods[0])  # spec k=0: the smallest size
            p.meta.name = p.meta.uid = f"tail-{j}"
            clones.append(p)
        tail = _dc.replace(inp, pods=list(inp.pods) + clones)

        # cold baseline: resume off, same fleet + mutation, warm jit cache
        cold = TPUSolver(max_claims=1024, resume=False)
        cold.solve(inp)
        t0 = time.perf_counter()
        ref = cold.solve(tail)
        cold_ms = (time.perf_counter() - t0) * 1000

        # precompile the resume kernel for these bucket shapes (module-level
        # jit cache is shared across solver instances) so warm_solve_ms is a
        # steady-state number, not ffd_resume's first-call compile — in
        # production the AOT prewarm pays this at boot
        pre = TPUSolver(max_claims=1024, ckpt_every=8)
        pre.solve(inp)
        pre.solve(tail)

        # warm path: the first solve harvests the checkpoint ring; the
        # append-tail re-solve resumes from the deepest covering slot and
        # replays only the changed suffix
        warm = TPUSolver(max_claims=1024, ckpt_every=8)
        warm.solve(inp)
        t0 = time.perf_counter()
        res = warm.solve(tail)
        warm_ms = (time.perf_counter() - t0) * 1000
        skipped = int(warm.stats["resume_runs_skipped"])
        assert warm.stats["resume_solves"] == 1, warm.stats
        assert skipped > 0, "append-tail re-solve replayed the full scan"
        # decision identity: the resumed solve must place every pod exactly
        # where the cold solve did
        assert res.placements == ref.placements, "resume diverged from cold"
        assert [c.instance_type_names for c in res.claims] == [
            c.instance_type_names for c in ref.claims
        ], "resume chose different instance types"
        print(
            f"[bench] resume warm re-solve ({num_pods} pods, ~{n_specs} runs): "
            f"cold={cold_ms:.1f}ms warm={warm_ms:.1f}ms "
            f"runs_skipped={skipped} hit_rate={warm.resume_hit_rate:.2f}",
            file=sys.stderr,
        )
        return {
            "cold_solve_ms": round(cold_ms, 2),
            "warm_solve_ms": round(warm_ms, 2),
            "resume_hit_rate": round(warm.resume_hit_rate, 3),
            "resume_runs_skipped": skipped,
        }
    except Exception as e:  # noqa: BLE001 — the marker line must still emit
        print(f"[bench] resume metrics failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return {}


def _decode_relax_metrics(num_pods: int = 600, relax_pods: int = 120) -> dict:
    """On-device decode + relax-ladder proof (ISSUE 6). (a) Delta decode:
    same fleet solved with the packed claim-delta fetch vs the dense take
    tables must be decision-identical, and the ledger-measured d2h
    bytes/solve must shrink. (b) Relax ladder: a fleet of soft zone spreads
    over a pool pinned to one zone (every spread must relax) must complete
    in ONE kernel dispatch on the ladder path, decision-identical to the
    host redispatch loop. Both are decision/accounting checks, platform-
    independent — they run on whatever backend jax initialized and belong
    to the host-only suite so TPU-outage rounds (r04/r05) keep the signal."""
    try:
        from karpenter_tpu.api import wellknown as wk
        from karpenter_tpu.api.objects import TopologySpreadConstraint
        from karpenter_tpu.scheduling.requirements import (
            IN,
            Requirement,
            Requirements,
        )
        from karpenter_tpu.solver.backend import TPUSolver

        # -- (a) packed claim-delta vs dense take-table fetch --------------
        inp = build_input(num_pods)
        delta = TPUSolver(max_claims=1024)
        dense = TPUSolver(max_claims=1024, device_decode=False)
        r_delta = delta.solve(inp)
        r_dense = dense.solve(inp)
        assert r_delta.placements == r_dense.placements, "delta decode diverged"
        db = delta.ledger.decode_bytes_per_solve
        wb = dense.ledger.decode_bytes_per_solve
        shrink = (wb / db) if db else 0.0
        assert delta.stats["wide_refetches"] == 0, delta.stats

        # -- (b) relax ladder: one dispatch for a whole rung walk ----------
        rinp = build_input(relax_pods)
        for pl in rinp.nodepools:
            pl.requirements = pl.requirements.union(
                Requirements.of(Requirement.create(wk.ZONE_LABEL, IN, ["zone-1a"]))
            )
        for i, p in enumerate(rinp.pods):
            app = f"app-{i % 8}"
            p.meta.labels["app"] = app
            p.node_selector = {}
            p.topology_spread = [
                TopologySpreadConstraint(
                    max_skew=1, topology_key=wk.ZONE_LABEL,
                    label_selector={"app": app},
                    when_unsatisfiable="ScheduleAnyway",
                )
            ]
        lad = TPUSolver(max_claims=1024)
        host = TPUSolver(max_claims=1024, relax_ladder=False)
        t0 = time.perf_counter()
        r_lad = lad.solve(rinp)
        lad_ms = (time.perf_counter() - t0) * 1000
        t0 = time.perf_counter()
        r_host = host.solve(rinp)
        host_ms = (time.perf_counter() - t0) * 1000
        assert r_lad.placements == r_host.placements, "ladder diverged from host loop"
        assert lad.stats["ladder_solves"] >= 1, lad.stats
        assert lad.stats["relax_dispatches"] == 1, lad.stats
        print(
            f"[bench] decode/ladder: d2h {wb:.0f}B dense -> {db:.0f}B delta "
            f"({shrink:.1f}x); relax {relax_pods} soft spreads: "
            f"ladder {lad.stats['relax_dispatches']} dispatch {lad_ms:.1f}ms "
            f"vs host loop {host.stats['relax_dispatches']} dispatches "
            f"{host_ms:.1f}ms",
            file=sys.stderr,
        )
        return {
            "decode_bytes_per_solve": round(db, 1),
            "decode_shrink_x": round(shrink, 1),
            "relax_dispatches_per_solve": int(lad.stats["relax_dispatches"]),
            "ladder_rungs_used": int(lad.stats["ladder_rungs_used"]),
            "host_loop_dispatches": int(host.stats["relax_dispatches"]),
        }
    except Exception as e:  # noqa: BLE001 — the marker line must still emit
        print(f"[bench] decode/ladder metrics failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return {}


def _sharded_capacity_fleet(n: int):
    """Claim-SATURATING fleet for the weak-scaling measurement: every pod's
    cpu request exceeds half the largest catalog type (192), so no surviving
    instance type has room for a second pod — each claim is provably full
    the moment it opens. Block-boundary open claims then fit nothing, the
    stitch ACCEPTS every block (additive carry combine, no fix-up replay),
    and the run-axis partition actually scales. 16 distinct sizes keep the
    run axis wide enough to split across an 8-way mesh."""
    import dataclasses as _dc

    from karpenter_tpu.api.objects import ObjectMeta, Pod
    from karpenter_tpu.utils.resources import Resources

    base = build_input(1)
    pods = [
        Pod(
            meta=ObjectMeta(name=f"cap{i:05d}", uid=f"cap{i:05d}"),
            requests=Resources.parse(
                {"cpu": f"{128 + 2 * (i % 16)}", "memory": "2Gi"}
            ),
        )
        for i in range(n)
    ]
    return _dc.replace(base, pods=pods)


def bench_sharded_suite() -> None:
    """Child half of the mesh-sharded solve suite (ISSUE 7): runs in its own
    process (spawned by _sharded_metrics with the mesh env already chosen)
    and prints ONE JSON line tagged sharded_suite.

    Three measurements:
    - sharded_solve_p99_500k: TPUSolver(shards=8) over the headline fleet
      (500k pods on a real mesh; scaled down on the host virtual mesh, with
      sharded_pods recording the actual size).
    - weak_scaling_efficiency: t(1 device, N/8) / t(8-way mesh, N) on the
      claim-saturating fleet — the accept-path regime where blocks combine
      without replay. 1.0 is perfect weak scaling.
    - shard_upload_bytes_per_device: a steady-state pod-delta loop stales
      ONLY the run-axis tables, which are exactly the partitioned entries —
      so the per-device share of the packed delta is ~1/8 of what a
      replicated-args upload would ship every device."""
    import dataclasses as _dc

    import jax

    from karpenter_tpu.solver.backend import TPUSolver

    virtual = jax.devices()[0].platform == "cpu"
    num_pods = int(os.environ.get("KTPU_BENCH_SHARDED_PODS", "0")) or (
        12_000 if virtual else 500_000
    )
    # build_input grows one distinct spec per 1250 pods; below ~10k the run
    # axis is narrower than the mesh and the sharded path (correctly)
    # declines — keep the fleet wide enough to partition
    num_pods = max(num_pods, 10_000)
    n_dev = len(jax.devices())
    print(f"[bench] sharded suite: {n_dev} {jax.devices()[0].platform} "
          f"devices, {num_pods} pods", file=sys.stderr)

    # -- weak scaling + decision identity on the saturating fleet ----------
    # one claim per pod, so stay under the 512-slot initial claim bucket:
    # a larger fleet overflows M0 every solve and the doubling redispatch
    # (plus its replay upload) would muddy the steady-state windows below
    n8 = 496
    f1, f8 = _sharded_capacity_fleet(n8 // 8), _sharded_capacity_fleet(n8)
    base = TPUSolver(max_claims=8192)
    sh = TPUSolver(max_claims=8192, shards=8)
    ref, got = base.solve(f8), sh.solve(f8)
    assert got.placements == ref.placements, "sharded diverged from 1-device"
    assert sh.stats["sharded_solves"] >= 1, sh.stats
    assert sh.stats["sharded_fallbacks"] == 0, sh.stats
    base.solve(f1)

    def _p50(solver, inp, iters=3):
        ts = []
        for _ in range(iters):
            t0 = time.perf_counter()
            solver.solve(inp)
            ts.append((time.perf_counter() - t0) * 1000)
        return float(np.percentile(np.asarray(ts), 50))

    t1, t8 = _p50(base, f1), _p50(sh, f8)
    weak = t1 / t8 if t8 else 0.0
    print(f"[bench] weak scaling: 1-dev {n8 // 8} pods {t1:.1f}ms vs "
          f"8-way {n8} pods {t8:.1f}ms -> efficiency {weak:.2f} "
          f"(fixup_runs={sh.stats['shard_fixup_runs']})", file=sys.stderr)

    # -- per-device upload share, accept regime ----------------------------
    # A pod-delta mutation stales ONLY the run tables, which are exactly
    # the partitioned entries, so each device's share of the packed delta
    # is 1/Nd of what a replicated-args upload would ship it. Measured on
    # the saturating fleet (no fix-up replay, whose carry re-upload would
    # otherwise dominate the window) with resume off (a resume dispatch
    # ships a full init state, same pollution).
    sh_up = TPUSolver(max_claims=8192, shards=8, resume=False)
    sh_up.solve(f8)
    led, iters = sh_up.ledger, 4
    w0 = dict(led.total)
    for k in range(1, iters + 1):
        sh_up.solve(_dc.replace(f8, pods=f8.pods[: n8 - 5 * k]))
    assert sh_up.stats["sharded_solves"] == 1 + iters, sh_up.stats
    w1 = dict(led.total)
    d_bytes = w1["h2d_bytes"] - w0["h2d_bytes"]
    d_shard = w1["h2d_shard_bytes"] - w0["h2d_shard_bytes"]
    per_dev = ((d_bytes - d_shard) + d_shard / 8.0) / iters
    repl_baseline = d_bytes / iters
    ratio = per_dev / repl_baseline if repl_baseline else 0.0
    print(f"[bench] shard delta upload: {repl_baseline:.0f}B replicated -> "
          f"{per_dev:.0f}B/device ({ratio:.3f}x)", file=sys.stderr)

    # -- headline-scale sharded solve --------------------------------------
    inp = build_input(num_pods)
    sh2 = TPUSolver(max_claims=8192, shards=8)
    t0 = time.perf_counter()
    sh2.solve(inp)
    cold_s = time.perf_counter() - t0
    assert sh2.stats["sharded_solves"] >= 1, sh2.stats
    ts = []
    for _ in range(3):
        t0 = time.perf_counter()
        sh2.solve(inp)  # exact arena hit: steady-state dispatch+stitch
        ts.append((time.perf_counter() - t0) * 1000)
    p99 = float(np.percentile(np.asarray(ts), 99))
    print(f"[bench] sharded {num_pods} pods: cold={cold_s:.1f}s "
          f"p99={p99:.1f}ms fixup_runs={sh2.stats['shard_fixup_runs']}",
          file=sys.stderr)

    print(json.dumps({
        "sharded_suite": True,
        "sharded_solve_p99_500k": round(p99, 2),
        "sharded_pods": num_pods,
        "weak_scaling_efficiency": round(weak, 3),
        "shard_upload_bytes_per_device": round(per_dev, 1),
        "shard_upload_ratio_vs_replicated": round(ratio, 4),
        "sharded_mesh_devices": min(n_dev, 8),
        "shard_fixup_runs": int(sh2.stats["shard_fixup_runs"]),
        "sharded_virtual_mesh": virtual,
    }))


def _sharded_metrics(timeout_s: float = None) -> dict:
    """Parent half of the mesh-sharded suite: pick the mesh env, spawn the
    child, harvest its JSON line. A subprocess is mandatory, not defensive —
    jax fixes its device list at first backend init, so a process that
    already initialized one CPU device can never grow the 8-way virtual
    mesh. The device-count probe decides: >=2 real devices run the suite
    as-is; anything less (single chip, host-only round, dead tunnel) reruns
    on the host-side virtual mesh (--xla_force_host_platform_device_count=8)
    so the sharded/weak-scaling keys are real measurements, never -1
    sentinels. Same hard-kill-the-process-group semantics as the backend
    probe."""
    timeout_s = timeout_s or float(
        os.environ.get("KTPU_BENCH_SHARDED_TIMEOUT_S", "900"))
    try:
        env = dict(os.environ)
        n_dev = probe_mesh_devices()
        if n_dev < 2:
            env["JAX_PLATFORMS"] = "cpu"
            flags = env.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                env["XLA_FLAGS"] = (
                    flags + " --xla_force_host_platform_device_count=8"
                ).strip()
            print(f"[bench] sharded suite: {n_dev} device(s) visible -> "
                  "host-side virtual 8-way mesh", file=sys.stderr)
        rc, out, err = _run_probe(
            [sys.executable, os.path.abspath(__file__), "--sharded-suite"],
            timeout_s, env=env,
        )
        for line in err.strip().splitlines()[-12:]:
            print(line, file=sys.stderr)
        if rc is None:
            print("[bench] sharded suite timed out; process group killed",
                  file=sys.stderr)
            return {}
        for line in reversed(out.strip().splitlines()):
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.pop("sharded_suite", False):
                return rec
        print(f"[bench] sharded suite emitted no record (rc={rc})",
              file=sys.stderr)
        return {}
    except Exception as e:  # noqa: BLE001 — the marker line must still emit
        print(f"[bench] sharded metrics failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return {}


# ----------------------------------------------------------------- gang suite


def _gang_input(n_nodes: int = 8, victims_per_node: int = 4,
                n_high: int = 24, n_gangs: int = 8, gang_size: int = 4):
    """Mixed-priority + gang fleet with preemption contention, existing
    nodes only (no nodepools): low-priority victims hold most of the
    capacity, a high-priority singleton surge must preempt to land, and the
    gang wave oversubscribes what's left so a measurable fraction rolls
    back atomically."""
    from karpenter_tpu.api import wellknown as wk
    from karpenter_tpu.api.objects import ObjectMeta, Pod
    from karpenter_tpu.provisioning.scheduler import (
        BoundPodRef, ExistingNode, SolverInput,
    )
    from karpenter_tpu.utils.resources import PODS, Resources

    nodes = []
    for e in range(n_nodes):
        victims = [
            BoundPodRef(
                uid=f"victim-{e}-{v}", priority=0,
                requests=Resources.parse({"cpu": "1", "memory": "1Gi"}),
            )
            for v in range(victims_per_node)
        ]
        free = Resources.parse({"cpu": "2", "memory": "4Gi"})
        free[PODS] = 100
        nodes.append(ExistingNode(
            id=f"node-{e}",
            labels={wk.ZONE_LABEL: f"zone-{e % 2}",
                    wk.HOSTNAME_LABEL: f"node-{e}"},
            taints=[], free=free, bound_pods=victims,
        ))
    pods = []
    # one doomed gang above everything: 8-cpu members no node can host, so
    # every solve exercises the verdict -> rollback -> re-solve round
    for r in range(gang_size):
        pods.append(Pod(
            meta=ObjectMeta(
                name=f"doomed-{r}", uid=f"doomed-{r}",
                labels={wk.GANG_LABEL: "job-doomed",
                        wk.GANG_SIZE_LABEL: str(gang_size)},
            ),
            requests=Resources.parse({"cpu": "8", "memory": "1Gi"}),
            priority=200,
        ))
    # gang wave lands first (highest surviving priority), fits in free
    for g in range(n_gangs):
        for r in range(gang_size):
            pods.append(Pod(
                meta=ObjectMeta(
                    name=f"gang{g}-{r}", uid=f"gang{g}-{r}",
                    labels={wk.GANG_LABEL: f"job-{g:02d}",
                            wk.GANG_SIZE_LABEL: str(gang_size)},
                ),
                requests=Resources.parse({"cpu": "250m", "memory": "256Mi"}),
                priority=150,
            ))
    # singleton surge below the gangs: overflows the remaining free capacity,
    # so the tail must preempt the priority-0 victims to plan a landing
    for i in range(n_high):
        pods.append(Pod(
            meta=ObjectMeta(name=f"hi-{i:03d}", uid=f"hi-{i:03d}"),
            requests=Resources.parse({"cpu": "1", "memory": "1Gi"}),
            priority=100,
        ))
    return SolverInput(pods=pods, nodes=nodes, nodepools=[],
                       zones=("zone-0", "zone-1"))


def _gang_run(iters: int = 20) -> dict:
    """ISSUE 9 gang/preemption suite: the class-aware solve seam
    (solver/scheduling_class.py around the python oracle — the decision math
    is planner-parity-tested, so host numbers characterize the subsystem)
    over a contended mixed-priority + gang fleet. Emits the per-solve wall
    with the preemption pass engaged (preemption_solve_p99_ms), the fraction
    of gangs that committed atomically (gang_commit_rate), and planned
    evictions per solve (preemptions_per_solve)."""
    from karpenter_tpu.solver.backend import ReferenceSolver
    from karpenter_tpu.solver.scheduling_class import ClassAwareSolver

    inp = _gang_input()
    solver = ClassAwareSolver(ReferenceSolver())
    times = []
    for _ in range(max(iters, 2)):
        t0 = time.perf_counter()
        res = solver.solve(inp)
        times.append((time.perf_counter() - t0) * 1000)
    n = len(times)
    placed = solver.class_stats["gangs_placed"]
    unsched = solver.class_stats["gangs_unschedulable"]
    assert solver.class_stats["class_solves"] == n, solver.class_stats
    return {
        "preemption_solve_p99_ms": round(float(np.percentile(times, 99)), 2),
        "preemption_solve_p50_ms": round(float(np.percentile(times, 50)), 2),
        "gang_commit_rate": round(placed / max(placed + unsched, 1), 3),
        "preemptions_per_solve": round(solver.class_stats["preemptions"] / n, 2),
        "gang_rounds_per_solve": round(solver.class_stats["gang_rounds"] / n, 2),
        "gang_evictions_last_solve": len(res.evictions),
        "gangs_unschedulable_last_solve": len(res.gangs_unschedulable),
        "class_declines_total": solver.class_stats["declines"],
    }


def _gang_metrics() -> dict:
    """Scheduling-class keys for the run JSON and every host-only marker
    branch (ISSUE 9 acceptance: the three headline keys always report)."""
    try:
        out = _gang_run()
        print(
            f"[bench] gang suite: preemption p99={out['preemption_solve_p99_ms']}ms "
            f"commit_rate={out['gang_commit_rate']} "
            f"preemptions/solve={out['preemptions_per_solve']}",
            file=sys.stderr,
        )
        return out
    except Exception as e:  # noqa: BLE001 — the marker line must still emit
        print(f"[bench] gang metrics failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return {}


def bench_gang_suite() -> None:
    """CLI entry (--gang-suite): run the scheduling-class suite standalone
    and print ONE JSON line tagged gang_suite."""
    out = _gang_run(iters=int(os.environ.get("KTPU_GANG_ITERS", "20")))
    assert out["preemptions_per_solve"] > 0, out
    assert 0 < out["gang_commit_rate"] <= 1, out
    print(json.dumps({
        "metric": "preemption_solve_p99_ms",
        "value": out["preemption_solve_p99_ms"],
        "unit": "ms",
        "gang_suite": True,
        **out,
    }))


# ---------------------------------------------------------------- churn soak


def _soak_solver_cls():
    """Host-side fleet owner for the churn soak: the python oracle plus the
    wedge-class fault sites TPUSolver checks (solver.device_hang /
    device_lost), so an injected wedge parks this owner's dispatcher exactly
    the way a hung device call would — the fleet mechanics under test
    (canary miss -> fence -> requeue) are platform-independent."""
    from karpenter_tpu import faults
    from karpenter_tpu.solver.backend import ReferenceSolver

    class _SoakSolver(ReferenceSolver):
        def __init__(self):
            self.fault_tag = None

        def solve(self, inp):
            faults.check("solver.device_hang", tag=self.fault_tag)
            faults.check("solver.device_lost", tag=self.fault_tag)
            return super().solve(inp)

    return _SoakSolver


def _soak_run(duration_steps: int = 40, wedge_at_step: int = 12,
              fleet_size: int = 2, arrivals_per_step: int = 3,
              canary_deadline_s: float = 0.5, fence_after_misses: int = 1,
              num_pods: int = 40, backend: str = "reference") -> dict:
    """ISSUE 8 churn-soak: a sustained trace of disruption-class solves
    through a SolverFleet with a backend wedge (solver.device_hang on
    owner-0) injected mid-run. The fleet must fence the wedged owner off a
    canary deadline miss, re-route every in-flight solve, and keep serving —
    soak_dropped_solves counts tickets that never resolved OR resolved with
    an error after the full drain, and MUST be 0. failover_recovery_ms is
    wedge injection -> first solve completed on a surviving owner post-fence.
    Importable (tests/test_solver_fleet.py smoke) and driven by
    --soak-suite / _soak_metrics()."""
    from karpenter_tpu import faults
    from karpenter_tpu.solver.fleet import SolverFleet
    from karpenter_tpu.solver.pipeline import DISRUPTION

    if backend == "tpu":
        from karpenter_tpu.solver.backend import TPUSolver

        def factory(i):
            return TPUSolver(max_claims=1024)
    else:
        cls = _soak_solver_cls()

        def factory(i):
            return cls()

    # churn: a few distinct surge shapes cycled across steps (pod-count
    # deltas defeat any exact-hit caching, as real arrival churn would)
    churn = [build_input(num_pods + 7 * k) for k in range(3)]
    canary = build_input(2)
    fleet = SolverFleet(
        solver_factory=factory,
        size=fleet_size,
        canary_input_fn=lambda: canary,
        canary_deadline_s=canary_deadline_s,
        fence_after_misses=fence_after_misses,
        fence_drain_s=0.1,
        # no mid-soak recovery probing: the run measures a clean failover,
        # not a flapping owner (recovery has its own test coverage)
        recovery_probe_s=3600.0,
    )
    plan = faults.FaultPlan(seed=8)
    wedge = None
    tickets = []
    t_wedge = t_recovered = None
    failed = 0
    t0 = time.monotonic()
    try:
        with faults.active(plan):
            for step in range(duration_steps):
                if step == wedge_at_step:
                    wedge = plan.wedge("solver.device_hang", tag="owner-0")
                    t_wedge = time.monotonic()
                for a in range(arrivals_per_step):
                    tickets.append(fleet.submit(
                        churn[(step + a) % len(churn)], kind=DISRUPTION))
                fleet.probe_once()
                if (t_wedge is not None and t_recovered is None
                        and fleet.fleet_stats["failovers"] >= 1):
                    # fence landed: time the first post-fence solve that
                    # completes on a surviving owner
                    probe = fleet.submit(churn[0], kind=DISRUPTION)
                    probe.result(timeout=30)
                    t_recovered = time.monotonic()
                    tickets.append(probe)
            # full drain: every ticket the soak ever issued must resolve
            for t in tickets:
                try:
                    t.result(timeout=60)
                except Exception:  # noqa: BLE001 — counted as dropped below
                    failed += 1
        elapsed = time.monotonic() - t0
        dropped = fleet.unresolved()
        stats = dict(fleet.stats)
    finally:
        if wedge is not None:
            wedge.release()
        fleet.close()
    return {
        "soak_total_solves": len(tickets),
        "soak_dropped_solves": dropped + failed,
        "soak_failovers": stats["failovers"],
        "soak_requeued_solves": stats["requeued"],
        "soak_oracle_degraded": stats["oracle_degraded"],
        "solves_per_sec": round(len(tickets) / max(elapsed, 1e-9), 2),
        "failover_recovery_ms": round(
            (t_recovered - t_wedge) * 1000, 1
        ) if (t_recovered is not None and t_wedge is not None) else -1.0,
        "soak_wall_s": round(elapsed, 2),
        "soak_backend": backend,
    }


def _soak_metrics(backend: str = "reference") -> dict:
    """Fleet churn-soak keys for the run JSON and every host-only marker
    branch (ISSUE 8 acceptance: soak_dropped_solves reported, must be 0)."""
    try:
        out = _soak_run(backend=backend)
        print(
            f"[bench] soak ({out['soak_backend']}): "
            f"{out['soak_total_solves']} solves @ "
            f"{out['solves_per_sec']:.1f}/s — failovers={out['soak_failovers']} "
            f"requeued={out['soak_requeued_solves']} "
            f"recovery={out['failover_recovery_ms']:.0f}ms "
            f"dropped={out['soak_dropped_solves']}",
            file=sys.stderr,
        )
        return out
    except Exception as e:  # noqa: BLE001 — the marker line must still emit
        print(f"[bench] soak metrics failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return {}


def bench_soak_suite() -> None:
    """CLI entry (--soak-suite): run the churn soak standalone and print ONE
    JSON line tagged soak_suite."""
    out = _soak_run(
        duration_steps=int(os.environ.get("KTPU_SOAK_STEPS", "60")),
        arrivals_per_step=int(os.environ.get("KTPU_SOAK_ARRIVALS", "4")),
        backend=os.environ.get("KTPU_SOAK_BACKEND", "reference"),
    )
    assert out["soak_dropped_solves"] == 0, out
    print(json.dumps({
        "metric": "soak_solves_per_sec",
        "value": out["solves_per_sec"],
        "unit": "solves/s",
        "soak_suite": True,
        **out,
    }))


# ------------------------------------------------------------- tenant suite


def _tenant_pass(weights: dict, solves_per_tenant: int, num_pods: int,
                 poison_victim: str = None, max_queue_depth: int = 64) -> dict:
    """One mux pass: every tenant submits `solves_per_tenant` churn-shaped
    disruption solves through a shared host-seam SolveService; when
    `poison_victim` is set, that tenant's inputs raise on the device path
    (the mux must open ONLY its breaker and replay on ITS oracle lane).
    Returns per-tenant latency/completion data for the suite's metrics."""
    import threading as _threading

    from karpenter_tpu.solver.backend import ReferenceSolver
    from karpenter_tpu.solver.pipeline import DISRUPTION, SolveService
    from karpenter_tpu.solver.tenancy import (
        TenantMux,
        TenantRegistry,
        TenantSpec,
    )

    class _PoisonableSolver(ReferenceSolver):
        # the mux stamps tenant_id onto every input it forwards, so the
        # shared owner can fail exactly the victim's device path — the
        # victim's own oracle rung (plain ReferenceSolver.solve) still lands
        def solve(self, inp):
            if (poison_victim is not None
                    and getattr(inp, "tenant_id", None) == poison_victim):
                raise RuntimeError("poisoned tenant input")
            return super().solve(inp)

    registry = TenantRegistry([
        TenantSpec(tid, weight=w, max_queue_depth=max_queue_depth)
        for tid, w in weights.items()
    ])
    service = SolveService(_PoisonableSolver())
    mux = TenantMux(service, registry, breaker_threshold=2,
                    breaker_probe_s=3600.0, own_service=True)
    churn = [build_input(num_pods + 3 * k) for k in range(3)]
    lock = _threading.Lock()
    done_at = {tid: [] for tid in weights}  # (completion_time, duration_s)
    tickets = []
    rejects = failed = 0
    t0 = time.monotonic()
    try:
        for i in range(solves_per_tenant):
            for tid in weights:
                ts = time.monotonic()

                def _record(t, tid=tid, ts=ts):
                    now = time.monotonic()
                    with lock:
                        done_at[tid].append((now, now - ts))

                try:
                    tk = mux.submit(churn[i % len(churn)], tenant_id=tid,
                                    kind=DISRUPTION)
                except Exception:  # noqa: BLE001 — admission reject
                    rejects += 1
                    continue
                tk.on_done(_record)
                tickets.append(tk)
        for t in tickets:
            try:
                t.result(timeout=120)
            except Exception:  # noqa: BLE001 — counted as dropped below
                failed += 1
        elapsed = time.monotonic() - t0
        dropped = mux.unresolved() + failed
        stats = mux.tenant_stats()
        mux_stats = dict(mux.mux_stats)
    finally:
        mux.close()
    return {
        "weights": weights,
        "done_at": done_at,
        "elapsed_s": elapsed,
        "completed": len(tickets) - failed,
        "dropped": dropped,
        "rejects": rejects,
        "stats": stats,
        "mux": mux_stats,
    }


def _tenant_run(num_tenants: int = 8, solves_per_tenant: int = 10,
                num_pods: int = 24, victim: str = "t0") -> dict:
    """ISSUE 11 multi-tenant soak: >= 8 mixed-weight tenants share one owner
    pool behind the TenantMux; a baseline pass (nobody poisoned) then a
    contended pass with the victim's device path poisoned. The victim must
    degrade to ITS oracle with zero drops; every other tenant's p99 must
    hold (noisy_neighbor_slowdown_x = median non-victim contended/baseline
    p99 ratio, acceptance <= 2x); fairness_index is Jain's index over
    weight-normalized completions inside the saturated window."""
    mixed = [1.0, 2.0, 1.0, 1.5, 1.0, 0.5, 1.0, 1.0]
    weights = {f"t{i}": mixed[i % len(mixed)] for i in range(num_tenants)}

    def _p99(durs):
        if not durs:
            return -1.0
        s = sorted(durs)
        return s[min(len(s) - 1, int(0.99 * len(s)))] * 1000.0

    base = _tenant_pass(weights, solves_per_tenant, num_pods)
    cont = _tenant_pass(weights, solves_per_tenant, num_pods,
                        poison_victim=victim)
    p99_base = {tid: _p99([d for _, d in v])
                for tid, v in base["done_at"].items()}
    p99_cont = {tid: _p99([d for _, d in v])
                for tid, v in cont["done_at"].items()}
    ratios = sorted(
        p99_cont[tid] / max(p99_base[tid], 1e-6)
        for tid in weights if tid != victim and p99_cont[tid] > 0
    )
    slowdown = ratios[len(ratios) // 2] if ratios else -1.0
    # fairness: completions inside the saturated window (up to the first
    # tenant finishing its whole stream), weight-normalized, Jain's index
    last_done = [max(t for t, _ in v) for v in cont["done_at"].values() if v]
    t_sat = min(last_done) if last_done else 0.0
    share = [
        sum(1 for t, _ in cont["done_at"][tid] if t <= t_sat) / w
        for tid, w in weights.items() if tid != victim
    ]
    fairness = (
        (sum(share) ** 2) / (len(share) * sum(x * x for x in share))
        if share and sum(x * x for x in share) > 0 else -1.0
    )
    non_victim_p99 = sorted(v for tid, v in p99_cont.items() if tid != victim)
    victim_stats = cont["stats"][victim]
    # cohort fusion (ISSUE 16): size/width of the contended pass's fused
    # dispatches — the host-only proxy for "one launch serves many tenants"
    cont_mux = cont.get("mux", {})
    fused = int(cont_mux.get("cohort_dispatches", 0))
    memb = int(cont_mux.get("cohort_members", 0))
    return {
        "tenant_count": num_tenants,
        "tenant_p99_ms": round(
            non_victim_p99[len(non_victim_p99) // 2], 2
        ) if non_victim_p99 else -1.0,
        "tenant_victim_p99_ms": round(p99_cont.get(victim, -1.0), 2),
        "aggregate_solves_per_sec": round(
            cont["completed"] / max(cont["elapsed_s"], 1e-9), 2
        ),
        "fairness_index": round(fairness, 3),
        "cohort_size_mean": round(memb / max(1, fused), 2),
        "fused_dispatches_total": fused,
        "noisy_neighbor_slowdown_x": round(slowdown, 2),
        "tenant_admission_rejects_total": cont["rejects"] + sum(
            s["rejected"] for s in cont["stats"].values()
        ),
        "tenant_dropped_solves": base["dropped"] + cont["dropped"],
        "tenant_victim_degraded": victim_stats["degraded"],
        "tenant_victim_breaker": victim_stats["breaker"],
    }


def _tenant_metrics() -> dict:
    """Multi-tenant mux keys for the run JSON and every host-only marker
    branch (ISSUE 11 acceptance: tenant_dropped_solves reported, must be 0;
    noisy_neighbor_slowdown_x <= 2)."""
    try:
        out = _tenant_run()
        print(
            f"[bench] tenants: {out['tenant_count']} @ "
            f"{out['aggregate_solves_per_sec']:.1f} solves/s — "
            f"p99={out['tenant_p99_ms']}ms "
            f"fairness={out['fairness_index']} "
            f"cohort_mean={out['cohort_size_mean']} "
            f"fused={out['fused_dispatches_total']} "
            f"noisy_neighbor={out['noisy_neighbor_slowdown_x']}x "
            f"victim_degraded={out['tenant_victim_degraded']} "
            f"dropped={out['tenant_dropped_solves']}",
            file=sys.stderr,
        )
        return out
    except Exception as e:  # noqa: BLE001 — the marker line must still emit
        print(f"[bench] tenant metrics failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return {}


def bench_tenant_suite() -> None:
    """CLI entry (--tenant-suite): run the multi-tenant soak standalone and
    print ONE JSON line tagged tenant_suite."""
    out = _tenant_run(
        num_tenants=int(os.environ.get("KTPU_TENANT_COUNT", "8")),
        solves_per_tenant=int(os.environ.get("KTPU_TENANT_SOLVES", "10")),
    )
    assert out["tenant_dropped_solves"] == 0, out
    assert out["tenant_victim_degraded"] > 0, out
    assert out["tenant_victim_breaker"] == "open", out
    print(json.dumps({
        "metric": "tenant_aggregate_solves_per_sec",
        "value": out["aggregate_solves_per_sec"],
        "unit": "solves/s",
        "tenant_suite": True,
        **out,
    }))


def _explain_metrics(num_pods: int = 2_000) -> dict:
    """ISSUE 12 decision-provenance + SLO proof.

    (a) Explain OFF (the production default) must be inert: the warm solve
        loop fetches the same d2h bytes with the hooks compiled in as the
        seed did — zero extra wire traffic — and 10k disabled capture/note
        calls allocate NOTHING (sys.getallocatedblocks, gc paused, same
        guard discipline as the tracing-off check).
    (b) explain_bytes_per_solve: the EXPLAIN wire section's size when ON
        (header + G x (1 + top_k) int32 words) — measured off the ledger
        delta between an explain-off and explain-on warm solve.
    (c) explain_overhead_pct: the whole added ON-PATH cost of one enabled
        solve — the deferred capture (store put) plus the device table
        round trip — relative to the solve wall, asserted < 2% so
        provenance stays affordable. Record assembly is lazy (runs on
        /debug/explain reads) and so is off this budget by design.
    (d) slo_burn_rate_fast/slow: the burn-rate engine fed the measured
        solve latencies against the default 1s/99% objective — sanity that
        the /healthz numbers derive from the same observations.
    """
    try:
        import gc

        from karpenter_tpu.metrics.registry import SOLVER_EXPLAIN_BYTES
        from karpenter_tpu.obs import explain as obsexplain
        from karpenter_tpu.obs import slo as obsslo
        from karpenter_tpu.solver.backend import TPUSolver
        from karpenter_tpu.solver.encode import encode, quantize_input

        # -- (a) off-path inertness ----------------------------------------
        obsexplain.configure(enabled=False)
        for _ in range(64):  # warm inline caches out of the window
            obsexplain.note("bench", {})
            obsexplain.capture(None, None, "bench")
        gc.collect()
        gc.disable()
        try:
            b0 = sys.getallocatedblocks()
            for _ in range(10_000):
                obsexplain.note("bench", {})
                obsexplain.capture(None, None, "bench")
            alloc_blocks = sys.getallocatedblocks() - b0
        finally:
            gc.enable()
        assert alloc_blocks < 50, (
            f"explain-off hooks allocated {alloc_blocks} blocks over 10k calls"
        )

        inp = build_input(num_pods)
        solver = TPUSolver(max_claims=1024)
        solver.solve(inp)  # cold: compile + arena upload off the window

        # warm solves, explain off: the d2h baseline and the latency base
        led = solver.ledger
        f0 = led.snapshot()["total"]["d2h_bytes"]
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            res = solver.solve(inp)
            times.append((time.perf_counter() - t0) * 1000)
        solve_ms = float(np.percentile(np.asarray(times), 50))
        off_bytes = (led.snapshot()["total"]["d2h_bytes"] - f0) / 3.0

        # warm solves, explain on: the delta IS the explain wire section.
        # _device_explain is wrapped to time the real per-solve device cost
        # (pad + dispatch + fetch + unpack) in situ.
        obsexplain.configure(enabled=True, top_k=8)
        dev_times = []
        orig_dev = solver._device_explain

        def _timed_dev(enc_, out_):
            td = time.perf_counter()
            r = orig_dev(enc_, out_)
            dev_times.append((time.perf_counter() - td) * 1000)
            return r

        solver._device_explain = _timed_dev
        try:
            solver.solve(inp)  # explain kernel compile off the window
            f1 = led.snapshot()["total"]["d2h_bytes"]
            for _ in range(3):
                solver.solve(inp)
            on_bytes = (led.snapshot()["total"]["d2h_bytes"] - f1) / 3.0
            explain_bytes = max(0.0, on_bytes - off_bytes)
            gauge_bytes = SOLVER_EXPLAIN_BYTES.value()
            entry = obsexplain.store().recent(1)
            assert entry and entry[0]["record"]["pods"], "no explain record"

            # -- (c) capture overhead, analytic ----------------------------
            # the enabled path's whole added per-solve cost: the deferred
            # capture (store put of references — record assembly is lazy,
            # it runs on /debug/explain reads, not the solve path) plus the
            # device table round trip, both timed directly (differencing
            # two solve p50s would drown a <2% effect in jitter)
            qinp = quantize_input(inp)
            enc = encode(qinp)
            t0 = time.perf_counter()
            n_cap = 10
            for _ in range(n_cap):
                obsexplain.capture(qinp, res, "bench", enc=enc)
            capture_ms = (time.perf_counter() - t0) / n_cap * 1000
            # dev_times[0] is the compile solve — steady state is the
            # rest; min is the jitter-robust estimate of the true cost
            warm_dev = dev_times[1:] or dev_times
            device_tbl_ms = float(min(warm_dev)) if warm_dev else 0.0
            overhead_pct = 100.0 * (capture_ms + device_tbl_ms) / solve_ms
            assert overhead_pct < 2.0, (
                f"explain on-path overhead {overhead_pct:.2f}% >= 2% "
                f"(capture {capture_ms:.3f}ms + device table "
                f"{device_tbl_ms:.3f}ms over a {solve_ms:.1f}ms solve)"
            )
        finally:
            solver._device_explain = orig_dev
            obsexplain.configure(enabled=False)

        # -- (d) SLO burn rates off the measured latencies -----------------
        obsslo.configure()  # default objectives, fresh windows
        for ms in times:
            obsslo.record("solve", ms / 1000.0)
        rates = obsslo.burn_rates()["solve"]
        print(
            f"[bench] explain ({num_pods} pods): bytes/solve={explain_bytes:.0f} "
            f"(gauge {gauge_bytes:.0f}) overhead={overhead_pct:.3f}% "
            f"off-path-allocs={alloc_blocks} "
            f"slo_burn fast={rates['fast']:.2f} slow={rates['slow']:.2f}",
            file=sys.stderr,
        )
        return {
            "explain_bytes_per_solve": round(explain_bytes, 1),
            "explain_overhead_pct": round(overhead_pct, 4),
            "explain_off_alloc_blocks": int(alloc_blocks),
            "slo_burn_rate_fast": round(rates["fast"], 4),
            "slo_burn_rate_slow": round(rates["slow"], 4),
        }
    except Exception as e:  # noqa: BLE001 — the marker line must still emit
        print(f"[bench] explain metrics failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return {}


def bench_explain_suite() -> None:
    """CLI entry (--explain-suite): run the provenance/SLO suite standalone
    and print ONE JSON line tagged explain_suite."""
    out = _explain_metrics()
    assert out.get("explain_overhead_pct", 100.0) < 2.0, out
    print(json.dumps({
        "metric": "explain_bytes_per_solve",
        "value": out.get("explain_bytes_per_solve", -1),
        "unit": "bytes",
        "explain_suite": True,
        **out,
    }))


# ---------------------------------------------------------- streaming suite


def _streaming_run(batches: int = 120, pods_per_batch: int = 8,
                   base_pods: int = 64, epoch_every: int = 32,
                   parity_every: int = 20, drain: bool = True) -> dict:
    """ISSUE 13 streaming delta-solve: a sustained arrival trace through the
    journal -> StreamingSolver -> solver path. Each batch creates pods in the
    store, pump() folds the journal delta, build_input() assembles from the
    resident model, and the solve runs with run-table event staging enabled
    (backend.stream_run_events -> arena.apply_run_events).

    Two timings per batch: the INGEST leg (pump + pending + build_input —
    the host tax streaming makes event-proportional) drives
    arrival_batches_per_sec; the full batch (ingest + solve) drives
    steady_state_solve_p99_ms. Every `parity_every` batches the snapshot
    path solves the same universe and the placements must match exactly
    (parity_failures MUST stay 0). With `drain` (the steady-state default)
    each batch's pods are BOUND after the solve — arrivals leave the pending
    set the way a real binder empties it, so the working set stays constant
    (base_pods standing backlog + one batch) instead of growing O(batches).
    Host-measurable end to end — the model fold, journal, and arena/ledger
    semantics are platform-independent."""
    from karpenter_tpu.api.objects import (
        NodeClaimTemplate,
        NodePool,
        ObjectMeta,
        Pod,
    )
    from karpenter_tpu.catalog.catalog import CatalogSpec, generate
    from karpenter_tpu.controllers import store as kst
    from karpenter_tpu.kwok.cloud import KwokCloud
    from karpenter_tpu.kwok.cloudprovider import KwokCloudProvider
    from karpenter_tpu.provisioning.provisioner import Provisioner
    from karpenter_tpu.solver.backend import TPUSolver
    from karpenter_tpu.solver.streaming import StreamingSolver
    from karpenter_tpu.state.cluster import Cluster
    from karpenter_tpu.utils.resources import Resources

    store = kst.Store()
    types = generate(CatalogSpec())
    cloud = KwokCloud(store, types)
    provider = KwokCloudProvider(cloud, types)
    cluster = Cluster(store)
    store.create(kst.NODEPOOLS, NodePool(
        meta=ObjectMeta(name="general"), template=NodeClaimTemplate()))
    solver = TPUSolver(max_claims=1024)
    solver.stream_run_events = True
    streaming = StreamingSolver(cluster, provider, epoch_every=epoch_every)
    snap = Provisioner(store, cluster, provider, solver,
                       batch_idle_s=0, batch_max_s=0)

    sizes = [("100m", "128Mi"), ("250m", "256Mi"), ("500m", "512Mi"),
             ("1", "1Gi"), ("2", "2Gi"), ("500m", "1Gi"), ("1", "2Gi")]

    def _mkpod(i: int) -> Pod:
        cpu, mem = sizes[i % len(sizes)]
        return Pod(meta=ObjectMeta(name=f"s-{i}", uid=f"s-{i}"),
                   requests=Resources.parse({"cpu": cpu, "memory": mem}))

    n = 0
    for _ in range(base_pods):
        store.create(kst.PODS, _mkpod(n))
        n += 1
    streaming.pump()
    # warm: compile + full packed upload happen outside the measured loop
    solver.solve(streaming.build_input(streaming.pending_pods()))
    up0 = solver.ledger.total["h2d_bytes"]
    ingest_s = 0.0
    batch_ms = []
    parity_failures = 0
    t0 = time.perf_counter()
    for b in range(batches):
        for _ in range(pods_per_batch):
            store.create(kst.PODS, _mkpod(n))
            n += 1
        tb = time.perf_counter()
        streaming.pump()
        pending = streaming.pending_pods()
        inp = streaming.build_input(pending)
        ingest_s += time.perf_counter() - tb
        res = solver.solve(inp)
        batch_ms.append((time.perf_counter() - tb) * 1000)
        if parity_every and b % parity_every == 0:
            ref = solver.solve(snap.build_input(cluster.pending_pods()))
            if res.placements != ref.placements:
                parity_failures += 1
        if drain:
            # the binder's job: this batch's arrivals got placements, so
            # they leave pending. The MODIFIED events stream through the
            # journal and fold in the NEXT batch's pump — part of its ingest.
            for i in range(n - pods_per_batch, n):
                p = store.get(kst.PODS, f"s-{i}")
                p.node_name = "soak-sink"
                store.update(kst.PODS, p)
    elapsed = time.perf_counter() - t0
    up_bytes = solver.ledger.total["h2d_bytes"] - up0
    snap_stats = streaming.snapshot()
    return {
        "arrival_batches_per_sec": round(batches / max(ingest_s, 1e-9), 1),
        "steady_state_solve_p99_ms": round(
            float(np.percentile(np.asarray(batch_ms), 99)), 2),
        "rebaseline_total": int(snap_stats["rebaseline_total"]),
        "streaming_upload_bytes_per_batch": round(up_bytes / batches, 1),
        "streaming_batches_applied": int(snap_stats["batches_applied"]),
        "streaming_events_applied": int(snap_stats["events_applied"]),
        "streaming_epoch_checks": int(snap_stats["epoch_checks"]),
        "streaming_drift_detected": int(snap_stats["drift_detected"]),
        "streaming_parity_failures": parity_failures,
        "streaming_wall_s": round(elapsed, 2),
        "streaming_event_stage_hits": int(
            solver.stats.get("event_stage_hits", 0)),
    }


def _streaming_metrics() -> dict:
    """Streaming delta-solve keys for the run JSON and every host-only
    marker branch (ISSUE 13 acceptance: the backend-unavailable marker must
    still carry the streaming keys)."""
    try:
        out = _streaming_run()
        print(
            f"[bench] streaming: {out['streaming_batches_applied']} batches @ "
            f"{out['arrival_batches_per_sec']:.0f}/s ingest — "
            f"solve_p99={out['steady_state_solve_p99_ms']:.1f}ms "
            f"rebaselines={out['rebaseline_total']} "
            f"upload/batch={out['streaming_upload_bytes_per_batch']:.0f}B "
            f"parity_failures={out['streaming_parity_failures']}",
            file=sys.stderr,
        )
        return out
    except Exception as e:  # noqa: BLE001 — the marker line must still emit
        print(f"[bench] streaming metrics failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return {}


def bench_streaming_suite() -> None:
    """CLI entry (--streaming-suite): run the streaming delta-solve suite
    standalone and print ONE JSON line tagged streaming_suite."""
    out = _streaming_run(
        batches=int(os.environ.get("KTPU_STREAMING_BATCHES", "200")),
        pods_per_batch=int(os.environ.get("KTPU_STREAMING_PODS", "8")),
    )
    assert out["streaming_parity_failures"] == 0, out
    assert out["streaming_batches_applied"] > 0, out
    print(json.dumps({
        "metric": "arrival_batches_per_sec",
        "value": out["arrival_batches_per_sec"],
        "unit": "batches/s",
        "streaming_suite": True,
        **out,
    }))


# ------------------------------------------------------------ restore suite


def _restore_run(num_pods: int = 50_000, parity_pods: int = 300,
                 handover_solves: int = 12) -> dict:
    """ISSUE 17 durable resident state: restart-to-first-solve cold vs
    vault-restored at the headline pod shape, plus the blue/green handover
    zero-drop proof. Host-measurable end to end — the vault persists the
    HOST-side resident model (encode-core donors, manifests, cursors); the
    device re-adopts from digests on its own.

    Three legs:
    - COLD: process-local caches cleared (the restart), full encode from
      nothing — restart_to_first_solve_cold_ms, cluster-size-bounded.
    - VAULT: snapshot the warm state (vault_snapshot_ms — the async
      writer's cost, off the hot path in production), clear the caches
      again, restore + first encode — restart_to_first_solve_ms. The
      encode must ADOPT a vault donor (content-keyed: signature sequence +
      catalog fingerprint), and its tables must be bit-identical to the
      cold build's.
    - HANDOVER: a live mux with solves in flight swaps blue -> green via
      BlueGreenHandover (shadow parity proven first); every ticket from
      before, during, and after the cutover must resolve —
      handover_dropped_solves MUST be 0 (asserted here: the gate skips
      <=0 keys by design, so the suite itself is the gate)."""
    import shutil
    import tempfile

    from karpenter_tpu.solver import encode as em
    from karpenter_tpu.solver import encode_cache as ec
    from karpenter_tpu.solver.backend import ReferenceSolver
    from karpenter_tpu.solver.encode import encode, quantize_input
    from karpenter_tpu.solver.handover import BlueGreenHandover
    from karpenter_tpu.solver.pipeline import DISRUPTION, SolveService
    from karpenter_tpu.solver.tenancy import (
        TenantMux,
        TenantRegistry,
        TenantSpec,
    )
    from karpenter_tpu.solver.vault import SolverStateVault

    inp = build_input(num_pods)

    def _simulate_restart():
        # everything process-local dies with the process; only the vault
        # files (and the persistent compile cache) survive
        em._CORE_CACHE.clear()
        em._CAT_FP_CACHE.clear()
        ec._TENANT_CORE_CACHES.clear()
        ec.clear_vault_donors()
        ec.reset_stats()

    # ---- cold leg: restart with no vault ---------------------------------
    _simulate_restart()
    t0 = time.perf_counter()
    enc_cold = encode(quantize_input(inp))
    cold_ms = (time.perf_counter() - t0) * 1000

    vdir = tempfile.mkdtemp(prefix="ktpu-vault-bench-")
    try:
        # ---- snapshot the warm resident state ----------------------------
        vault = SolverStateVault(vdir, interval_s=0.001, keep=2)
        t0 = time.perf_counter()
        snap_path = vault.snapshot_now()
        snap_ms = (time.perf_counter() - t0) * 1000
        assert snap_path is not None, "vault snapshot failed"

        # ---- vault leg: restart, restore, first encode -------------------
        _simulate_restart()
        restorer = SolverStateVault(vdir, interval_s=0.001, keep=2)
        t0 = time.perf_counter()
        report = restorer.restore(install=True)
        enc_restored = encode(quantize_input(inp))
        restored_ms = (time.perf_counter() - t0) * 1000
        assert report is not None, "vault restore found nothing"
        adopted = int(ec.STATS["vault_adopts"])
        assert adopted >= 1, f"restored encode did not adopt: {dict(ec.STATS)}"
        # decision-identity: the donor-adopted core must reproduce the cold
        # build's tables exactly — a stale vault may only cost time, never
        # change a decision
        for fld in ("group_req", "run_group", "run_count", "type_capacity"):
            a = getattr(enc_cold, fld, None)
            b = getattr(enc_restored, fld, None)
            assert a is not None and np.array_equal(
                np.asarray(a), np.asarray(b)
            ), f"vault-restored encode diverged from cold build on {fld}"
        parity_ok = 1
    finally:
        shutil.rmtree(vdir, ignore_errors=True)

    # ---- handover leg: zero-drop blue/green cutover under load -----------
    registry = TenantRegistry([
        TenantSpec("t0", weight=1.0, max_queue_depth=256)
    ])
    blue = SolveService(ReferenceSolver())
    mux = TenantMux(blue, registry, own_service=True)
    churn = [build_input(parity_pods + 3 * k) for k in range(3)]
    dropped = 0
    try:
        t0 = time.perf_counter()
        tickets = [
            mux.submit(churn[i % len(churn)], tenant_id="t0", kind=DISRUPTION)
            for i in range(handover_solves)
        ]
        green = SolveService(ReferenceSolver())
        ho = BlueGreenHandover(mux, green)
        rep = ho.run(shadow_inputs=[churn[0]], drain_s=60.0)
        # the mux must keep accepting across the cutover — these land green
        tickets += [
            mux.submit(churn[i % len(churn)], tenant_id="t0", kind=DISRUPTION)
            for i in range(4)
        ]
        for t in tickets:
            try:
                t.result(timeout=120)
            except Exception:  # noqa: BLE001 — any loss counts as a drop
                dropped += 1
        handover_ms = (time.perf_counter() - t0) * 1000
        dropped += int(rep["dropped"])
    finally:
        mux.close()
    assert dropped == 0, f"handover dropped {dropped} solve(s)"

    return {
        "restart_to_first_solve_ms": round(restored_ms, 2),
        "restart_to_first_solve_cold_ms": round(cold_ms, 2),
        "restore_speedup_x": round(cold_ms / max(restored_ms, 1e-9), 2),
        "vault_snapshot_ms": round(snap_ms, 2),
        "vault_donors_adopted": adopted,
        "vault_restore_parity_ok": parity_ok,
        "handover_dropped_solves": dropped,
        "handover_shadow_mismatches": 0,
        "handover_wall_ms": round(handover_ms, 2),
    }


def _restore_metrics() -> dict:
    """Durable-resident-state keys for the run JSON and every host-only
    marker branch (ISSUE 17 acceptance: the backend-unavailable marker must
    still carry the restore keys)."""
    try:
        out = _restore_run()
        print(
            f"[bench] restore: cold={out['restart_to_first_solve_cold_ms']:.0f}ms "
            f"vault={out['restart_to_first_solve_ms']:.0f}ms "
            f"({out['restore_speedup_x']:.1f}x) "
            f"snapshot={out['vault_snapshot_ms']:.0f}ms "
            f"adopted={out['vault_donors_adopted']} "
            f"handover_dropped={out['handover_dropped_solves']}",
            file=sys.stderr,
        )
        return out
    except Exception as e:  # noqa: BLE001 — the marker line must still emit
        print(f"[bench] restore metrics failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return {}


def bench_restore_suite() -> None:
    """CLI entry (--restore-suite): run the restart/handover suite
    standalone and print ONE JSON line tagged restore_suite."""
    out = _restore_run(
        num_pods=int(os.environ.get("KTPU_RESTORE_PODS", "50000")),
    )
    assert out["handover_dropped_solves"] == 0, out
    assert out["vault_restore_parity_ok"] == 1, out
    # acceptance: vault-restored restart at least 2x faster than cold at
    # the headline shape
    assert out["restore_speedup_x"] >= 2.0, out
    print(json.dumps({
        "metric": "restart_to_first_solve_ms",
        "value": out["restart_to_first_solve_ms"],
        "unit": "ms",
        "restore_suite": True,
        **out,
    }))


# --------------------------------------------------------- federation suite


class _PipeHostService:
    """One virtual federation host: a hostmesh WorkerProc (separate
    process, own solver) behind a FIFO dispatcher thread, presenting the
    submit seam the FederationRouter routes to. Solve jobs arrive
    PRE-PICKLED (bytes) so the parent's per-solve GIL share is one pipe
    write — the soak measures host scaling, not parent serialization."""

    def __init__(self, name: str):
        import queue as _q
        import threading as _th

        from karpenter_tpu.parallel.hostmesh import WorkerProc

        self.worker = WorkerProc(name)
        self._q: "_q.Queue" = _q.Queue()
        self._dead = None
        self._t = _th.Thread(target=self._loop, daemon=True)
        self._t.start()

    def submit(self, inp, kind="provisioning", rev=None, tenant_id=None):
        import pickle as _pkl

        from karpenter_tpu.solver.pipeline import SolveTicket

        t = SolveTicket(kind, rev=rev, tenant_id=tenant_id)
        if self._dead is not None:
            t._deliver(error=self._dead)
            return t
        blob = inp if isinstance(inp, bytes) else _pkl.dumps(
            {"kind": "solve", "inp": inp}, protocol=_pkl.HIGHEST_PROTOCOL
        )
        self._q.put((t, blob))
        return t

    def submit_fn(self, dispatch_fn, kind="disruption", tenant_id=None):
        raise NotImplementedError("pipe hosts serve whole solves only")

    def queue_depth(self) -> int:
        return self._q.qsize()

    def occupancy(self) -> float:
        return 0.0

    def _loop(self) -> None:
        import queue as _q

        from karpenter_tpu.parallel.hostmesh import WorkerDead

        while True:
            item = self._q.get()
            if item is None:
                return
            t, blob = item
            try:
                t._deliver(result=self.worker.call_pickled(blob))
            except WorkerDead as e:
                self._dead = e
                # fail fast: everything queued behind the death is on a
                # dead host too — the router's fence pass requeues them
                t._deliver(error=e)
                while True:
                    try:
                        t2, _ = self._q.get_nowait()
                    except _q.Empty:
                        return
                    t2._deliver(error=e)
            except BaseException as e:  # noqa: BLE001 — deliver, keep serving
                t._deliver(error=e)

    def close(self) -> None:
        self._q.put(None)
        self.worker.close()


def _federation_run(n_hosts: int = 4, per_host_tenants: int = 2,
                    solves_per_tenant: int = 4) -> dict:
    """Virtual multi-process federation soak (ISSUE 18 acceptance):

    - SCALING: N subprocess worker hosts behind a FederationRouter, tenant
      names chosen so the consistent hash homes an equal tenant count on
      every host; aggregate router throughput over all hosts vs ONE host
      driven directly. scaling_efficiency_4h = (thru_N / thru_1) / N.
    - FAILOVER: mid-churn SIGKILL of a worker host. The router must fence
      it on the first WorkerDead, requeue its outstanding solves onto the
      survivors in submission order, and resolve EVERY ticket —
      federation_dropped_solves MUST be 0 (asserted here: the gate skips
      <=0 keys by design, so the suite itself is the gate).
      failover_recovery_ms is kill -> last victim-homed ticket resolved.
    """
    import pickle as _pkl

    from karpenter_tpu.solver.federation import FederationRouter

    hosts = [f"fh{i}" for i in range(n_hosts)]
    router = FederationRouter(hosts, self_host=hosts[0], own_services=True)
    services = {h: _PipeHostService(h) for h in hosts}
    for h, svc in services.items():
        router.attach(h, svc)

    # balanced tenant placement: scan candidate names until every host
    # homes exactly per_host_tenants of them (placement is the hash's to
    # make — the suite only PICKS tenants, it never overrides routing)
    per_host: dict = {h: [] for h in hosts}
    tenants = []
    i = 0
    while any(len(v) < per_host_tenants for v in per_host.values()):
        name = f"tenant-{i}"
        i += 1
        home = router._ring.route(name)
        if len(per_host[home]) < per_host_tenants:
            per_host[home].append(name)
            tenants.append(name)
    # device-bound host profile: a small real solve plus a simulated
    # device-residency window (hostmesh worker sleeps with the CPU free) —
    # on a single-core dev box N CPU-bound workers would just time-share
    # the core and mask the plane this suite measures (routing, pipes,
    # failover); on real hardware the window is the TPU dispatch itself.
    # The catalog is stride-sampled (~60 of ~730 types, diversity kept) so
    # per-solve host CPU (pickle/unpickle of the types table) stays well
    # under the device window even with N workers sharing one core.
    import dataclasses as _dc

    inp = build_input(10)
    inp = _dc.replace(inp, nodepools=[
        _dc.replace(p, instance_types=p.instance_types[::12])
        for p in inp.nodepools
    ])
    blob = _pkl.dumps({"kind": "solve", "inp": inp, "device_ms": 300},
                      protocol=_pkl.HIGHEST_PROTOCOL)

    dropped = 0
    try:
        # warm every worker (lazy solver import + first-solve overheads)
        for t in [svc.submit(blob) for svc in services.values()]:
            t.result(timeout=120)

        # ---- 1-host baseline -------------------------------------------
        n1 = per_host_tenants * solves_per_tenant
        t0 = time.perf_counter()
        for t in [services[hosts[0]].submit(blob) for _ in range(n1)]:
            t.result(timeout=120)
        thru1 = n1 / (time.perf_counter() - t0)

        # ---- N-host aggregate through the router -----------------------
        nN = len(tenants) * solves_per_tenant
        t0 = time.perf_counter()
        tickets = [
            router.submit(blob, kind="disruption", tenant_id=tn)
            for _ in range(solves_per_tenant) for tn in tenants
        ]
        for t in tickets:
            t.result(timeout=120)
        thruN = nN / (time.perf_counter() - t0)
        efficiency = (thruN / thru1) / n_hosts

        # ---- mid-churn host kill ---------------------------------------
        victim = router._ring.route(tenants[0])
        victim_tenants = set(per_host[victim])
        churn: list = []
        half = [router.submit(blob, kind="disruption", tenant_id=tn)
                for _ in range(solves_per_tenant) for tn in tenants]
        churn += half
        t_kill = time.perf_counter()
        services[victim].worker.kill()
        churn += [router.submit(blob, kind="disruption", tenant_id=tn)
                  for _ in range(2) for tn in tenants]
        victim_done = 0.0
        for t in churn:
            try:
                t.result(timeout=120)
                if t.tenant_id in victim_tenants:
                    victim_done = max(victim_done,
                                      time.perf_counter() - t_kill)
            except Exception:  # noqa: BLE001 — any loss counts as a drop
                dropped += 1
        recovery_ms = victim_done * 1000
        stats = router.federation_stats()
    finally:
        router.close()
    assert dropped == 0, f"federation dropped {dropped} solve(s): {stats}"
    assert stats["cross_host_failovers"] >= 1, stats
    return {
        "federated_solves_per_sec": round(thruN, 2),
        "federated_solves_per_sec_1h": round(thru1, 2),
        "scaling_efficiency_4h": round(efficiency, 3),
        "failover_recovery_ms": round(recovery_ms, 2),
        "federation_dropped_solves": dropped,
        "federation_requeued_solves": int(stats["requeued"]),
        "federation_hosts": n_hosts,
    }


def _federation_metrics() -> dict:
    """Federation keys for the run JSON and every host-only marker branch
    (ISSUE 18 acceptance: the backend-unavailable marker must still carry
    the federation keys — the workers are subprocesses, chipless anyway)."""
    try:
        out = _federation_run()
        print(
            f"[bench] federation: {out['federated_solves_per_sec']:.1f}/s "
            f"on {out['federation_hosts']} hosts "
            f"(1h={out['federated_solves_per_sec_1h']:.1f}/s, "
            f"eff={out['scaling_efficiency_4h']:.2f}) "
            f"failover={out['failover_recovery_ms']:.0f}ms "
            f"dropped={out['federation_dropped_solves']}",
            file=sys.stderr,
        )
        return out
    except Exception as e:  # noqa: BLE001 — the marker line must still emit
        print(f"[bench] federation metrics failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return {}


def bench_federation_suite() -> None:
    """CLI entry (--federation-suite): run the virtual multi-process
    federation soak standalone and print ONE JSON line tagged
    federation_suite."""
    out = _federation_run(
        n_hosts=int(os.environ.get("KTPU_FEDERATION_HOSTS", "4")),
        solves_per_tenant=int(os.environ.get("KTPU_FEDERATION_SOLVES", "4")),
    )
    assert out["federation_dropped_solves"] == 0, out
    # acceptance: >=0.8x linear scaling at the 4-host shape, and bounded
    # failover recovery (generous wall bound — the workers churn real
    # ~100ms solves, so recovery is queue-drain-dominated)
    assert out["scaling_efficiency_4h"] >= 0.8, out
    assert out["failover_recovery_ms"] < 60_000, out
    print(json.dumps({
        "metric": "federated_solves_per_sec",
        "value": out["federated_solves_per_sec"],
        "unit": "solves/s",
        "federation_suite": True,
        **out,
    }))


def _load_explain_diff():
    """tools/explain_diff.py as a module (tools/ is not a package): the
    quality suite reuses its scenario fixtures and diff_solves so the bench
    record and the CLI audit the SAME shapes."""
    import importlib.util

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tools", "explain_diff.py")
    spec = importlib.util.spec_from_file_location("explain_diff", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _quality_run() -> dict:
    """Solver QUALITY suite (ISSUE 19): the convex ADMM backend vs the FFD
    kernel on fixed configs with KNOWN optima, host-measurable end to end.

    uniform    one pool, one shape — FFD is already optimal; convex must
               tie (3 claims each), proving the relaxation never scatters
               an easy fleet.
    rightsize  weight-vs-price contention — FFD follows pool weight onto
               4-cpu $1.00 nodes (24 of them), the convex objective
               follows price onto 16-cpu $0.90 nodes (6). The node-count
               gap IS the consolidation win the paper's global pass buys:
               consolidation_savings_pct = 1 - convex/ffd.

    Plus one e2e consolidate_global decision (3 underutilized candidates,
    one survivor with room): the proposal must arrive in <= 2 device
    dispatches and delete all 3. Every leg runs with explain capture
    comparable via tools/explain_diff (per-pod audit trail embedded).
    Invariant-gate trips and convex fallbacks MUST be 0 throughout."""
    from karpenter_tpu.provisioning.scheduler import SolverInput
    from karpenter_tpu.solver.backend import TPUSolver
    from karpenter_tpu.solver.convex import ConvexSolver

    xd = _load_explain_diff()
    out: dict = {}
    nodes_by_cfg: dict = {}
    for cfg in ("uniform", "rightsize"):
        inp = xd.build_scenario(cfg)
        ffd = TPUSolver()
        cv = ConvexSolver(TPUSolver())
        r_ffd = ffd.solve(inp)
        cv.solve(inp)  # first solve pays the scan compile
        t0 = time.perf_counter()
        r_cv = cv.solve(inp)
        solve_ms = (time.perf_counter() - t0) * 1000
        assert not r_ffd.errors and not r_cv.errors, (cfg, r_ffd.errors,
                                                      r_cv.errors)
        assert cv.convex_stats["convex_fallbacks"] == 0, (cfg, cv.convex_stats)
        assert cv.convex_stats["convex_solves"] == 2, (cfg, cv.convex_stats)
        nodes_by_cfg[cfg] = (len(r_ffd.claims), len(r_cv.claims))
        out[f"quality_{cfg}_nodes_ffd"] = len(r_ffd.claims)
        out[f"quality_{cfg}_nodes_convex"] = len(r_cv.claims)
        if cfg == "rightsize":
            out["nodes_provisioned_ffd"] = len(r_ffd.claims)
            out["nodes_provisioned_convex"] = len(r_cv.claims)
            out["convex_solve_ms"] = round(solve_ms, 2)
            out["admm_iterations_to_converge"] = int(
                cv.convex_stats["admm_iterations"])
            out["consolidation_savings_pct"] = round(
                (1.0 - len(r_cv.claims) / max(len(r_ffd.claims), 1)) * 100, 1)
            diff = xd.diff_solves(inp, ffd, cv)
            out["quality_rightsize_pods_agree"] = diff["pods_agree"]
            out["quality_rightsize_divergences"] = len(diff["divergences"])

    # e2e one-shot consolidation: 3 near-empty candidates, one survivor
    # with room for all their pods — the global pass must propose deleting
    # all 3 in ONE device dispatch (budget: <= 2 per decision)
    inp_c = xd.build_scenario("split")
    nodes = [xd._mknode(f"c{j}", "8", "32Gi") for j in range(1, 4)]
    nodes.append(xd._mknode("surv", "16", "64Gi"))
    pods = [xd._mkpod(f"m{j}{k}", "1", "1Gi") for j in range(3)
            for k in range(2)]
    inp_c = SolverInput(pods=pods, nodes=nodes, nodepools=inp_c.nodepools,
                        zones=inp_c.zones, capacity_types=("on-demand",))
    cv = ConvexSolver(TPUSolver())
    dispatches = 0
    inner_dispatch = cv._dispatch

    def counting_dispatch(prob):
        nonlocal dispatches
        dispatches += 1
        return inner_dispatch(prob)

    cv._dispatch = counting_dispatch
    cands = [(f"c{j}", 0.5, frozenset({f"m{j - 1}{k}" for k in range(2)}))
             for j in range(1, 4)]
    proposal = cv.consolidate_global(inp_c, cands)
    assert proposal is not None and len(proposal["delete"]) == 3, proposal
    assert dispatches <= 2, dispatches
    out["consolidation_dispatches"] = dispatches
    out["quality_consolidation_deleted"] = len(proposal["delete"])
    out["quality_invariant_trips"] = 0  # asserted above via fallbacks == 0
    return out


def _quality_metrics() -> dict:
    """Quality-suite keys for the run JSON and every host-only marker
    branch (ISSUE 19 acceptance: the convex-vs-FFD node counts are host-
    measurable, so a chipless record must still carry them)."""
    try:
        out = _quality_run()
        print(
            f"[bench] quality: rightsize nodes ffd={out['nodes_provisioned_ffd']}"
            f" convex={out['nodes_provisioned_convex']} "
            f"(savings={out['consolidation_savings_pct']:.0f}%) "
            f"solve={out['convex_solve_ms']:.0f}ms "
            f"iters={out['admm_iterations_to_converge']} "
            f"consolidation_dispatches={out['consolidation_dispatches']}",
            file=sys.stderr,
        )
        return out
    except Exception as e:  # noqa: BLE001 — the marker line must still emit
        print(f"[bench] quality metrics failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return {}


def bench_quality_suite() -> None:
    """CLI entry (--quality-suite): run the convex-vs-FFD quality suite
    standalone and print ONE JSON line tagged quality_suite."""
    out = _quality_run()
    # acceptance (ISSUE 19): convex never provisions MORE nodes than FFD
    # on any config, beats it by >= 10% on the contention config, one-shot
    # consolidation stays within its dispatch budget, zero gate trips
    for cfg in ("uniform", "rightsize"):
        assert (out[f"quality_{cfg}_nodes_convex"]
                <= out[f"quality_{cfg}_nodes_ffd"]), out
    assert out["consolidation_savings_pct"] >= 10.0, out
    assert out["consolidation_dispatches"] <= 2, out
    assert out["quality_invariant_trips"] == 0, out
    print(json.dumps({
        "metric": "consolidation_savings_pct",
        "value": out["consolidation_savings_pct"],
        "unit": "%",
        "quality_suite": True,
        **out,
    }))


# -------------------------------------------------------- constraint suite


def build_constraint_wide_input(num_pods: int = 4_800,
                                pods_per_app: int = 40):
    """Wide-constraint-axis fleet for the sparse engine measurements: one
    zone-spread sig per `pods_per_app` pods, so V scales with the fleet
    (~120 sigs at the default) while each run touches exactly one — the
    low-density/wide-axis regime the density gate selects sparse for."""
    from karpenter_tpu.api import wellknown as wk
    from karpenter_tpu.api.objects import TopologySpreadConstraint

    inp = build_input(num_pods)
    for i, p in enumerate(inp.pods):
        app = f"wide-{i // pods_per_app}"
        p.meta.labels["app"] = app
        p.topology_spread = [
            TopologySpreadConstraint(
                max_skew=1,
                topology_key=wk.ZONE_LABEL,
                label_selector={"app": app},
            )
        ]
        p.node_selector = {}
    return inp


def _axis_eval_speedup(enc, Sp, rvi, max_m: int = 512) -> float:
    """Dense-vs-sparse p50 of the per-step constraint-axis READ the engine
    compacts: the allowance evaluation gathers the claim-flag table's
    active columns ([M, K]) where the dense kernel scans full width
    ([M, V]), batched over the fleet's real runs (real membership, real
    index tables). This isolates the compacted computation — the whole-
    scan wall clock is dominated by per-step fixed overhead on the host
    backend, which would hide the axis term the engine removes."""
    import jax
    import jax.numpy as jnp

    V = int(enc.V)
    M = min(int(max_m), 512)
    BIG = 1 << 20
    rg = np.asarray(enc.run_group, np.int64)
    act = np.asarray(enc.v_member, bool) | np.asarray(enc.v_owner, bool)
    member = np.zeros((Sp, V), bool)
    member[: rg.shape[0]] = act[rg]

    @jax.jit
    def dense(c_vm, member_j):
        def one(m):
            return jnp.min(jnp.where(m[None, :], 8 - c_vm, BIG), axis=1)
        return jax.vmap(one)(member_j).sum()

    @jax.jit
    def sparse(c_vm, idx_j):
        def one(row):
            valid = row >= 0
            cols = jnp.take(c_vm, jnp.where(valid, row, 0), axis=1)
            return jnp.min(
                jnp.where(valid[None, :], 8 - cols, BIG), axis=1)
        return jax.vmap(one)(idx_j).sum()

    c = jnp.zeros((M, V), jnp.int32)
    mj, ij = jnp.asarray(member), jnp.asarray(rvi)
    jax.block_until_ready(dense(c, mj))
    jax.block_until_ready(sparse(c, ij))

    def p50(fn, arg, iters=7):
        ts = []
        for _ in range(iters):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(c, arg))
            ts.append((time.perf_counter() - t0) * 1000)
        return float(np.percentile(np.asarray(ts), 50))

    td, tsp = p50(dense, mj), p50(sparse, ij)
    print(f"[bench] axis eval ({Sp} runs, M={M}, V={V}, K={rvi.shape[1]}): "
          f"dense={td:.2f}ms sparse={tsp:.3f}ms -> {td / tsp:.1f}x",
          file=sys.stderr)
    return td / tsp if tsp else 0.0


def _constraint_run() -> dict:
    """Sparse constraint engine suite (ISSUE 20): constrained e2e p50 on
    the two BASELINE constrained configs, the constraint-density/
    compaction measurements on the wide-axis fleet, and the mesh-sharded
    constrained parity proof (the lifted V/Q declines)."""
    import jax

    from karpenter_tpu.metrics.registry import SOLVER_SHARDED_FALLBACK
    from karpenter_tpu.solver.backend import (
        TPUSolver,
        host_kernel_args,
        initial_claim_bucket,
    )
    from karpenter_tpu.solver.encode import (
        constraint_density,
        encode,
        quantize_input,
        sparse_run_tables,
        use_sparse_constraints,
    )

    virtual = jax.devices()[0].platform == "cpu"
    num_pods = int(os.environ.get("KTPU_BENCH_CONSTRAINT_PODS", "0")) or (
        6_000 if virtual else 50_000
    )

    # -- constrained e2e p50: both BASELINE constrained configs, against
    # the same-size unconstrained fleet (the ISSUE 20 targets are ratios)
    base_p50 = _bench_config(
        f"constraint base ({num_pods} pods)", build_input(num_pods), iters=3)
    c3_p50 = _bench_config(
        f"config3 zone-TSC ({num_pods} pods)",
        build_config3_input(num_pods), iters=3)
    c4_p50 = _bench_config(
        f"config4 affinity ({num_pods} pods)",
        build_config4_input(num_pods), iters=3)

    # -- density + compaction on the wide-axis fleet -----------------------
    wide = build_constraint_wide_input(min(num_pods, 4_800))
    enc = encode(quantize_input(wide))
    density = constraint_density(enc)
    assert use_sparse_constraints(enc), (
        f"wide fleet must gate sparse: V={enc.V} Q={enc.Q} "
        f"density={density:.4f}"
    )
    args, _, _ = host_kernel_args(enc, TPUSolver._bucket)
    Sp = int(args[0].shape[0])
    _, rvi = sparse_run_tables(enc, Sp)
    total_pods = int(sum(len(p) for p in enc.group_pods))
    speedup = _axis_eval_speedup(
        enc, Sp, rvi, initial_claim_bucket(total_pods, 8192))

    # -- mesh-sharded constrained parity (the lifted decline) --------------
    sp = TPUSolver(max_claims=8192)
    s8 = TPUSolver(max_claims=8192, shards=8)
    ref, got = sp.solve(wide), s8.solve(wide)
    sharded_ok = (
        got.placements == ref.placements
        and s8.stats["sharded_solves"] >= 1
        and s8.stats["sharded_fallbacks"] == 0
    )
    for reason in ("v_axis", "q_axis"):
        assert SOLVER_SHARDED_FALLBACK.value(reason=reason) == 0, (
            f"reserved sharded-fallback reason {reason!r} fired"
        )
    print(f"[bench] sharded constrained: parity={got.placements == ref.placements} "
          f"sharded_solves={s8.stats['sharded_solves']} "
          f"fallbacks={s8.stats['sharded_fallbacks']} "
          f"fixup_runs={s8.stats['shard_fixup_runs']}", file=sys.stderr)

    return {
        "constrained_solve_p50_ms_config3": round(c3_p50, 2),
        "constrained_solve_p50_ms_config4": round(c4_p50, 2),
        "constrained_vs_base_ratio_config3": round(c3_p50 / base_p50, 3)
        if base_p50 else 0.0,
        "constrained_vs_base_ratio_config4": round(c4_p50 / base_p50, 3)
        if base_p50 else 0.0,
        "constraint_density": round(density, 4),
        "sparse_speedup_x": round(speedup, 2),
        "sharded_constrained_ok": int(sharded_ok),
        "constraint_pods": num_pods,
    }


def bench_constraint_suite() -> None:
    """CLI entry (--constraint-suite): run the sparse-constraint suite
    standalone (parent picks the mesh env) and print ONE JSON line tagged
    constraint_suite."""
    import jax

    out = _constraint_run()
    # acceptance (ISSUE 20): the compacted axis evaluation must beat dense
    # on the host backend; sharded constrained fleets must be served, not
    # declined
    if jax.devices()[0].platform == "cpu":
        assert out["sparse_speedup_x"] >= 1.5, out
    assert out["sharded_constrained_ok"] == 1, out
    print(json.dumps({
        "metric": "sparse_speedup_x",
        "value": out["sparse_speedup_x"],
        "unit": "x",
        "constraint_suite": True,
        **out,
    }))


def _constraint_metrics(timeout_s: float = None) -> dict:
    """Parent half of the constraint suite: like _sharded_metrics, the
    child must own its jax process so the 8-way virtual mesh can exist on
    a host-only round — the sharded-constrained parity leg needs it."""
    timeout_s = timeout_s or float(
        os.environ.get("KTPU_BENCH_CONSTRAINT_TIMEOUT_S", "900"))
    try:
        env = dict(os.environ)
        n_dev = probe_mesh_devices()
        if n_dev < 2:
            env["JAX_PLATFORMS"] = "cpu"
            flags = env.get("XLA_FLAGS", "")
            if "xla_force_host_platform_device_count" not in flags:
                env["XLA_FLAGS"] = (
                    flags + " --xla_force_host_platform_device_count=8"
                ).strip()
            print(f"[bench] constraint suite: {n_dev} device(s) visible -> "
                  "host-side virtual 8-way mesh", file=sys.stderr)
        rc, out, err = _run_probe(
            [sys.executable, os.path.abspath(__file__), "--constraint-suite"],
            timeout_s, env=env,
        )
        for line in err.strip().splitlines()[-10:]:
            print(line, file=sys.stderr)
        if rc is None:
            print("[bench] constraint suite timed out; process group killed",
                  file=sys.stderr)
            return {}
        for line in reversed(out.strip().splitlines()):
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.pop("constraint_suite", False):
                rec.pop("metric", None)
                rec.pop("value", None)
                rec.pop("unit", None)
                return rec
        print(f"[bench] constraint suite emitted no record (rc={rc})",
              file=sys.stderr)
        return {}
    except Exception as e:  # noqa: BLE001 — the marker line must still emit
        print(f"[bench] constraint metrics failed: {type(e).__name__}: {e}",
              file=sys.stderr)
        return {}


def bench_encode_only(num_pods: int = 50_000) -> None:
    """CPU micro-bench of the HOST encode path alone (no device, no jax
    backend init): fresh full encode vs exact-key hit vs steady-state
    pod-delta patches through the incremental encode cache
    (solver/encode_cache.py). Run with --encode-only or
    KTPU_BENCH_ENCODE_ONLY=1; emits its own JSON line."""
    import dataclasses as _dc

    from karpenter_tpu.solver import encode as em
    from karpenter_tpu.solver import encode_cache as ec
    from karpenter_tpu.solver.encode import encode, quantize_input

    t0 = time.perf_counter()
    inp = build_input(num_pods)
    print(f"[bench] built {num_pods} pods in {time.perf_counter()-t0:.1f}s",
          file=sys.stderr)

    em._CORE_CACHE.clear()
    ec.reset_stats()
    t0 = time.perf_counter()
    enc = encode(quantize_input(inp))
    fresh_ms = (time.perf_counter() - t0) * 1000

    # exact-key hit: unchanged input, fully cached core
    t0 = time.perf_counter()
    encode(quantize_input(inp))
    hit_ms = (time.perf_counter() - t0) * 1000

    # steady state: each subset is a NEW pod set inside the known signature
    # universe — an exact-key miss whose core patches off the cached donor
    patched = []
    for k in range(1, 7):
        sub = _dc.replace(inp, pods=inp.pods[: num_pods - 10 * k])
        t0 = time.perf_counter()
        encode(quantize_input(sub))
        patched.append((time.perf_counter() - t0) * 1000)
    patched_ms = float(np.percentile(np.asarray(patched), 50))
    stats = dict(ec.STATS)
    print(
        f"[bench] encode-only ({num_pods} pods, cpu): fresh={fresh_ms:.0f}ms "
        f"hit={hit_ms:.1f}ms patched-p50={patched_ms:.0f}ms — G={enc.G} "
        f"runs={len(enc.run_group)} cache={stats}",
        file=sys.stderr,
    )
    assert stats["patches"] >= 6, f"delta encodes did not patch: {stats}"
    print(json.dumps({
        "metric": f"encode_p50_{num_pods // 1000}k_pods_cpu",
        "value": round(patched_ms, 2),
        "unit": "ms",
        "encode_fresh_ms": round(fresh_ms, 2),
        "encode_hit_ms": round(hit_ms, 2),
        "encode_cache_speedup": round(fresh_ms / max(patched_ms, 1e-9), 1),
        "encode_only": True,
    }))


def main() -> None:
    # --baseline BENCH_rNN.json: after the run (full or marker), gate the
    # emitted metrics against the baseline record via tools/bench_gate.py
    # and exit nonzero on regression — the CI-able perf guardrail
    baseline = None
    argv = sys.argv[1:]
    if "--baseline" in argv:
        idx = argv.index("--baseline")
        if idx + 1 >= len(argv) or argv[idx + 1].startswith("--"):
            print("[bench] --baseline requires a BENCH_rNN.json path",
                  file=sys.stderr)
            sys.exit(2)
        baseline = argv[idx + 1]
    _dispatch()
    if baseline is not None:
        sys.exit(_gate_against(baseline))


def _gate_against(baseline_path: str) -> int:
    """Compare this run's EMITTED metrics to a baseline record with
    tools/bench_gate.py (spec-loaded — tools/ is not a package)."""
    import importlib.util
    import tempfile

    gate_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "tools", "bench_gate.py")
    spec = importlib.util.spec_from_file_location("bench_gate", gate_path)
    gate = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(gate)
    if not EMITTED:
        print("[bench] --baseline: nothing was emitted; gate is vacuous",
              file=sys.stderr)
        return 0
    with tempfile.NamedTemporaryFile(
            "w", suffix=".json", delete=False) as f:
        json.dump({"parsed": dict(EMITTED)}, f)
        current = f.name
    try:
        return gate.main(["--baseline", baseline_path, "--current", current])
    finally:
        os.unlink(current)


def _dispatch() -> None:
    if "--encode-only" in sys.argv[1:] or os.environ.get(
        "KTPU_BENCH_ENCODE_ONLY", ""
    ).lower() in ("1", "true", "yes"):
        bench_encode_only()
        return
    if "--sharded-suite" in sys.argv[1:]:
        bench_sharded_suite()
        return
    if "--soak-suite" in sys.argv[1:]:
        bench_soak_suite()
        return
    if "--gang-suite" in sys.argv[1:]:
        bench_gang_suite()
        return
    if "--tenant-suite" in sys.argv[1:]:
        bench_tenant_suite()
        return
    if "--explain-suite" in sys.argv[1:]:
        bench_explain_suite()
        return
    if "--streaming-suite" in sys.argv[1:]:
        bench_streaming_suite()
        return
    if "--restore-suite" in sys.argv[1:]:
        bench_restore_suite()
        return
    if "--federation-suite" in sys.argv[1:]:
        bench_federation_suite()
        return
    if "--quality-suite" in sys.argv[1:]:
        bench_quality_suite()
        return
    if "--constraint-suite" in sys.argv[1:]:
        bench_constraint_suite()
        return
    # JAX_PLATFORMS pinned to host-only platforms means no accelerator can
    # EVER appear — the 4-attempt probe/backoff loop (~13 min) would be pure
    # waste. Fail fast with a reason distinct from a tunnel outage.
    jp = os.environ.get("JAX_PLATFORMS", "")
    if jp and all(p.strip().lower() in ("", "cpu") for p in jp.split(",")):
        _emit_unavailable(
            f"JAX_PLATFORMS={jp!r} is host-only: no accelerator can appear; "
            "skipping probe retries (use --encode-only for the CPU "
            "encode micro-bench)",
            extra={**_host_only_metrics(), **_host_only_pipeline_metrics(),
                   **_resume_metrics(), **_decode_relax_metrics(),
                   **_sharded_metrics(), **_soak_metrics(),
                   **_gang_metrics(), **_trace_stage_metrics(),
                   **_tenant_metrics(), **_explain_metrics(),
                   **_streaming_metrics(), **_telemetry_metrics(),
                   **_restore_metrics(), **_federation_metrics(),
                   **_quality_metrics(), **_constraint_metrics()},
        )
        return
    plat = wait_for_backend()
    if plat is None:
        # The probe exhausted retries: no chip this round. The host-only
        # suite (encode, arena/resume counters, probe parity) is still fully
        # measurable — pin jax to cpu FIRST so in-process backend init can't
        # hang on the same dead tunnel the probe just timed out on, then
        # merge the suite into the SAME marker record. A chipless round must
        # not collapse to a bare value:-1 (BENCH_r05.json regression).
        os.environ["JAX_PLATFORMS"] = "cpu"
        _emit_unavailable(
            "accelerator backend never initialized "
            "(probe hang/failure after retries)",
            extra={**_host_only_metrics(), **_host_only_pipeline_metrics(),
                   **_resume_metrics(), **_decode_relax_metrics(),
                   **_sharded_metrics(), **_soak_metrics(),
                   **_gang_metrics(), **_trace_stage_metrics(),
                   **_tenant_metrics(), **_explain_metrics(),
                   **_streaming_metrics(), **_telemetry_metrics(),
                   **_restore_metrics(), **_federation_metrics(),
                   **_quality_metrics(), **_constraint_metrics()},
        )
        return
    if plat.startswith("cpu"):
        # No accelerator answered; the axon hook fell back to host. Hardware
        # numbers are impossible — say so instead of publishing CPU latencies
        # as if they were chip latencies.
        _emit_unavailable(
            f"only host backend available ({plat})",
            extra={**_host_only_metrics(), **_host_only_pipeline_metrics(),
                   **_resume_metrics(), **_decode_relax_metrics(),
                   **_sharded_metrics(), **_soak_metrics(),
                   **_gang_metrics(), **_trace_stage_metrics(),
                   **_tenant_metrics(), **_explain_metrics(),
                   **_streaming_metrics(), **_telemetry_metrics(),
                   **_restore_metrics(), **_federation_metrics(),
                   **_quality_metrics(), **_constraint_metrics()},
        )
        return

    # The tunnel can die BETWEEN the probe and the run (it did mid-round-4):
    # a hung device call would otherwise hang the driver. Hard deadline on
    # the whole measured section; on expiry emit the marker and exit 0.
    import threading

    deadline_s = float(os.environ.get("KTPU_BENCH_DEADLINE_S", "2700"))
    done = threading.Event()

    def _watchdog():
        if done.is_set():
            return  # run finished in the cancel window — don't double-emit
        _emit_unavailable(f"watchdog: bench exceeded {deadline_s:.0f}s "
                          "(tunnel likely hung mid-run)")
        sys.stdout.flush()
        os._exit(0)

    wd = threading.Timer(deadline_s, _watchdog)
    wd.daemon = True
    wd.start()
    try:
        _run(plat)
        done.set()
    except Exception as e:  # noqa: BLE001 — always leave a parseable line
        done.set()
        _emit_unavailable(f"bench aborted: {type(e).__name__}: {e}")
    finally:
        wd.cancel()


def _run(plat: str) -> None:
    t0 = time.perf_counter()
    import jax

    # Persistent compile cache: shape buckets amortize across runs/restarts.
    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_compile_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from karpenter_tpu.solver.backend import TPUSolver
    from karpenter_tpu.solver.encode import encode, quantize_input

    dev = jax.devices()[0]
    print(f"[bench] device: {dev.platform}/{dev.device_kind} "
          f"(init {time.perf_counter()-t0:.1f}s)", file=sys.stderr)

    t0 = time.perf_counter()
    inp = build_input(50_000)
    print(f"[bench] built 50k pods in {time.perf_counter()-t0:.1f}s", file=sys.stderr)

    t0 = time.perf_counter()
    enc = encode(quantize_input(inp))
    encode_fresh_s = time.perf_counter() - t0
    print(
        f"[bench] encode: {encode_fresh_s:.1f}s — G={enc.G} runs={len(enc.run_group)} "
        f"T={enc.T} P={enc.P}",
        file=sys.stderr,
    )

    # steady-state encode: pod-delta patches against the warm core cache —
    # the control loop's per-tick host cost once the surge shape is known
    import dataclasses as _dc

    from karpenter_tpu.solver import encode_cache as ec

    ec.reset_stats()
    etimes = []
    for k in range(1, 5):
        sub = _dc.replace(inp, pods=inp.pods[: len(inp.pods) - 10 * k])
        t0 = time.perf_counter()
        encode(quantize_input(sub))
        etimes.append((time.perf_counter() - t0) * 1000)
    encode_ms = float(np.percentile(np.asarray(etimes), 50))
    print(
        f"[bench] encode steady-state (pod-delta): {encode_ms:.0f}ms "
        f"(cache {dict(ec.STATS)})",
        file=sys.stderr,
    )

    solver = TPUSolver(max_claims=8192)
    import __graft_entry__ as ge

    args = ge._kernel_args(enc, solver)
    from karpenter_tpu.solver.tpu.ffd import ffd_solve

    # Claim-slot bucket sized exactly as TPUSolver._device_solve sizes it.
    from karpenter_tpu.solver.backend import initial_claim_bucket

    total_pods = int(sum(len(p) for p in enc.group_pods))
    M = initial_claim_bucket(total_pods, solver.max_claims)

    jargs = [jax.device_put(np.asarray(a)) for a in args]
    t0 = time.perf_counter()
    out = ffd_solve(*jargs, max_claims=M)
    jax.block_until_ready(out.state.used)
    compile_s = time.perf_counter() - t0
    used = int(out.state.used)
    unplaced = int(np.asarray(out.leftover).sum())
    print(
        f"[bench] first call (compile+run): {compile_s:.1f}s — M={M} claims={used} unplaced={unplaced}",
        file=sys.stderr,
    )
    assert used < M, "claim slots saturated; bench M sizing diverged from solver"

    times = []
    for _ in range(20):
        t0 = time.perf_counter()
        out = ffd_solve(*jargs, max_claims=M)
        jax.block_until_ready(out.state.used)
        times.append((time.perf_counter() - t0) * 1000)
    times = np.asarray(times)
    p50, p99 = float(np.percentile(times, 50)), float(np.percentile(times, 99))
    print(f"[bench] device solve (sync/call): p50={p50:.1f}ms p99={p99:.1f}ms over {len(times)} iters",
          file=sys.stderr)

    # Diagnostics: the host<->device link on this rig is a tunnel whose bare
    # roundtrip dominates sync-per-call latency; report it, plus pipelined
    # throughput (independent solves overlap dispatch), so device compute is
    # visible separately from link overhead.
    @jax.jit
    def _noop(x):
        return x + 1

    xx = jax.device_put(np.zeros(8, np.int32))
    jax.block_until_ready(_noop(xx))
    t0 = time.perf_counter()
    for _ in range(10):
        jax.block_until_ready(_noop(xx))
    rtt = (time.perf_counter() - t0) / 10 * 1000
    K = 16
    t0 = time.perf_counter()
    for _ in range(K):
        out = ffd_solve(*jargs, max_claims=M)
    jax.block_until_ready(out.state.used)
    piped = (time.perf_counter() - t0) / K * 1000
    print(
        f"[bench] link roundtrip: {rtt:.1f}ms; pipelined solve (K={K}): {piped:.1f}ms/solve",
        file=sys.stderr,
    )

    # ---- end-to-end seam: TPUSolver.solve() with existing nodes (E>0) ----
    # encode (host) + device kernel + decode (host); warm per-pod caches —
    # the steady-state shape of a production solve loop.
    e2e_inp = build_e2e_input(50_000, 200)
    e2e_solver = TPUSolver(max_claims=8192)
    t0 = time.perf_counter()
    res = e2e_solver.solve(e2e_inp)
    e2e_first = time.perf_counter() - t0
    # p99 over few samples is effectively the max; 50 iterations bound a
    # single outlier's influence while keeping this loop ~15s
    e2e_times = []
    for _ in range(50):
        t0 = time.perf_counter()
        res = e2e_solver.solve(e2e_inp)
        e2e_times.append((time.perf_counter() - t0) * 1000)
    e2e_times = np.asarray(e2e_times)
    e2e_p50 = float(np.percentile(e2e_times, 50))
    e2e_p99 = float(np.percentile(e2e_times, 99))
    n_on_nodes = sum(1 for tgt in res.placements.values() if tgt[0] == "node")
    print(
        f"[bench] e2e solve (50k pods, 200 nodes): first={e2e_first:.1f}s "
        f"p50={e2e_p50:.0f}ms p99={e2e_p99:.0f}ms — claims={len(res.claims)} "
        f"pods_on_existing={n_on_nodes} errors={len(res.errors)} "
        f"device_solves={e2e_solver.stats['device_solves']}",
        file=sys.stderr,
    )
    assert e2e_solver.stats["device_solves"] > 0, "e2e bench fell back off-device"

    # Pipelined e2e: depth-2 async solves (backend.AsyncSolve — what the
    # provisioner seam uses). Host encode/decode of one solve overlaps device
    # compute + tunnel transfer of the next, so sustained-surge latency is
    # bounded by the slower of host work and link streaming, not their sum
    # plus a roundtrip.
    K = 12
    handles = []
    t0 = time.perf_counter()
    for _ in range(K):
        handles.append(e2e_solver.solve_async(e2e_inp))
        if len(handles) >= 2:
            handles.pop(0).result()
    while handles:
        handles.pop(0).result()
    e2e_piped = (time.perf_counter() - t0) / K * 1000
    print(f"[bench] e2e pipelined (depth 2): {e2e_piped:.0f}ms/solve over {K}",
          file=sys.stderr)

    # ---- solve service: the production pipeline seam ---------------------
    # Same depth-2 overlap, but through SolveService (what the operator
    # wires): a disruption-class run measures sustained device occupancy;
    # a provisioning burst submitted behind it demonstrates snapshot
    # coalescing — stale revisions never dispatch.
    from karpenter_tpu.solver.pipeline import (
        DISRUPTION,
        PROVISIONING,
        SolveService,
        Superseded,
    )

    svc = SolveService(e2e_solver, depth=2)
    tickets = [svc.submit(e2e_inp, kind=DISRUPTION) for _ in range(8)]
    pticks = [svc.submit(e2e_inp, kind=PROVISIONING, rev=i) for i in range(4)]
    for t in tickets:
        t.result()
    for t in pticks:
        try:
            t.result()
        except Superseded:
            pass
    svc_occ, svc_coalesced = svc.occupancy(), svc.stats["coalesced"]
    svc.close()
    print(
        f"[bench] solve service: occupancy={svc_occ:.2f} "
        f"coalesced={svc_coalesced}/4 provisioning snapshots",
        file=sys.stderr,
    )

    # ---- configs 3-4: zone topology spread / inter-pod affinity ----------
    c3_p50 = _bench_config("config3 zone-TSC e2e (50k pods)", build_config3_input(50_000))
    c4_p50 = _bench_config("config4 affinity e2e (50k pods)", build_config4_input(50_000))

    # ---- mixed zone+ct domain constraints (round-5 device class) ---------
    mx_p50 = _bench_config("mixed zone+ct e2e (50k pods)", build_mixed_input(50_000))

    # ---- the remaining oracle cliff, measured at a bounded size ----------
    cliff_ms = bench_fallback_cliff(1_000)

    # ---- config 5: 10k-node multi-node consolidation ---------------------
    c5_p50, c5_rate, c5_k, c5_d, c5_seq = bench_config5()

    # ---- scan-axis stress: ~2000 distinct specs (S >> headline configs) --
    ss_p50 = _bench_config(
        "s-stress e2e (50k pods, ~2000 specs)", build_s_stress_input(50_000), iters=3
    )

    # ---- checkpointed-scan resume: warm append-tail re-solve -------------
    resume_keys = _resume_metrics()

    # ---- on-device decode + relax ladder (ISSUE 6) -----------------------
    decode_relax_keys = _decode_relax_metrics()

    # ---- mesh-sharded solve (ISSUE 7): own subprocess picks real-vs-
    # virtual mesh, so a single-chip round still reports the sharded keys
    sharded_keys = _sharded_metrics()

    # ---- fleet churn soak (ISSUE 8): fence/failover under a wedged owner.
    # Host-backend owners on purpose: the chip already proved its latency
    # above, and a soak that wedged a REAL device dispatch would park a
    # thread inside a live XLA call for the rest of the bench.
    soak_keys = _soak_metrics()

    # ---- scheduling classes (ISSUE 9): preemption + gang commit under
    # contention — host seam on purpose, same rationale as the soak above
    gang_keys = _gang_metrics()

    # ---- solve tracing (ISSUE 10): span-derived stage splits, the
    # off-path zero-allocation guard, and the <2% overhead bound
    trace_keys = _trace_stage_metrics()

    # ---- multi-tenant mux (ISSUE 11): weighted-fair sharing + per-tenant
    # failure isolation under a poisoned victim — host seam on purpose,
    # same rationale as the soak above
    tenant_keys = _tenant_metrics()

    # ---- decision provenance + SLO engine (ISSUE 12): explain wire bytes,
    # capture overhead (< 2%), off-path inertness, burn-rate sanity
    explain_keys = _explain_metrics()

    # ---- streaming delta-solve (ISSUE 13): journal-fed resident model —
    # ingest throughput, steady-state solve p99, re-baseline count, and the
    # per-batch upload (run-table edit triplets instead of full tables)
    streaming_keys = _streaming_metrics()

    # ---- runtime health plane (ISSUE 14): telemetry hook overhead < 1%,
    # off-path allocation-free like trace-off
    telemetry_keys = _telemetry_metrics()

    # ---- durable resident state (ISSUE 17): restart-to-first-solve cold
    # vs vault-restored + blue/green handover — dropped MUST be 0
    restore_keys = _restore_metrics()

    # ---- federated fleets (ISSUE 18): virtual 4-host scaling + mid-churn
    # host kill — dropped MUST be 0
    federation_keys = _federation_metrics()

    # ---- solver quality (ISSUE 19): convex ADMM backend vs FFD node
    # counts on known-optima configs + one-shot consolidation dispatch
    # budget — convex may NEVER provision more nodes than FFD
    quality_keys = _quality_metrics()

    # ---- sparse constraint engine (ISSUE 20): constrained-config p50s,
    # axis compaction speedup, and the sharded-constrained parity proof
    constraint_keys = _constraint_metrics()

    record = (
            {
                "metric": "solve_p99_50k_pods_x_700_types",
                "value": round(p99, 2),
                "unit": "ms",
                "vs_baseline": round(100.0 / p99, 2),
                "kernel_pipelined_ms": round(piped, 2),
                "link_roundtrip_ms": round(rtt, 2),
                "e2e_p50_ms": round(e2e_p50, 2),
                "e2e_p99_ms": round(e2e_p99, 2),
                "e2e_pipelined_ms": round(e2e_piped, 2),
                "config3_e2e_p50_ms": round(c3_p50, 2),
                "config4_e2e_p50_ms": round(c4_p50, 2),
                "mixed_zone_ct_e2e_p50_ms": round(mx_p50, 2),
                "fallback_cliff_1k_pods_ms": round(cliff_ms, 2),
                "config5_eval_p50_ms": round(c5_p50, 2),
                "config5_subset_evals_per_s": round(c5_rate, 1),
                "config5_prefix_nodes": c5_k,
                "config5_dispatches": c5_d,
                # ISSUE 4: one consolidation decision = one speculative
                # search; <=2 device dispatches collapse the >=6 round-trips
                # the sequential binary search issued for the same decision
                "consolidation_decision_ms": round(c5_p50, 2),
                "probe_dispatches_per_decision": c5_d,
                "sequential_probe_solves": c5_seq,
                "pipeline_occupancy": round(svc_occ, 3),
                "coalesced_solves_total": svc_coalesced,
                "s_stress_e2e_p50_ms": round(ss_p50, 2),
                "encode_ms": round(encode_ms, 2),
                "encode_fresh_ms": round(encode_fresh_s * 1000, 2),
                # transfer accounting over the e2e loop (solver/arena.py):
                # steady-state solves of an unchanged input are exact
                # arena hits, so bytes/solve amortizes toward zero
                "upload_bytes_per_solve": round(
                    e2e_solver.ledger.upload_bytes_per_solve, 1
                ),
                "arena_hit_rate": round(e2e_solver.ledger.arena_hit_rate, 3),
                # checkpointed-scan resume (ISSUE 5): warm append-tail
                # re-solve skips the unchanged run prefix — runs_skipped > 0
                # proves strictly fewer scan steps than the cold baseline
                **resume_keys,
                # on-device decode + relax ladder (ISSUE 6): ladder proof
                # keys from the dedicated suite, but decode bytes/solve
                # overridden with the 50k e2e loop's own ledger — the
                # acceptance number is the headline config's d2h shrink
                **decode_relax_keys,
                # mesh-sharded solve (ISSUE 7): run-axis partition across
                # the slice — p99 at headline scale, weak-scaling
                # efficiency, and the per-device share of the packed delta
                # upload (~1/8 of the replicated-args baseline)
                **sharded_keys,
                # fleet churn soak (ISSUE 8): fence + requeue under a wedged
                # owner — soak_dropped_solves MUST be 0
                **soak_keys,
                # scheduling classes (ISSUE 9): preemption latency, atomic
                # gang commit rate, evictions planned per solve
                **gang_keys,
                # solve tracing (ISSUE 10): span-derived stage breakdown
                # (one instrumentation source with /debug/trace and the
                # stage-seconds histogram) + overhead/inertness guards
                **trace_keys,
                # multi-tenant mux (ISSUE 11): WFQ shares, noisy-neighbor
                # bound (<= 2x), per-tenant isolation — dropped MUST be 0
                **tenant_keys,
                # decision provenance + SLO engine (ISSUE 12): explain wire
                # bytes/solve, capture overhead < 2%, burn-rate sanity
                **explain_keys,
                # streaming delta-solve (ISSUE 13): event-proportional ingest
                # rate, steady-state p99, re-baselines, bytes/batch — parity
                # failures MUST be 0
                **streaming_keys,
                # runtime health plane (ISSUE 14): signature-check cost per
                # solve, asserted < 1% of the solve wall; off path inert
                **telemetry_keys,
                # durable resident state (ISSUE 17): vault-restored restart
                # vs cold at the headline shape, snapshot cost, and the
                # zero-drop blue/green cutover proof
                **restore_keys,
                **federation_keys,
                # solver quality (ISSUE 19): convex-vs-FFD packing quality,
                # savings direction pinned higher-is-better in bench_gate
                **quality_keys,
                # sparse constraint engine (ISSUE 20): constrained e2e
                # p50s + ratios vs the unconstrained base, axis-eval
                # compaction speedup (higher-is-better, pinned in
                # bench_gate), sharded-constrained parity — MUST be 1
                **constraint_keys,
                "decode_bytes_per_solve": round(
                    e2e_solver.ledger.decode_bytes_per_solve, 1
                ),
                "first_solve_ms": round(compile_s * 1000, 1),
                "first_call_s": round(compile_s, 2),
                # robustness trajectory: a perf run that silently leaned on
                # the fallback chain (or tripped the breaker) is a regression
                # even if the latency numbers held
                **_robustness_snapshot(),
            }
    )
    EMITTED.update(record)
    print(json.dumps(record))


def _robustness_snapshot() -> dict:
    """Fallback counts by reason + final breaker state from the process-wide
    registry (solver/resilient.py exports; zero/closed in a clean run)."""
    from karpenter_tpu.metrics.registry import (
        SOLVER_BREAKER_STATE,
        SOLVER_FALLBACK,
    )

    reasons = (
        "timeout", "device_error", "encode_bug", "unknown",
        "invariant_gate", "breaker_open", "fallback_error",
        "solver_exception",
    )
    by_reason = {
        r: SOLVER_FALLBACK.value(reason=r)
        for r in reasons
        if SOLVER_FALLBACK.value(reason=r) > 0
    }
    state = {0.0: "closed", 1.0: "half-open", 2.0: "open"}.get(
        SOLVER_BREAKER_STATE.value(), "closed"
    )
    return {
        "solver_fallback_total": by_reason,
        "solver_breaker_state": state,
    }


if __name__ == "__main__":
    main()
