from .render import main

main()
