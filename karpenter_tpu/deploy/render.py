"""Deploy-artifact renderer: the Helm-chart/values analog (L7).

The reference ships `charts/karpenter` whose values.yaml materializes the
flag table into a Deployment's KARPENTER_* env plus the HA scaffolding
around it (2 replicas + leader election + PDB, service account, metrics
Service — charts/karpenter/values.yaml, templates/deployment.yaml:91-170).
Its CRD chart ships the API schemas (charts/karpenter-crd).

This framework's API server is the in-process store, so the CRD half lives
in `api/validation.py` (admission rules); this module renders the runtime
half: a values dict → Kubernetes manifests. The values→env mapping is
DERIVED from `operator/options.py` (same `_env_name`, same dataclass
fields), so the chart can never drift from the flag table — the round-trip
test parses the rendered env back through `options.parse` and asserts
identity (the property the reference maintains by hand via hack/docs).
"""

from __future__ import annotations

import copy
from dataclasses import fields
from typing import Any, Dict, List, Optional

from ..operator.options import Options, _env_name

# chart-surface defaults mirroring charts/karpenter/values.yaml (subset that
# is meaningful for this runtime: HA, probes, resources, settings)
DEFAULT_VALUES: Dict[str, Any] = {
    "nameOverride": "",
    "namespace": "karpenter",
    "image": "karpenter-tpu:latest",
    "imagePullPolicy": "IfNotPresent",
    "replicas": 2,  # HA: leader + standby (values.yaml "replicas: 2")
    "revisionHistoryLimit": 10,
    "podDisruptionBudget": {"maxUnavailable": 1},
    "additionalLabels": {},
    "podAnnotations": {},
    "serviceAccount": {"create": True, "name": "", "annotations": {}},
    "priorityClassName": "system-cluster-critical",
    "controller": {
        # reference controller footprint (Makefile:16-19)
        "resources": {
            "requests": {"cpu": "1", "memory": "1Gi"},
            "limits": {"cpu": "1", "memory": "1Gi"},
        },
        "env": [],  # extra raw env entries appended verbatim
    },
    # every key here must be an Options field (camelCase of the snake_case
    # name); rendered as KARPENTER_* env. Unlisted fields keep code defaults.
    "settings": {
        "batchIdleDurationS": 1.0,
        "batchMaxDurationS": 10.0,
        "featureGates": "",
        "preferencePolicy": "Respect",
        "leaderElect": True,
        "solverBackend": "tpu",
        "warmStart": True,
        # HA shared state: replicas contend the flock'd lease and the
        # takeover re-hydrates from the snapshot — both live on the shared
        # state volume mounted below (controllers/filelease.py)
        "snapshotPath": "/var/lib/karpenter/state.snap",
        "leasePath": "/var/lib/karpenter/leader.lease",
    },
    # Both replicas (spread across hosts) mount this ReadWriteMany volume.
    # The storage class MUST be named and RWX-capable — the render refuses
    # an empty name rather than silently falling back to the cluster default
    # StorageClass, which is commonly RWO-only (EBS/PD) and would leave both
    # replicas Pending. Set it to your cluster's RWX class (NFS/Filestore/
    # EFS/CephFS). To run without HA state, set stateVolume to null AND
    # clear settings.snapshotPath/leasePath (render enforces consistency).
    "stateVolume": {"storageClassName": "shared-rwx", "size": "1Gi"},
}

_OPTION_FIELDS = {f.name: f for f in fields(Options)}


def _camel(snake: str) -> str:
    head, *rest = snake.split("_")
    return head + "".join(w.capitalize() for w in rest)


_CAMEL_TO_SNAKE = {_camel(name): name for name in _OPTION_FIELDS}


def merge_values(overrides: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Deep-merge user overrides onto DEFAULT_VALUES (helm `-f` semantics)."""
    out = copy.deepcopy(DEFAULT_VALUES)

    def deep(dst: Dict[str, Any], src: Dict[str, Any]) -> None:
        for k, v in src.items():
            if isinstance(v, dict) and isinstance(dst.get(k), dict):
                deep(dst[k], v)
            else:
                dst[k] = v

    if overrides:
        deep(out, overrides)
    return out


def settings_env(settings: Dict[str, Any]) -> List[Dict[str, str]]:
    """values.settings → KARPENTER_* env entries, validated against Options.

    Unknown keys raise (the chart cannot silently carry dead flags — the
    reference regenerates its settings table from code for the same reason,
    website/.../reference/settings.md:11).
    """
    env = []
    for key in sorted(settings):
        snake = _CAMEL_TO_SNAKE.get(key)
        if snake is None:
            raise ValueError(
                f"values.settings.{key} does not match any option "
                f"(known: {sorted(_CAMEL_TO_SNAKE)})"
            )
        v = settings[key]
        if isinstance(v, bool):
            sv = "true" if v else "false"
        else:
            sv = str(v)
        env.append({"name": _env_name(snake), "value": sv})
    return env


def _meta(name: str, values: Dict[str, Any], extra: Optional[Dict[str, str]] = None):
    labels = {"app.kubernetes.io/name": name, **values["additionalLabels"]}
    m: Dict[str, Any] = {"name": name, "namespace": values["namespace"], "labels": labels}
    if extra:
        m["annotations"] = dict(extra)
    return m


def render(overrides: Optional[Dict[str, Any]] = None) -> List[Dict[str, Any]]:
    """values → [ServiceAccount, Service, PodDisruptionBudget, Deployment]."""
    v = merge_values(overrides)
    name = v["nameOverride"] or "karpenter-tpu"
    opts = Options()  # code defaults → ports for probes/service
    sa_name = v["serviceAccount"]["name"] or name
    out: List[Dict[str, Any]] = []
    if v["serviceAccount"]["create"]:
        out.append(
            {
                "apiVersion": "v1",
                "kind": "ServiceAccount",
                "metadata": _meta(sa_name, v, v["serviceAccount"]["annotations"] or None),
            }
        )
    out.append(
        {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": _meta(name, v),
            "spec": {
                "type": "ClusterIP",
                "selector": {"app.kubernetes.io/name": name},
                "ports": [
                    {"name": "http-metrics", "port": opts.metrics_port, "protocol": "TCP"}
                ],
            },
        }
    )
    out.append(
        {
            "apiVersion": "policy/v1",
            "kind": "PodDisruptionBudget",
            "metadata": _meta(name, v),
            "spec": {
                "maxUnavailable": v["podDisruptionBudget"]["maxUnavailable"],
                "selector": {"matchLabels": {"app.kubernetes.io/name": name}},
            },
        }
    )
    state_vol = v.get("stateVolume")
    if not state_vol and (
        v["settings"].get("leasePath") or v["settings"].get("snapshotPath")
    ):
        # each replica would get a container-LOCAL lease file -> both lead ->
        # duplicate capacity. Fail the render instead of shipping split-brain.
        raise ValueError(
            "stateVolume is disabled but settings.leasePath/snapshotPath are "
            "set: without the shared volume every replica leases against its "
            "own filesystem. Clear both settings or keep stateVolume."
        )
    if state_vol and not state_vol.get("storageClassName"):
        # RWX alone is not sufficient: the lease transport is flock-based
        # (controllers/filelease.py), so the class must also provide
        # CROSS-HOST-coherent advisory locking — NFSv4+/Filestore/EFS/CephFS
        # qualify; NFSv3 lockd setups and `nolock`/`nobrl` mounts grant
        # flock locally and would let two replicas lead.
        raise ValueError(
            "stateVolume.storageClassName must name an RWX-capable class "
            "with cross-host flock coherence (NFSv4+/Filestore/EFS/CephFS): "
            "falling back to the cluster default StorageClass (commonly "
            "RWO-only) would leave every replica Pending, and a class "
            "without coherent locking silently breaks the leader lease. "
            "Name your class, or disable stateVolume (and clear "
            "settings.leasePath/snapshotPath) to run without HA state."
        )
    if state_vol:
        # shared HA state: lease file + snapshot on one RWX volume — two
        # replicas on different hosts (the topology spread below) contend
        # the same flock'd lease and the takeover restores the same snapshot
        pvc = {
            "apiVersion": "v1",
            "kind": "PersistentVolumeClaim",
            "metadata": _meta(f"{name}-state", v),
            "spec": {
                "accessModes": ["ReadWriteMany"],
                "resources": {"requests": {"storage": state_vol["size"]}},
            },
        }
        if state_vol.get("storageClassName"):
            pvc["spec"]["storageClassName"] = state_vol["storageClassName"]
        out.append(pvc)
    env = settings_env(v["settings"]) + list(v["controller"]["env"])
    probe_port = opts.health_probe_port
    out.append(
        {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": _meta(name, v),
            "spec": {
                "replicas": v["replicas"],
                "revisionHistoryLimit": v["revisionHistoryLimit"],
                "strategy": {"rollingUpdate": {"maxUnavailable": 1}},
                "selector": {"matchLabels": {"app.kubernetes.io/name": name}},
                "template": {
                    "metadata": {
                        "labels": {"app.kubernetes.io/name": name},
                        "annotations": dict(v["podAnnotations"]),
                    },
                    "spec": {
                        "serviceAccountName": sa_name,
                        "priorityClassName": v["priorityClassName"],
                        "securityContext": {"runAsNonRoot": True},
                        # spread replicas across hosts: a co-located standby
                        # shares the leader's failure domain
                        "topologySpreadConstraints": [
                            {
                                "maxSkew": 1,
                                "topologyKey": "kubernetes.io/hostname",
                                "whenUnsatisfiable": "DoNotSchedule",
                                "labelSelector": {
                                    "matchLabels": {"app.kubernetes.io/name": name}
                                },
                            }
                        ],
                        "containers": [
                            {
                                "name": "controller",
                                "image": v["image"],
                                "imagePullPolicy": v["imagePullPolicy"],
                                "command": ["python", "-m", "karpenter_tpu.operator"],
                                "env": env,
                                "ports": [
                                    {
                                        "name": "http-metrics",
                                        "containerPort": opts.metrics_port,
                                    },
                                    {
                                        "name": "http-probe",
                                        "containerPort": probe_port,
                                    },
                                ],
                                "livenessProbe": {
                                    "httpGet": {"path": "/healthz", "port": probe_port},
                                    "initialDelaySeconds": 30,
                                    "timeoutSeconds": 30,
                                },
                                "readinessProbe": {
                                    "httpGet": {"path": "/readyz", "port": probe_port},
                                    "timeoutSeconds": 30,
                                },
                                "resources": v["controller"]["resources"],
                                **(
                                    {
                                        "volumeMounts": [
                                            {
                                                "name": "state",
                                                "mountPath": "/var/lib/karpenter",
                                            }
                                        ]
                                    }
                                    if state_vol
                                    else {}
                                ),
                            }
                        ],
                        **(
                            {
                                "volumes": [
                                    {
                                        "name": "state",
                                        "persistentVolumeClaim": {
                                            "claimName": f"{name}-state"
                                        },
                                    }
                                ]
                            }
                            if state_vol
                            else {}
                        ),
                    },
                },
            },
        }
    )
    return out


def render_yaml(overrides: Optional[Dict[str, Any]] = None) -> str:
    import yaml

    return "---\n".join(
        yaml.safe_dump(m, sort_keys=False, default_flow_style=False) for m in render(overrides)
    )


def crds_yaml() -> str:
    """The --crds artifact, ONE serialization shared by the CLI and the
    golden test (so the golden pins what actually ships)."""
    import yaml

    from ..api.validation import rules_document

    return "---\n".join(
        yaml.safe_dump(d, sort_keys=False) for d in rules_document()
    )


def main(argv: Optional[List[str]] = None) -> None:
    """`python -m karpenter_tpu.deploy [-f values.yaml]` — the `helm template`."""
    import argparse

    import yaml

    ap = argparse.ArgumentParser(prog="karpenter-tpu-deploy")
    ap.add_argument("-f", "--values", help="values YAML file with overrides")
    ap.add_argument(
        "--crds", action="store_true",
        help="emit the admission-rule documents (the CRD-chart analog) "
             "instead of the runtime manifests",
    )
    args = ap.parse_args(argv)
    if args.crds:
        print(crds_yaml())
        return
    overrides = None
    if args.values:
        with open(args.values) as f:
            overrides = yaml.safe_load(f) or {}
    print(render_yaml(overrides))
