"""Deterministic fault injection: seeded, scriptable failure plans.

The TPU seam adds a failure domain the reference never had — XLA runtime
errors, device OOM, compile stalls, garbage decodes — and the only way to
PROVE the resilience layer (solver/resilient.py, controllers/manager.py
backoff) is to make those failures happen on demand, hermetically and
reproducibly. This module is the chaos-test seam: a registry of named
injection sites wired into the production code paths, consulted on every
pass through the site, and a `FaultPlan` that scripts exactly what each
site does ("device dies for 3 solves then recovers") or fails with seeded
probability.

Sites wired into production code:

- ``solver.device_dispatch`` — TPUSolver._dispatch, before the kernel call
  (covers the initial dispatch AND overflow-retry redispatches).
- ``solver.decode``         — TPUSolver device-result decode, after fetch.
- ``solver.device_hang``    — TPUSolver dispatch path; wedge-class: a
  scripted `Wedge` BLOCKS the calling thread (a hung XLA dispatch, not a
  raised one) until the test releases it.
- ``solver.device_lost``    — TPUSolver dispatch path; raises `DeviceLost`
  (the runtime reported the device gone, unrecoverable by retry).
- ``solver.arena_corrupt``  — TPUSolver device-adopt path, before the arena
  residency is trusted; raises `ArenaCorrupt` (device buffers unusable —
  the arena must be invalidated and re-adopted).
- ``cloud.create``          — KwokCloud.create_fleet, before the launch.
- ``store.update``          — Store.update, before persistence.
- ``vault.write``           — SolverStateVault.snapshot_now, before the
  capture/write; a failure skips the snapshot (throttled WARN) and the
  next interval retries.
- ``vault.corrupt``         — SolverStateVault._read, before a candidate
  file is parsed; lets chaos tests reject restore candidates without
  hand-crafting broken bytes.

Sites on the solver dispatch path accept an optional `tag` so a fleet of
several solver instances can wedge ONE owner: `plan.wedge(site, tag="owner-0")`
fires only for the solver whose `fault_tag` is "owner-0"; an untagged script
fires for every caller of the site.

The check is a no-op module-level None test when no plan is active, so the
hot paths pay one attribute load in production.

Usage (tests):

    plan = FaultPlan(seed=7)
    plan.fail_n("solver.device_dispatch", 3, DeviceError("injected XLA err"))
    with active(plan):
        ...  # first 3 dispatches raise, then the device "recovers"
    assert plan.fired["solver.device_dispatch"] == 3

Outcomes in a script may be: an Exception instance (raised, re-instantiated
per fire so tracebacks never chain), an Exception class (instantiated and
raised), a callable (invoked — may raise or side-effect, e.g. advance a fake
clock to trip a deadline), or the string "ok" (explicit no-op).
"""

from __future__ import annotations

import random
import threading
from collections import defaultdict, deque
from contextlib import contextmanager
from typing import Callable, Dict, Optional

from .obs import trace as obstrace

SITES = (
    "solver.device_dispatch",
    "solver.decode",
    "solver.device_hang",
    "solver.device_lost",
    "solver.arena_corrupt",
    "cloud.create",
    "store.update",
    "vault.write",
    "vault.corrupt",
)


class FaultError(Exception):
    """Base class for injected faults."""


class DeviceError(FaultError):
    """A transient device/runtime failure (XLA error, OOM, dead tunnel)."""


class DeviceLost(DeviceError):
    """The runtime reported the device gone — retrying on it is hopeless."""


class ArenaCorrupt(DeviceError):
    """Device-resident arena buffers are unusable; residency must be
    invalidated and re-adopted before the next dispatch can trust them."""


class DecodeError(FaultError, ValueError):
    """A deterministic garbage-decode failure (classified as an encode bug)."""


class Wedge:
    """A wedge-class outcome: check() BLOCKS (outside the plan lock) until
    release()d, then proceeds normally — modelling a dispatch that HANGS
    rather than raises. Sticky: the same Wedge keeps blocking every check
    that draws it until released. Counters let tests assert how many
    threads actually hit the wedge."""

    def __init__(self, name: str = "wedge"):
        self.name = name
        self._released = threading.Event()
        self._lock = threading.Lock()
        self.blocked = 0  # threads that entered the wedge
        self.wedged = 0  # threads currently parked in it

    def __call__(self) -> None:
        with self._lock:
            self.blocked += 1
            self.wedged += 1
        try:
            self._released.wait()
        finally:
            with self._lock:
                self.wedged -= 1

    def release(self) -> None:
        """Un-hang: every parked thread (and all future checks) proceed."""
        self._released.set()

    def released(self) -> bool:
        return self._released.is_set()


class FaultPlan:
    """A seeded, deterministic schedule of outcomes per injection site.

    Per-site outcome resolution order on each check():
      1. the next scripted outcome, if the script is non-empty;
      2. the probabilistic rule (seeded RNG), if one is set;
      3. ok.
    """

    def __init__(self, seed: int = 0):
        self.seed = seed
        self._rng = random.Random(seed)
        # scripts/wedges are keyed by (site, tag); tag None is the untagged
        # (fires-for-everyone) slot, so existing call sites are unchanged
        self._scripts: Dict[tuple, deque] = defaultdict(deque)
        self._wedges: Dict[tuple, Wedge] = {}
        self._maybe: Dict[str, tuple] = {}  # site -> (p, exc)
        self._lock = threading.Lock()
        self.calls: Dict[str, int] = defaultdict(int)  # checks per site
        self.fired: Dict[str, int] = defaultdict(int)  # raises per site

    # -- scripting ----------------------------------------------------------

    def script(self, site: str, *outcomes, tag: Optional[str] = None) -> "FaultPlan":
        """Append explicit outcomes consumed one per check, in order. With
        `tag`, the outcomes fire only for checks carrying that tag (one
        solver instance in a fleet)."""
        self._scripts[(site, tag)].extend(outcomes)
        return self

    def fail_n(self, site: str, n: int, exc=None, tag: Optional[str] = None) -> "FaultPlan":
        """Site fails the next `n` checks, then recovers (script suffix)."""
        exc = exc if exc is not None else DeviceError(f"injected fault at {site}")
        return self.script(site, *([exc] * n), tag=tag)

    def wedge(self, site: str, tag: Optional[str] = None) -> Wedge:
        """Wedge the site: every check (matching `tag`, if given) BLOCKS
        until the returned Wedge is release()d. Sticky, not consumed —
        models a hung device, detected only by a liveness deadline."""
        w = Wedge(name=f"{site}@{tag}" if tag else site)
        with self._lock:
            self._wedges[(site, tag)] = w
        return w

    def maybe(self, site: str, p: float, exc=None) -> "FaultPlan":
        """Fail each UNSCRIPTED check with probability `p` (seeded RNG, so a
        given (seed, call sequence) always fires identically)."""
        exc = exc if exc is not None else DeviceError(f"injected fault at {site}")
        self._maybe[site] = (p, exc)
        return self

    # -- consumption --------------------------------------------------------

    def check(self, site: str, tag: Optional[str] = None) -> None:
        with self._lock:
            self.calls[site] += 1
            if tag is not None:
                self.calls[f"{site}@{tag}"] += 1
            wedge = self._wedges.get((site, tag))
            if wedge is None and tag is not None:
                wedge = self._wedges.get((site, None))
            if wedge is not None and wedge.released():
                wedge = None  # un-wedged: the site behaves again
            out = None
            for key in ((site, tag), (site, None)) if tag is not None else ((site, None),):
                if self._scripts[key]:
                    out = self._scripts[key].popleft()
                    break
            if out is None and wedge is None and site in self._maybe:
                p, exc = self._maybe[site]
                if self._rng.random() < p:
                    out = exc
        if wedge is not None:
            # block OUTSIDE the plan lock: other sites keep injecting while
            # this thread hangs, exactly like a real wedged dispatch
            with self._lock:
                self.fired[site] += 1
                if tag is not None:
                    self.fired[f"{site}@{tag}"] += 1
            # tag the fault site on the solve's span tree BEFORE parking:
            # the flight-recorder dump of the ensuing fence shows where the
            # wedged thread is stuck
            obstrace.annotate(fault_site=site, fault_kind="wedge")
            wedge()
        if out is None or out == "ok":
            return
        if callable(out) and not (isinstance(out, type) and issubclass(out, BaseException)):
            out()  # side-effect hook; may itself raise
            return
        with self._lock:
            self.fired[site] += 1
            if tag is not None:
                self.fired[f"{site}@{tag}"] += 1
        obstrace.annotate(fault_site=site, fault_kind="raise")
        if isinstance(out, type):
            raise out(f"injected fault at {site}")
        # re-instantiate so each fire raises a fresh exception object
        raise type(out)(*out.args)

    def pending(self, site: str, tag: Optional[str] = None) -> int:
        """Scripted outcomes not yet consumed (test bookkeeping)."""
        with self._lock:
            return len(self._scripts[(site, tag)])


# -- global activation seam (production sites consult this) ------------------

_ACTIVE: Optional[FaultPlan] = None


def use(plan: Optional[FaultPlan]) -> None:
    global _ACTIVE
    _ACTIVE = plan


@contextmanager
def active(plan: FaultPlan):
    """Scope a plan: sites fire only inside the with-block."""
    prev = _ACTIVE
    use(plan)
    try:
        yield plan
    finally:
        use(prev)


def check(site: str, tag: Optional[str] = None) -> None:
    """Production-site hook: free when no plan is active."""
    if _ACTIVE is not None:
        _ACTIVE.check(site, tag=tag)
