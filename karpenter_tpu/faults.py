"""Deterministic fault injection: seeded, scriptable failure plans.

The TPU seam adds a failure domain the reference never had — XLA runtime
errors, device OOM, compile stalls, garbage decodes — and the only way to
PROVE the resilience layer (solver/resilient.py, controllers/manager.py
backoff) is to make those failures happen on demand, hermetically and
reproducibly. This module is the chaos-test seam: a registry of named
injection sites wired into the production code paths, consulted on every
pass through the site, and a `FaultPlan` that scripts exactly what each
site does ("device dies for 3 solves then recovers") or fails with seeded
probability.

Sites wired into production code:

- ``solver.device_dispatch`` — TPUSolver._dispatch, before the kernel call
  (covers the initial dispatch AND overflow-retry redispatches).
- ``solver.decode``         — TPUSolver device-result decode, after fetch.
- ``cloud.create``          — KwokCloud.create_fleet, before the launch.
- ``store.update``          — Store.update, before persistence.

The check is a no-op module-level None test when no plan is active, so the
hot paths pay one attribute load in production.

Usage (tests):

    plan = FaultPlan(seed=7)
    plan.fail_n("solver.device_dispatch", 3, DeviceError("injected XLA err"))
    with active(plan):
        ...  # first 3 dispatches raise, then the device "recovers"
    assert plan.fired["solver.device_dispatch"] == 3

Outcomes in a script may be: an Exception instance (raised, re-instantiated
per fire so tracebacks never chain), an Exception class (instantiated and
raised), a callable (invoked — may raise or side-effect, e.g. advance a fake
clock to trip a deadline), or the string "ok" (explicit no-op).
"""

from __future__ import annotations

import random
import threading
from collections import defaultdict, deque
from contextlib import contextmanager
from typing import Callable, Dict, Optional

SITES = (
    "solver.device_dispatch",
    "solver.decode",
    "cloud.create",
    "store.update",
)


class FaultError(Exception):
    """Base class for injected faults."""


class DeviceError(FaultError):
    """A transient device/runtime failure (XLA error, OOM, dead tunnel)."""


class DecodeError(FaultError, ValueError):
    """A deterministic garbage-decode failure (classified as an encode bug)."""


class FaultPlan:
    """A seeded, deterministic schedule of outcomes per injection site.

    Per-site outcome resolution order on each check():
      1. the next scripted outcome, if the script is non-empty;
      2. the probabilistic rule (seeded RNG), if one is set;
      3. ok.
    """

    def __init__(self, seed: int = 0):
        self.seed = seed
        self._rng = random.Random(seed)
        self._scripts: Dict[str, deque] = defaultdict(deque)
        self._maybe: Dict[str, tuple] = {}  # site -> (p, exc)
        self._lock = threading.Lock()
        self.calls: Dict[str, int] = defaultdict(int)  # checks per site
        self.fired: Dict[str, int] = defaultdict(int)  # raises per site

    # -- scripting ----------------------------------------------------------

    def script(self, site: str, *outcomes) -> "FaultPlan":
        """Append explicit outcomes consumed one per check, in order."""
        self._scripts[site].extend(outcomes)
        return self

    def fail_n(self, site: str, n: int, exc=None) -> "FaultPlan":
        """Site fails the next `n` checks, then recovers (script suffix)."""
        exc = exc if exc is not None else DeviceError(f"injected fault at {site}")
        return self.script(site, *([exc] * n))

    def maybe(self, site: str, p: float, exc=None) -> "FaultPlan":
        """Fail each UNSCRIPTED check with probability `p` (seeded RNG, so a
        given (seed, call sequence) always fires identically)."""
        exc = exc if exc is not None else DeviceError(f"injected fault at {site}")
        self._maybe[site] = (p, exc)
        return self

    # -- consumption --------------------------------------------------------

    def check(self, site: str) -> None:
        with self._lock:
            self.calls[site] += 1
            out = self._scripts[site].popleft() if self._scripts[site] else None
            if out is None and site in self._maybe:
                p, exc = self._maybe[site]
                if self._rng.random() < p:
                    out = exc
        if out is None or out == "ok":
            return
        if callable(out) and not (isinstance(out, type) and issubclass(out, BaseException)):
            out()  # side-effect hook; may itself raise
            return
        with self._lock:
            self.fired[site] += 1
        if isinstance(out, type):
            raise out(f"injected fault at {site}")
        # re-instantiate so each fire raises a fresh exception object
        raise type(out)(*out.args)

    def pending(self, site: str) -> int:
        """Scripted outcomes not yet consumed (test bookkeeping)."""
        with self._lock:
            return len(self._scripts[site])


# -- global activation seam (production sites consult this) ------------------

_ACTIVE: Optional[FaultPlan] = None


def use(plan: Optional[FaultPlan]) -> None:
    global _ACTIVE
    _ACTIVE = plan


@contextmanager
def active(plan: FaultPlan):
    """Scope a plan: sites fire only inside the with-block."""
    prev = _ACTIVE
    use(plan)
    try:
        yield plan
    finally:
        use(prev)


def check(site: str) -> None:
    """Production-site hook: free when no plan is active."""
    if _ACTIVE is not None:
        _ACTIVE.check(site)
