"""kwok CloudProvider: the L2 adapter backed by the fake cloud.

Implements the CloudProvider contract (pkg/cloudprovider/cloudprovider.go:
56-305 behaviorally) against KwokCloud:

- create(): instance-type options filtered by claim requirements →
  truncate(60) (pkg/providers/instance/instance.go:60) → offerings expanded
  to fleet overrides (cross-product, instance.go:399-448) → lowest-price
  CreateFleet → fleet ICE errors feed the UnavailableOfferings cache
  (instance.go:450-486) → claim status filled from the launched instance.
- delete(): skip if already shutting down (instance.go:203-221).
- get_instance_types(): catalog with ICE-masked offering availability.
- is_drifted(): nodeclass-hash drift (drift.go:34-74 behaviorally).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

from ..api import wellknown as wk
from ..api.objects import NodeClaim
from ..cloudprovider.types import (
    CloudProvider,
    InstanceType,
    InsufficientCapacityError,
    NodeClaimNotFoundError,
    Offering,
    truncate,
)
from ..providers.capacityreservation import CapacityReservationProvider
from ..providers.unavailable import UnavailableOfferings
from ..scheduling.requirements import Requirements
from ..utils.resources import Resources
from .cloud import FleetOverride, KwokCloud


class KwokCloudProvider(CloudProvider):
    def __init__(
        self,
        cloud: KwokCloud,
        instance_types: Sequence[InstanceType],
        unavailable: Optional[UnavailableOfferings] = None,
        reservations: Optional[CapacityReservationProvider] = None,
        max_launch_types: int = 60,
        discovered=None,
    ):
        self.cloud = cloud
        self._types = list(instance_types)
        self._by_name = {it.name: it for it in instance_types}
        self.unavailable = unavailable or UnavailableOfferings()
        self.reservations = reservations or CapacityReservationProvider()
        self.max_launch_types = max_launch_types
        self.discovered = discovered  # DiscoveredCapacityCache | None
        self._lock = threading.Lock()
        self._ice_seq = (-1, -1, -1)
        self._masked_cache: List[InstanceType] = []

    # -- instance types -----------------------------------------------------

    def get_instance_types(self, nodepool_name: str = "") -> List[InstanceType]:
        """Catalog with per-offering availability masked by the ICE cache.
        Rebuilt only when the ICE SeqNum moves (offering/offering.go:181-199
        cache-key protocol)."""
        with self._lock:
            seq = (
                self.unavailable.seq_num,
                self._reservation_version(),
                self.discovered.seq if self.discovered is not None else -1,
            )
            if seq == self._ice_seq:
                return self._masked_cache
            from ..utils.resources import MEMORY

            out: List[InstanceType] = []
            for it in self._types:
                offerings = [
                    Offering(
                        zone=o.zone,
                        capacity_type=o.capacity_type,
                        price=o.price,
                        available=o.available
                        and not self.unavailable.is_unavailable(o.capacity_type, it.name, o.zone),
                        reservation_capacity=o.reservation_capacity,
                        reservation_id=o.reservation_id,
                    )
                    for o in it.offerings
                ]
                capacity = it.capacity
                if self.discovered is not None:
                    # discovered-capacity learning: observed memory from live
                    # nodes replaces the catalog's VM-overhead ESTIMATE
                    # (instancetype.go:320-344)
                    mem = self.discovered.memory(it.name)
                    if mem is not None and mem != capacity.get(MEMORY):
                        capacity = type(it.capacity)(it.capacity)
                        capacity[MEMORY] = mem
                out.append(
                    InstanceType(
                        name=it.name,
                        requirements=Requirements(it.requirements),
                        capacity=capacity,
                        overhead=it.overhead,
                        offerings=offerings,
                    )
                )
            self.reservations.inject(out)
            self._ice_seq = seq
            self._masked_cache = out
            return out

    def _reservation_version(self) -> int:
        return sum((r.total + 1) * 1000 + r.available for r in self.reservations.list())

    def catalog_token(self) -> tuple:
        """Identity of the current masked catalog for the encode-cache stamp
        (state/cluster.py:EncodeDeltas): the same SeqNum tuple that keys the
        masked-catalog cache above, so equal tokens guarantee
        get_instance_types returned the SAME list objects (pools_key ids)."""
        with self._lock:
            return (
                self.unavailable.seq_num,
                self._reservation_version(),
                self.discovered.seq if self.discovered is not None else -1,
            )

    # -- create -------------------------------------------------------------

    def create(self, claim: NodeClaim, instance_type_names: Optional[Sequence[str]] = None) -> NodeClaim:
        types = self.get_instance_types(claim.nodepool)
        by_name = {it.name: it for it in types}
        candidates = (
            [by_name[n] for n in instance_type_names if n in by_name]
            if instance_type_names
            else types
        )
        reqs = claim.requirements
        compatible = [
            it
            for it in candidates
            if reqs.compatible(it.requirements) and it.available(reqs)
        ]
        if not compatible:
            raise InsufficientCapacityError("no compatible offering is available")
        kept = truncate(compatible, reqs, self.max_launch_types)
        overrides: List[FleetOverride] = []
        for it in kept:
            for o in it.offerings:
                if not o.available:
                    continue
                if not reqs.compatible(o.requirements()):
                    continue
                overrides.append(
                    FleetOverride(
                        instance_type=it.name,
                        zone=o.zone,
                        capacity_type=o.capacity_type,
                        price=o.price,
                        reservation_id=o.reservation_id,
                    )
                )
        if not overrides:
            raise InsufficientCapacityError("no launchable offering after filtering")
        inst, errors = self.cloud.create_fleet(
            overrides, tags={"karpenter.sh/nodeclaim": claim.name}
        )
        for err in errors:
            if err.code == "InsufficientInstanceCapacity":
                self.unavailable.mark_unavailable(err.capacity_type, err.instance_type, err.zone)
        if inst is None:
            raise InsufficientCapacityError(
                f"all {len(overrides)} offerings failed",
                offerings=[(e.instance_type, e.zone, e.capacity_type) for e in errors],
            )
        if inst.capacity_type == wk.CAPACITY_TYPE_RESERVED and inst.reservation_id:
            self.reservations.mark_launched(inst.reservation_id)
        it = self._by_name[inst.instance_type]
        claim.provider_id = f"kwok:///{inst.zone}/{inst.id}"
        claim.instance_type = inst.instance_type
        claim.zone = inst.zone
        claim.capacity_type = inst.capacity_type
        claim.price = inst.price
        claim.capacity = Resources(it.capacity)
        claim.allocatable = it.allocatable()
        claim.node_name = inst.node_name
        claim.launched = True
        return claim

    # -- get/list/delete ----------------------------------------------------

    @staticmethod
    def _instance_id(provider_id: str) -> str:
        return provider_id.rsplit("/", 1)[-1]

    def get(self, provider_id: str) -> NodeClaim:
        insts = self.cloud.describe_instances([self._instance_id(provider_id)])
        if not insts:
            raise NodeClaimNotFoundError(provider_id)
        return self._to_claim(insts[0])

    def list(self) -> List[NodeClaim]:
        return [self._to_claim(i) for i in self.cloud.describe_instances()]

    def delete(self, claim: NodeClaim) -> None:
        iid = self._instance_id(claim.provider_id)
        insts = self.cloud.describe_instances([iid])
        if not insts:
            raise NodeClaimNotFoundError(claim.provider_id)
        if insts[0].state == "shutting-down":
            return  # already terminating (instance.go:203-221 dedup)
        inst = insts[0]
        self.cloud.terminate_instances([iid])
        if inst.capacity_type == wk.CAPACITY_TYPE_RESERVED and inst.reservation_id:
            self.reservations.mark_terminated(inst.reservation_id)

    def _to_claim(self, inst) -> NodeClaim:
        from ..api.objects import ObjectMeta

        it = self._by_name.get(inst.instance_type)
        claim = NodeClaim(
            meta=ObjectMeta(
                name=inst.tags.get("karpenter.sh/nodeclaim", inst.id),
                creation_timestamp=inst.launch_time,
            ),
            provider_id=f"kwok:///{inst.zone}/{inst.id}",
            instance_type=inst.instance_type,
            zone=inst.zone,
            capacity_type=inst.capacity_type,
            price=inst.price,
            launched=True,
        )
        if it is not None:
            claim.capacity = Resources(it.capacity)
            claim.allocatable = it.allocatable()
        claim.node_name = inst.node_name
        return claim

    # -- drift --------------------------------------------------------------

    def is_drifted(self, claim: NodeClaim) -> Optional[str]:
        return claim.drifted
