"""Token-bucket rate limiters mimicking cloud API throttling.

Mirrors the reference's kwok per-API token buckets (kwok/ec2/ratelimiting.go:
86-107: non-mutating 20/100, mutating 5/50, TerminateInstances 5/100,
CreateTags 10/100) so the hermetic benchmark exercises the same backpressure
the real cloud applies. A Nop limiter exists for pure-throughput benches
(ratelimiting.go:33-60).
"""

from __future__ import annotations

import threading
import time


class ThrottleError(Exception):
    """Equivalent of EC2 RequestLimitExceeded."""


class TokenBucket:
    def __init__(self, rate: float, burst: int, clock=time.monotonic):
        self.rate = float(rate)
        self.burst = float(burst)
        self.clock = clock
        self._tokens = float(burst)
        self._last = clock()
        self._lock = threading.Lock()

    def try_take(self, n: float = 1.0) -> bool:
        with self._lock:
            now = self.clock()
            self._tokens = min(self.burst, self._tokens + (now - self._last) * self.rate)
            self._last = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def take_or_raise(self, api: str) -> None:
        if not self.try_take():
            raise ThrottleError(f"rate limit exceeded for {api}")


class NopLimiter:
    def try_take(self, n: float = 1.0) -> bool:
        return True

    def take_or_raise(self, api: str) -> None:
        return None


class ApiLimits:
    """The reference's per-API-class buckets."""

    def __init__(self, enabled: bool = True, clock=time.monotonic):
        if enabled:
            self.non_mutating = TokenBucket(20, 100, clock=clock)
            self.mutating = TokenBucket(5, 50, clock=clock)
            self.terminate = TokenBucket(5, 100, clock=clock)
            self.tags = TokenBucket(10, 100, clock=clock)
        else:
            self.non_mutating = self.mutating = self.terminate = self.tags = NopLimiter()
