"""kwok fake cloud: the hermetic benchmark substrate.

Behavioral mirror of the reference's in-memory EC2 (kwok/ec2/ec2.go:55-110,
374-628): CreateFleet picks the lowest-price override (kwok/strategy/
strategy.go:28-60), fabricates an instance record, and **directly creates the
Node object** in the store with kwok labels, the unregistered taint, and
capacity/allocatable from the instance-type model (ec2.go:865-897 toNode) —
so nodes run kubelet-less and the whole control loop closes without real
hardware. A node-killer purges Nodes whose instance vanished
(ec2.go:219-262); per-API token buckets mimic EC2 throttling.

Fault injection mirrors pkg/fake/ec2api.go:41-76: capacity pools that, when
exhausted, produce InsufficientCapacity fleet errors for specific
(instance-type, zone, capacity-type) offerings.
"""

from __future__ import annotations

import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from .. import faults
from ..api import wellknown as wk
from ..api.objects import Node, ObjectMeta, Taint
from ..cloudprovider.types import InstanceType
from ..controllers import store as st
from ..utils.resources import Resources
from .ratelimit import ApiLimits

KWOK_LABEL_KEY = "kwok.x-k8s.io/node"
KWOK_LABEL_VALUE = "fake"
KWOK_PARTITION_LABEL_KEY = "kwok-partition"


@dataclass
class FleetOverride:
    instance_type: str
    zone: str
    capacity_type: str
    price: float
    reservation_id: str = ""


@dataclass
class Instance:
    id: str
    instance_type: str
    zone: str
    capacity_type: str
    price: float
    reservation_id: str = ""
    tags: Dict[str, str] = field(default_factory=dict)
    state: str = "running"  # running | shutting-down | terminated
    launch_time: float = field(default_factory=time.monotonic)
    node_name: str = ""


@dataclass
class FleetError:
    instance_type: str
    zone: str
    capacity_type: str
    code: str  # InsufficientInstanceCapacity | ...
    message: str = ""


class KwokCloud:
    """In-memory cloud with direct Node fabrication."""

    def __init__(
        self,
        store: st.Store,
        instance_types: Sequence[InstanceType],
        rate_limits: bool = False,
        auto_register_delay_s: float = 0.0,
        clock=time.monotonic,
    ):
        self.store = store
        self.types = {it.name: it for it in instance_types}
        self.limits = ApiLimits(enabled=rate_limits, clock=clock)
        self.auto_register_delay_s = auto_register_delay_s
        self.clock = clock  # instance launch_time shares the control-plane clock
        self._instances: Dict[str, Instance] = {}
        self._lock = threading.RLock()
        self._seq = itertools.count(1)
        # fault injection: capacity pools keyed (type, zone, capacity_type);
        # -1 = unlimited
        self._capacity_pools: Dict[Tuple[str, str, str], int] = {}

    # -- fault injection ----------------------------------------------------

    def set_capacity(self, instance_type: str, zone: str, capacity_type: str, count: int) -> None:
        with self._lock:
            self._capacity_pools[(instance_type, zone, capacity_type)] = count

    def _take_capacity(self, key: Tuple[str, str, str]) -> bool:
        cur = self._capacity_pools.get(key, -1)
        if cur < 0:
            return True
        if cur == 0:
            return False
        self._capacity_pools[key] = cur - 1
        return True

    # -- fleet API ----------------------------------------------------------

    def create_fleet(
        self, overrides: Sequence[FleetOverride], tags: Optional[Dict[str, str]] = None
    ) -> Tuple[Optional[Instance], List[FleetError]]:
        """Launch ONE instance choosing the lowest-price override (the
        reference strategy), walking up the price list past ICE'd offerings."""
        self.limits.mutating.take_or_raise("CreateFleet")
        faults.check("cloud.create")
        errors: List[FleetError] = []
        with self._lock:
            for ov in sorted(overrides, key=lambda o: (o.price, o.instance_type, o.zone)):
                key = (ov.instance_type, ov.zone, ov.capacity_type)
                if ov.instance_type not in self.types:
                    errors.append(FleetError(*key, code="InvalidParameterValue"))
                    continue
                if not self._take_capacity(key):
                    errors.append(
                        FleetError(*key, code="InsufficientInstanceCapacity",
                                   message="We currently do not have sufficient capacity")
                    )
                    continue
                inst = Instance(
                    id=f"i-{next(self._seq):017x}",
                    instance_type=ov.instance_type,
                    zone=ov.zone,
                    capacity_type=ov.capacity_type,
                    price=ov.price,
                    reservation_id=ov.reservation_id,
                    tags=dict(tags or {}),
                    launch_time=self.clock(),
                )
                self._instances[inst.id] = inst
                self._create_node(inst)
                return inst, errors
        return None, errors

    # -- node fabrication (ec2.go:865-897 toNode) ---------------------------

    def _create_node(self, inst: Instance) -> None:
        it = self.types[inst.instance_type]
        name = f"kwok-{inst.id}"
        inst.node_name = name
        labels = {
            KWOK_LABEL_KEY: KWOK_LABEL_VALUE,
            wk.INSTANCE_TYPE_LABEL: inst.instance_type,
            wk.ZONE_LABEL: inst.zone,
            wk.CAPACITY_TYPE_LABEL: inst.capacity_type,
            wk.HOSTNAME_LABEL: name,
            wk.REGION_LABEL: "region-1",
        }
        for key, req in it.requirements.items():
            vals = req.values_list()
            if len(vals) == 1 and key not in labels:
                labels[key] = vals[0]
        node = Node(
            meta=ObjectMeta(
                name=name,
                labels=labels,
                annotations={},
            ),
            capacity=Resources(it.capacity),
            allocatable=it.allocatable(),
            taints=[Taint(key=wk.UNREGISTERED_TAINT_KEY, effect=wk.EFFECT_NO_EXECUTE)],
            ready=False,
            provider_id=f"kwok:///{inst.zone}/{inst.id}",
        )
        self.store.create(st.NODES, node)

    # -- describe/terminate --------------------------------------------------

    def describe_instances(self, ids: Optional[Sequence[str]] = None) -> List[Instance]:
        self.limits.non_mutating.take_or_raise("DescribeInstances")
        with self._lock:
            if ids is None:
                return [i for i in self._instances.values() if i.state != "terminated"]
            return [
                self._instances[i]
                for i in ids
                if i in self._instances and self._instances[i].state != "terminated"
            ]

    def terminate_instances(self, ids: Sequence[str]) -> List[str]:
        self.limits.terminate.take_or_raise("TerminateInstances")
        done = []
        with self._lock:
            for iid in ids:
                inst = self._instances.get(iid)
                if inst is None or inst.state == "terminated":
                    continue
                inst.state = "terminated"
                done.append(iid)
                # node-killer: purge the Node backing a vanished instance
                if inst.node_name and self.store.try_get(st.NODES, inst.node_name):
                    node = self.store.get(st.NODES, inst.node_name)
                    node.meta.finalizers = [
                        f for f in node.meta.finalizers if f != wk.TERMINATION_FINALIZER
                    ]
                    try:
                        self.store.delete(st.NODES, inst.node_name)
                    except st.NotFound:
                        pass
        return done

    def create_tags(self, instance_id: str, tags: Dict[str, str]) -> None:
        self.limits.tags.take_or_raise("CreateTags")
        with self._lock:
            inst = self._instances.get(instance_id)
            if inst:
                inst.tags.update(tags)

    # -- registration simulation (kwok nodes have no kubelet) ---------------

    def register_node(self, node_name: str) -> bool:
        """Flip a fabricated node to Ready and drop the unregistered taint —
        what kubelet+node-lifecycle would do on a real node."""
        node = self.store.try_get(st.NODES, node_name)
        if node is None:
            return False
        node.taints = [t for t in node.taints if t.key != wk.UNREGISTERED_TAINT_KEY]
        node.ready = True
        self.store.update(st.NODES, node)
        return True
