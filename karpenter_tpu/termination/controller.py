"""Termination: finalizer-based graceful drain.

Mirrors the reference's termination flow (designs/termination.md;
website/.../concepts/disruption.md:30-38,244-270; SURVEY.md §3.3):

  deletion requested -> finalizer blocks -> taint karpenter.sh/disrupted
  -> evict pods via the (PDB-aware) eviction path, skipping daemonset-like
  and tolerating pods -> when drained (or past terminationGracePeriod,
  which force-deletes) -> delete the cloud instance -> remove finalizers.
"""

from __future__ import annotations

import time
from typing import List, Optional

from ..api import wellknown as wk
from ..api.objects import Node, NodeClaim, Pod, PodDisruptionBudget, Taint
from ..cloudprovider.types import CloudProvider, NodeClaimNotFoundError
from ..controllers import store as st
from ..metrics.registry import NODECLAIMS_TERMINATED


class EvictionQueue:
    """PDB-aware pod eviction (the Eviction API stand-in)."""

    def __init__(self, store: st.Store):
        self.store = store

    def can_evict(self, pod: Pod) -> bool:
        for pdb in self.store.list(st.PDBS):
            if not pdb.matches(pod):
                continue
            peers = [
                p
                for p in self.store.list(st.PODS)
                if pdb.matches(p) and not p.meta.deleting and p.phase != "Failed"
            ]
            healthy = [p for p in peers if p.node_name is not None]
            if pdb.min_available is not None:
                if len(healthy) - 1 < pdb.min_available:
                    return False
            if pdb.max_unavailable is not None:
                unavailable = len(peers) - len(healthy) + 1
                if unavailable > pdb.max_unavailable:
                    return False
        return True

    def evict(self, pod: Pod) -> bool:
        if not self.can_evict(pod):
            return False
        # eviction unbinds; the pod returns to Pending for the provisioner
        # (mirrors a ReplicaSet recreating the pod elsewhere)
        st.repose_pod(self.store, pod)
        return True


class TerminationController:
    name = "termination"

    def __init__(
        self,
        store: st.Store,
        cloud_provider: CloudProvider,
        clock=time.monotonic,
    ):
        self.store = store
        self.cloud_provider = cloud_provider
        self.eviction = EvictionQueue(store)
        self.clock = clock

    # -- helpers ------------------------------------------------------------

    def _pods_on(self, node_name: str) -> List[Pod]:
        return [p for p in self.store.list(st.PODS) if p.node_name == node_name]

    def _drainable(self, pod: Pod, node: Optional[Node]) -> bool:
        if pod.owner_kind == "DaemonSet":
            return False  # daemonsets are not drained (disruption.md:30-38)
        if node is not None and any(
            tol.tolerates(Taint(key=wk.DISRUPTED_TAINT_KEY, effect=wk.EFFECT_NO_SCHEDULE))
            for tol in pod.tolerations
        ):
            # pods tolerating the disruption taint opted in to staying
            return False
        return True

    # -- reconcile ----------------------------------------------------------

    def reconcile(self) -> bool:
        did = False
        for claim in self.store.list(st.NODECLAIMS):
            if not claim.meta.deleting:
                continue
            did = self._terminate(claim) or did
        # nodes deleted directly (kubectl delete node) also drain via their claim
        for node in self.store.list(st.NODES):
            if node.meta.deleting and wk.TERMINATION_FINALIZER in node.meta.finalizers:
                claim = self._claim_for(node)
                if claim is not None and not claim.meta.deleting:
                    self.store.delete(st.NODECLAIMS, claim.name)
                    did = True
                elif claim is None:
                    node.meta.finalizers.remove(wk.TERMINATION_FINALIZER)
                    self.store.update(st.NODES, node)
                    did = True
        return did

    def _claim_for(self, node: Node) -> Optional[NodeClaim]:
        for c in self.store.list(st.NODECLAIMS):
            if c.node_name == node.meta.name or (
                c.provider_id and c.provider_id == node.provider_id
            ):
                return c
        return None

    def _terminate(self, claim: NodeClaim) -> bool:
        did = False
        node = self.store.try_get(st.NODES, claim.node_name) if claim.node_name else None
        if node is not None:
            # 1. taint so nothing reschedules here (disruption.md:15-28)
            if not any(t.key == wk.DISRUPTED_TAINT_KEY for t in node.taints):
                node.taints.append(Taint(key=wk.DISRUPTED_TAINT_KEY, effect=wk.EFFECT_NO_SCHEDULE))
                node.unschedulable = True
                self.store.update(st.NODES, node)
                did = True
            # 2. drain
            force = (
                claim.termination_grace_period_s is not None
                and claim.meta.deletion_timestamp is not None
                and self.clock() - claim.meta.deletion_timestamp
                > claim.termination_grace_period_s
            )
            remaining = []
            for pod in self._pods_on(node.meta.name):
                if not self._drainable(pod, node):
                    continue
                if force:
                    st.repose_pod(self.store, pod)
                    did = True
                elif self.eviction.evict(pod):
                    did = True
                else:
                    remaining.append(pod)
            if remaining:
                # PDB-blocked: report progress only if something moved this
                # tick (returning True forever would livelock settle())
                return did
        # 3. delete the instance
        if claim.provider_id:
            try:
                self.cloud_provider.delete(claim)
            except NodeClaimNotFoundError:
                pass
        # 4. release finalizers (node object may already be gone via the
        # cloud's node-killer)
        if node is not None and self.store.try_get(st.NODES, node.meta.name):
            if wk.TERMINATION_FINALIZER in node.meta.finalizers:
                node.meta.finalizers.remove(wk.TERMINATION_FINALIZER)
                self.store.update(st.NODES, node)
            try:
                self.store.delete(st.NODES, node.meta.name)
            except st.NotFound:
                pass
        if wk.TERMINATION_FINALIZER in claim.meta.finalizers:
            claim.meta.finalizers.remove(wk.TERMINATION_FINALIZER)
            self.store.update(st.NODECLAIMS, claim)
            NODECLAIMS_TERMINATED.inc(nodepool=claim.nodepool, reason="terminated")
        return True
