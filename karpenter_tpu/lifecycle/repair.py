"""Node auto-repair.

Mirrors the reference's repair flow (pkg/cloudprovider/cloudprovider.go:
264-305 RepairPolicies; website/.../concepts/disruption.md:208-234): when a
node condition matches a repair policy and has persisted past the policy's
toleration duration, the NodeClaim is force-deleted (repair is forceful — no
pre-spun replacement; provisioning replaces reactively). A circuit breaker
refuses to repair when >20% of the fleet is unhealthy — mass-unhealthiness
usually means a controller/infra problem, not node problems.
"""

from __future__ import annotations

import logging
import time
from typing import List

from ..api import wellknown as wk
from ..cloudprovider.types import CloudProvider, RepairPolicy
from ..controllers import store as st
from ..metrics.registry import NODECLAIMS_TERMINATED, REPAIR_BREAKER_OPEN

UNHEALTHY_BREAKER_FRACTION = 0.2  # disruption.md:208-234

log = logging.getLogger("karpenter_tpu")


class RepairController:
    name = "node.repair"

    def __init__(self, store: st.Store, cloud_provider: CloudProvider, clock=time.monotonic):
        self.store = store
        self.cloud_provider = cloud_provider
        self.clock = clock
        self._breaker_open = False

    def _set_breaker(self, open_: bool, unhealthy: int = 0, total: int = 0) -> None:
        if open_ and not self._breaker_open:
            # log once per trip, not every tick while the fleet stays sick
            log.warning(
                "node repair breaker OPEN: %d/%d nodes unhealthy (> %.0f%%) "
                "— refusing to repair a fleet-wide problem",
                unhealthy, total, UNHEALTHY_BREAKER_FRACTION * 100,
            )
        elif not open_ and self._breaker_open:
            log.info("node repair breaker closed")
        self._breaker_open = open_
        REPAIR_BREAKER_OPEN.set(1.0 if open_ else 0.0)

    def reconcile(self) -> bool:
        policies: List[RepairPolicy] = self.cloud_provider.repair_policies()
        nodes = self.store.list(st.NODES)
        if not nodes:
            return False
        now = self.clock()

        def matches(node) -> bool:
            for pol in policies:
                if node.conditions.get(pol.condition_type) == pol.condition_status:
                    return True
            return False

        unhealthy = [n for n in nodes if matches(n)]
        if not unhealthy:
            self._set_breaker(False)
            return False
        if len(unhealthy) / len(nodes) > UNHEALTHY_BREAKER_FRACTION and len(nodes) > 1:
            # circuit breaker: fleet-wide problem, do nothing
            self._set_breaker(True, len(unhealthy), len(nodes))
            return False
        self._set_breaker(False)

        claims_by_node = {c.node_name: c for c in self.store.list(st.NODECLAIMS) if c.node_name}
        did = False
        for node in unhealthy:
            claim = claims_by_node.get(node.meta.name)
            if claim is None or claim.meta.deleting:
                continue
            ripe = any(
                node.conditions.get(pol.condition_type) == pol.condition_status
                and now - node.condition_since.get(pol.condition_type, now)
                >= pol.toleration_duration_s
                for pol in policies
            )
            if not ripe:
                continue
            # forceful: no graceful drain wait (terminationGracePeriod ignored)
            try:
                self.store.delete(st.NODECLAIMS, claim.name)
            except st.NotFound:
                continue
            NODECLAIMS_TERMINATED.inc(nodepool=claim.nodepool, reason="repaired")
            did = True
        return did
