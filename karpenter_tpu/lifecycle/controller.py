"""NodeClaim lifecycle: launch -> register -> initialize (+liveness, expiry).

The NodeClaim state machine of karpenter core (SURVEY.md §2.1 "node
lifecycle"; website/.../concepts/nodeclaims.md):

  Create() -> launched (cloud capacity exists)
          -> registered (node joined; unregistered taint removed)
          -> initialized (startup taints cleared, resources posted)

plus liveness GC for claims whose node never registers, and forced expiry
(`expireAfter`). Launch failures with InsufficientCapacityError delete the
claim so the provisioner re-solves against the updated ICE mask — the
"retry in milliseconds" loop (concepts/_index.md:89).
"""

from __future__ import annotations

import time
from typing import Optional

from ..api import wellknown as wk
from ..api.objects import NodeClaim
from ..cloudprovider.types import CloudProvider, InsufficientCapacityError
from ..controllers import store as st
from ..metrics.registry import NODECLAIMS_CREATED, NODECLAIMS_TERMINATED


#: ticks-equivalent pause after a throttled create before retrying that claim
THROTTLE_BACKOFF_S = 1.0


class LaunchController:
    name = "nodeclaim.launch"

    def __init__(self, store: st.Store, cloud_provider: CloudProvider, clock=time.monotonic):
        self.store = store
        self.cloud_provider = cloud_provider
        self.clock = clock
        self._throttled_until: dict = {}  # claim name -> clock() deadline

    def reconcile(self) -> bool:
        from ..kwok.ratelimit import ThrottleError

        did = False
        now = self.clock()
        # drop backoff entries for claims that no longer exist
        live = {c.name for c in self.store.list(st.NODECLAIMS)}
        self._throttled_until = {
            k: v for k, v in self._throttled_until.items() if k in live
        }
        for claim in self.store.list(st.NODECLAIMS):
            if claim.launched or claim.meta.deleting:
                continue
            if self._throttled_until.get(claim.name, 0) > now:
                continue
            try:
                self.cloud_provider.create(claim, claim.instance_type_options)
                NODECLAIMS_CREATED.inc(nodepool=claim.nodepool)
                self._throttled_until.pop(claim.name, None)
            except ThrottleError:
                # per-claim isolation: one throttled create must not abort
                # the remaining launches this tick — back this claim off
                # briefly and move on (the bucket refills on the same clock)
                self._throttled_until[claim.name] = now + THROTTLE_BACKOFF_S
                continue
            except InsufficientCapacityError:
                # ICE: delete the claim; the provisioner re-solves with the
                # failed offerings masked (instance.go:450-486 flow)
                claim.meta.finalizers = []
                self.store.update(st.NODECLAIMS, claim)
                try:
                    self.store.delete(st.NODECLAIMS, claim.name)
                except st.NotFound:
                    pass
                NODECLAIMS_TERMINATED.inc(nodepool=claim.nodepool, reason="insufficient_capacity")
                did = True
                continue
            claim.last_transition = self.clock()
            self.store.update(st.NODECLAIMS, claim)
            did = True
        return did


class RegistrationController:
    """Remove the unregistered taint and adopt the node once it appears
    (core lifecycle: registration — the kwok node was fabricated with
    karpenter.sh/unregistered:NoExecute, kwok/ec2/ec2.go:865-897)."""

    name = "nodeclaim.registration"

    def __init__(self, store: st.Store, clock=time.monotonic):
        self.store = store
        self.clock = clock

    def reconcile(self) -> bool:
        did = False
        for claim in self.store.list(st.NODECLAIMS):
            if not claim.launched or claim.registered or claim.meta.deleting:
                continue
            if not claim.node_name:
                continue
            node = self.store.try_get(st.NODES, claim.node_name)
            if node is None:
                continue
            node.taints = [t for t in node.taints if t.key != wk.UNREGISTERED_TAINT_KEY]
            node.taints.extend(claim.taints)
            node.taints.extend(claim.startup_taints)
            node.meta.labels[wk.NODEPOOL_LABEL] = claim.nodepool
            node.meta.labels[wk.REGISTERED_LABEL] = "true"
            for k, v in claim.requirements.labels().items():
                node.meta.labels.setdefault(k, v)
            if wk.TERMINATION_FINALIZER not in node.meta.finalizers:
                node.meta.finalizers.append(wk.TERMINATION_FINALIZER)
            node.ready = True
            self.store.update(st.NODES, node)
            claim.registered = True
            claim.last_transition = self.clock()
            self.store.update(st.NODECLAIMS, claim)
            did = True
        return did


class InitializationController:
    """registered -> initialized once startup taints are gone and the node
    posts capacity (core lifecycle: initialization)."""

    name = "nodeclaim.initialization"

    def __init__(self, store: st.Store, clock=time.monotonic):
        self.store = store
        self.clock = clock

    def reconcile(self) -> bool:
        did = False
        for claim in self.store.list(st.NODECLAIMS):
            if not claim.registered or claim.initialized or claim.meta.deleting:
                continue
            node = self.store.try_get(st.NODES, claim.node_name) if claim.node_name else None
            if node is None or not node.ready:
                continue
            startup_keys = {t.key for t in claim.startup_taints}
            if any(t.key in startup_keys for t in node.taints):
                continue
            if not node.allocatable:
                continue
            node.meta.labels[wk.INITIALIZED_LABEL] = "true"
            self.store.update(st.NODES, node)
            claim.initialized = True
            claim.last_transition = self.clock()
            self.store.update(st.NODECLAIMS, claim)
            did = True
        return did


class LivenessController:
    """Delete claims whose node never registered within the TTL (core
    liveness GC; reference default 15m)."""

    name = "nodeclaim.liveness"

    def __init__(self, store: st.Store, ttl_s: float = 15 * 60, clock=time.monotonic):
        self.store = store
        self.ttl_s = ttl_s
        self.clock = clock

    def reconcile(self) -> bool:
        did = False
        for claim in self.store.list(st.NODECLAIMS):
            if claim.registered or claim.meta.deleting:
                continue
            if self.clock() - claim.last_transition < self.ttl_s:
                continue
            self.store.delete(st.NODECLAIMS, claim.name)
            NODECLAIMS_TERMINATED.inc(nodepool=claim.nodepool, reason="liveness")
            did = True
        return did


class ExpirationController:
    """Forceful expiry after `expireAfter` (disruption.md:208-234 'expiration
    is forceful; it does not wait for replacement')."""

    name = "nodeclaim.expiration"

    def __init__(self, store: st.Store, clock=time.monotonic):
        self.store = store
        self.clock = clock

    def reconcile(self) -> bool:
        did = False
        for claim in self.store.list(st.NODECLAIMS):
            if claim.meta.deleting or claim.expire_after_s is None:
                continue
            if self.clock() - claim.meta.creation_timestamp < claim.expire_after_s:
                continue
            self.store.delete(st.NODECLAIMS, claim.name)
            NODECLAIMS_TERMINATED.inc(nodepool=claim.nodepool, reason="expired")
            did = True
        return did
