"""Event recorder with dedupe.

Mirror of karpenter core pkg/events (SURVEY.md §2.1): typed events attached
to objects, with a dedupe window so hot reconcile loops don't flood the
stream (the reference's recorder drops identical events within a TTL).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


@dataclass(frozen=True)
class Event:
    kind: str  # object kind
    name: str  # object name
    type: str  # Normal | Warning
    reason: str
    message: str


class Recorder:
    def __init__(self, dedupe_ttl_s: float = 60.0, max_events: int = 10_000, clock=time.monotonic):
        self.dedupe_ttl_s = dedupe_ttl_s
        self.max_events = max_events
        self.clock = clock
        self._events: List[Tuple[float, Event]] = []
        self._last_seen: Dict[Event, float] = {}
        self._lock = threading.Lock()

    def publish(self, event: Event) -> bool:
        """Record unless an identical event fired within the dedupe TTL.
        Returns True if recorded."""
        with self._lock:
            now = self.clock()
            last = self._last_seen.get(event)
            if last is not None and now - last < self.dedupe_ttl_s:
                return False
            self._last_seen[event] = now
            self._events.append((now, event))
            if len(self._events) > self.max_events:
                self._events = self._events[-self.max_events :]
            return True

    def events(self, kind: Optional[str] = None, name: Optional[str] = None) -> List[Event]:
        with self._lock:
            return [
                e
                for _, e in self._events
                if (kind is None or e.kind == kind) and (name is None or e.name == name)
            ]


# Typed event constructors (the reference's per-subsystem events packages)
def nominated(pod_name: str, node_name: str) -> Event:
    return Event("pods", pod_name, "Normal", "Nominated", f"Pod should schedule on {node_name}")


def unschedulable(pod_name: str, reason: str) -> Event:
    return Event("pods", pod_name, "Warning", "FailedScheduling", reason)


def launched(claim_name: str, instance_type: str) -> Event:
    return Event("nodeclaims", claim_name, "Normal", "Launched", f"Launched {instance_type}")


def disrupted(node_name: str, reason: str) -> Event:
    return Event("nodes", node_name, "Normal", "DisruptionBlocked" if "blocked" in reason else "Disrupted", reason)


def interrupted(claim_name: str, kind: str) -> Event:
    return Event("nodeclaims", claim_name, "Warning", "Interrupted", f"Interruption: {kind}")


def preempted(pod_name: str, node_name: str, by_pod: str) -> Event:
    return Event(
        "pods", pod_name, "Normal", "Preempted",
        f"Preempted from {node_name} by higher-priority pod {by_pod}",
    )


def gang_unschedulable(pod_name: str, gang_id: str) -> Event:
    return Event(
        "pods", pod_name, "Warning", "GangUnschedulable",
        f"Gang {gang_id} rolled back: fewer than min-ranks members could schedule",
    )
