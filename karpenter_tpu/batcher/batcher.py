"""Generic request-coalescing engine.

Behavioral mirror of pkg/batcher (SURVEY.md §2.6, batcher.go:59-196):
requests hash into buckets; a bucket flushes when idle for `idle_s` or after
`max_s` since the first request (or at `max_items`); one backend call serves
the whole batch and per-request results split back to callers. The reference
instantiates this for CreateFleet (35ms/1s/1000, createfleet.go:37-117),
DescribeInstances and TerminateInstances — kwok's cloud here is in-process,
so the default windows are 0 and batching's value is call-count amortization
against the rate-limited cloud APIs.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Generic, Hashable, List, Optional, Tuple, TypeVar

from ..metrics.registry import BATCHER_BATCH_SIZE, BATCHER_BATCH_TIME

Req = TypeVar("Req")
Resp = TypeVar("Resp")

# exec_fn: (bucket_key, [requests]) -> [responses] (same order/length)
ExecFn = Callable[[Hashable, List[Any]], List[Any]]


@dataclass
class _Bucket:
    requests: List[Any] = field(default_factory=list)
    events: List[threading.Event] = field(default_factory=list)
    results: List[Any] = field(default_factory=list)
    first_at: float = 0.0
    last_at: float = 0.0


class Batcher(Generic[Req, Resp]):
    def __init__(
        self,
        name: str,
        exec_fn: ExecFn,
        idle_s: float = 0.035,  # createfleet.go:39-41
        max_s: float = 1.0,
        max_items: int = 1000,
        clock=time.monotonic,
    ):
        self.name = name
        self.exec_fn = exec_fn
        self.idle_s = idle_s
        self.max_s = max_s
        self.max_items = max_items
        self.clock = clock
        self._buckets: Dict[Hashable, _Bucket] = defaultdict(_Bucket)
        self._lock = threading.Lock()

    def add(self, key: Hashable, request: Req) -> Callable[[], Resp]:
        """Queue a request; returns a waiter that blocks until the batch
        flushes and yields this request's response."""
        with self._lock:
            b = self._buckets[key]
            now = self.clock()
            if not b.requests:
                b.first_at = now
            b.last_at = now
            idx = len(b.requests)
            b.requests.append(request)
            ev = threading.Event()
            b.events.append(ev)
            flush_now = len(b.requests) >= self.max_items or (
                self.idle_s == 0 and self.max_s == 0
            )
        if flush_now:
            self.flush(key)

        def wait(timeout: Optional[float] = None) -> Resp:
            if not ev.wait(timeout if timeout is not None else max(self.max_s * 4, 1.0)):
                raise TimeoutError(f"batcher {self.name} flush timed out")
            res = ev.result  # type: ignore[attr-defined]
            if isinstance(res, Exception):
                raise res
            return res

        return wait

    def poll(self) -> bool:
        """Flush any bucket whose idle/max window elapsed (call from the
        controller tick loop)."""
        now = self.clock()
        due = []
        with self._lock:
            for key, b in self._buckets.items():
                if not b.requests:
                    continue
                if (now - b.last_at) >= self.idle_s or (now - b.first_at) >= self.max_s:
                    due.append(key)
        for key in due:
            self.flush(key)
        return bool(due)

    def flush(self, key: Hashable) -> None:
        with self._lock:
            b = self._buckets.pop(key, None)
        if b is None or not b.requests:
            return
        BATCHER_BATCH_SIZE.observe(len(b.requests), batcher=self.name)
        BATCHER_BATCH_TIME.observe(self.clock() - b.first_at, batcher=self.name)
        try:
            results = self.exec_fn(key, b.requests)
        except Exception as e:  # deliver the error to every waiter
            results = [e] * len(b.requests)
        for ev, res in zip(b.events, results):
            ev.result = res  # type: ignore[attr-defined]
            ev.set()
