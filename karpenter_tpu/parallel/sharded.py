"""Multi-chip sharding for batched solves.

The solver's scale-out axis is *independent solves* (SURVEY.md §2.10): the
disruption engine simulates thousands of candidate subsets, each a re-solve
(HOT LOOP #2, SURVEY.md §3.2). Batching candidates as a leading vmap axis and
sharding that axis across a `jax.sharding.Mesh` is the whole point of the TPU
backend — each chip evaluates its shard of candidates, results gather back.
No cross-candidate communication is needed during the solve, so collectives
(an all-gather of per-candidate costs) ride ICI only at the end.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..solver.tpu.ffd import ffd_solve


def make_mesh(n_devices: Optional[int] = None, axis: str = "candidates") -> Mesh:
    devs = jax.devices()[: n_devices or len(jax.devices())]
    return Mesh(np.asarray(devs), (axis,))


def batched_solve(mesh: Mesh, batched_args: tuple, max_claims: int):
    """vmap ffd_solve over a leading candidate axis, sharded across the mesh.

    `batched_args`: the positional ffd_solve arrays (order/arity defined by
    ffd.ARG_SPEC), each with a leading batch axis B divisible by the mesh
    size. Returns FFDOutput with leading batch axes, sharded the same way.
    """
    axis = mesh.axis_names[0]
    sharding = NamedSharding(mesh, P(axis))

    fn = jax.vmap(functools.partial(ffd_solve.__wrapped__, max_claims=max_claims))
    jfn = jax.jit(fn, in_shardings=(sharding,) * len(batched_args), out_shardings=sharding)
    placed = tuple(jax.device_put(a, sharding) for a in batched_args)
    return jfn(*placed)


def replicate_args(args: tuple, batch: int) -> tuple:
    """Tile single-solve args to a batch (test/dryrun helper)."""
    return tuple(np.broadcast_to(np.asarray(a)[None], (batch,) + np.asarray(a).shape).copy() for a in args)
