"""Multi-chip sharding for batched solves.

The solver's scale-out axis is *independent solves* (SURVEY.md §2.10): the
disruption engine simulates thousands of candidate subsets, each a re-solve
(HOT LOOP #2, SURVEY.md §3.2). Batching candidates as a leading vmap axis and
sharding that axis across a `jax.sharding.Mesh` is the whole point of the TPU
backend — each chip evaluates its shard of candidates, results gather back.
No cross-candidate communication is needed during the solve, so collectives
(an all-gather of per-candidate costs) ride ICI only at the end.
"""

from __future__ import annotations

import functools
import weakref
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..solver.tpu.ffd import ffd_solve


def make_mesh(n_devices: Optional[int] = None, axis: str = "candidates") -> Mesh:
    devs = jax.devices()[: n_devices or len(jax.devices())]
    return Mesh(np.asarray(devs), (axis,))


def batch_bucket(b: int, mesh: Optional[Mesh] = None, mult: int = 8) -> int:
    """Bucket a candidate-batch size so dispatches compile once per bucket,
    not once per exact row count, and the batch axis divides evenly across
    the mesh when one exists (lcm of the bucket multiple and the device
    count). Shared by simulate_subsets and the speculative-probe planner so
    a probe frontier sized to `probe_batch_max` lands on the same compiled
    executable every decision."""
    import math

    if mesh is not None:
        n_dev = int(mesh.devices.size)
        mult = mult * n_dev // math.gcd(mult, n_dev)
    return max(mult, ((b + mult - 1) // mult) * mult)


# Memoized jitted vmap per (mesh identity, arity, max_claims): rebuilding
# jax.jit(vmap(...)) per call discarded the trace cache, so every multichip
# dispatch re-traced and re-lowered the whole kernel even though the
# compiled executable was shape-identical. The identity token covers device
# ids, the device-grid SHAPE, and axis names — equal meshes over the same
# devices share an entry, while a RESHAPED mesh (same flat devices, new
# grid) can never serve the stale compiled fn its predecessor lowered.
_JIT_CACHE: dict = {}

# Per-Mesh-object token memo: the token construction walks mesh.devices
# (O(n_devices) python per call), which showed up in the batched_solve hot
# path — the disruption engine calls this once per probe frontier. Weak keys
# keep dead meshes from pinning their tokens.
_MESH_TOKENS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _mesh_token(mesh: Mesh) -> tuple:
    try:
        tok = _MESH_TOKENS.get(mesh)
    except TypeError:
        tok = None  # un-weakref-able mesh implementation: compute per call
    if tok is None:
        tok = (
            tuple(int(d.id) for d in mesh.devices.flat),
            tuple(mesh.devices.shape),
            tuple(mesh.axis_names),
        )
        try:
            _MESH_TOKENS[mesh] = tok
        except TypeError:
            pass
    return tok


def batched_solve(mesh: Mesh, batched_args: tuple, max_claims: int,
                  zone_engine: bool = True):
    """vmap ffd_solve over a leading candidate axis, sharded across the mesh.

    `batched_args`: the positional ffd_solve arrays (order/arity defined by
    ffd.ARG_SPEC), each with a leading batch axis B divisible by the mesh
    size. Returns FFDOutput with leading batch axes, sharded the same way.

    `zone_engine` mirrors ffd_solve's static of the same name (the cohort
    dispatch passes the members' shared `enc.V > 0` so a fused lane runs the
    exact kernel its solo dispatch would); it is part of the jit-cache key.
    """
    axis = mesh.axis_names[0]
    key = (
        _mesh_token(mesh),
        len(batched_args),
        int(max_claims),
        bool(zone_engine),
    )
    ent = _JIT_CACHE.get(key)
    if ent is None:
        sharding = NamedSharding(mesh, P(axis))
        fn = jax.vmap(functools.partial(
            ffd_solve.__wrapped__, max_claims=max_claims,
            zone_engine=zone_engine,
        ))
        jfn = jax.jit(
            fn, in_shardings=(sharding,) * len(batched_args), out_shardings=sharding
        )
        ent = (jfn, sharding)
        _JIT_CACHE[key] = ent
    jfn, sharding = ent
    placed = tuple(jax.device_put(a, sharding) for a in batched_args)
    return jfn(*placed)


# Memoized jitted pad fn per (arity, target batch, per-arg shapes/dtypes):
# the cohort dispatch pads every fused batch to its power-of-two bucket, so
# without the cache each dispatch would re-trace a fresh concatenate per arg.
_PAD_CACHE: dict = {}


def pad_batch(batched_args: tuple, batch: int) -> tuple:
    """Pad a batched args tuple to `batch` lanes by replicating the LAST
    real member's lane on device.

    This is the cached pad-member path `replicate_args` lacks: the inputs
    are already device-resident (argument-arena buffers), and the pad lanes
    are broadcast views of the last real row — zero host→device bytes, no
    TransferLedger traffic. Decode discards the pad lanes (only real members
    are fanned out), so their content only needs to be a valid solve, which
    the replicated member trivially is."""
    if not batched_args:
        return tuple(batched_args)
    b = int(batched_args[0].shape[0])
    if b >= batch:
        return tuple(batched_args)
    key = (
        len(batched_args),
        int(batch),
        tuple((tuple(a.shape), str(a.dtype)) for a in batched_args),
    )
    fn = _PAD_CACHE.get(key)
    if fn is None:
        pad = batch - b

        def _pad(args):
            return tuple(
                jnp.concatenate(
                    [a, jnp.broadcast_to(a[-1:], (pad,) + a.shape[1:])]
                )
                for a in args
            )

        fn = jax.jit(_pad)
        _PAD_CACHE[key] = fn
    return tuple(fn(tuple(batched_args)))


# -- process-spanning meshes (ISSUE 18; SPEC.md "Federation semantics") ------
#
# Everything above assumes jax.process_count() == 1: jax.devices() is the
# whole world and any contiguous slice of it is a valid 1-D mesh. On a pod
# slice (jax.distributed initialized, SNIPPETS [2]) jax.devices() is the
# GLOBAL device list in process-major order, and a mesh that does not take
# the same number of devices from every process silently places some
# processes' addressable shards under another process's blocks — the solve
# "works" and returns garbage block boundaries. These helpers are the
# fail-closed construction path: a grid the processes cannot divide evenly
# raises a typed MeshConstructionError instead of building a wrong mesh.


class MeshConstructionError(RuntimeError):
    """Process-spanning mesh construction failed fail-closed: the requested
    device grid cannot be divided evenly across the participating processes
    (or the sharding arguments to a mesh call were inconsistent). Callers
    must fall back to the single-process path or fix the topology — never
    proceed with a silently-wrong mesh."""


def init_distributed(coordinator_address: str, num_processes: int,
                     process_id: int) -> None:
    """jax.distributed.initialize wrapper for the multi-host mesh solve.

    Must run before the first jax backend touch (jax fixes its device list
    at first init). Raises MeshConstructionError when the runtime has no
    distributed support rather than letting a later mesh build half-connect.
    """
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except Exception as e:  # noqa: BLE001 — typed fail-closed surface
        raise MeshConstructionError(
            f"jax.distributed.initialize failed for "
            f"{coordinator_address} ({process_id}/{num_processes}): {e}"
        ) from e


def make_process_mesh(n_shards: Optional[int] = None, axis: str = "shards"):
    """1-D mesh whose `axis` spans every participating process, plus the
    contiguous block range this process owns.

    Returns `(mesh, (lo, hi))`: blocks `[lo, hi)` of the `n_shards`-wide
    grid are addressable from THIS process (its rows of a
    `PartitionSpec(axis, None)` array live on local devices). Single-process
    degenerates to `make_mesh` with the full range — byte-identical to the
    legacy path.

    Fail-closed validation (the satellite contract): with
    `jax.process_count() > 1`, every process must contribute the same
    number of devices and `n_shards` must divide evenly across processes;
    anything else raises MeshConstructionError instead of building a mesh
    whose block boundaries straddle process boundaries."""
    nproc = int(jax.process_count())
    if nproc <= 1:
        m = make_mesh(n_shards, axis=axis)
        return m, (0, int(m.devices.size))
    devs = jax.devices()  # global, process-major
    by_proc: dict = {}
    for d in devs:
        by_proc.setdefault(int(d.process_index), []).append(d)
    sizes = {p: len(v) for p, v in sorted(by_proc.items())}
    if len(set(sizes.values())) != 1:
        raise MeshConstructionError(
            f"devices do not divide the grid: per-process device counts "
            f"are uneven ({sizes}) — a 1-D run axis cannot split into "
            f"equal contiguous per-process blocks"
        )
    total = len(devs)
    n = int(n_shards) if n_shards else total
    if n % nproc:
        raise MeshConstructionError(
            f"devices do not divide the grid: n_shards={n} is not a "
            f"multiple of process_count={nproc}"
        )
    per = n // nproc
    if per > min(sizes.values()):
        raise MeshConstructionError(
            f"devices do not divide the grid: n_shards={n} needs {per} "
            f"devices per process but processes hold "
            f"{min(sizes.values())}"
        )
    # process-major contiguous layout: process p owns blocks
    # [p*per, (p+1)*per) — exactly the run-block slices the host-side
    # stitch walks left-to-right (backend._shard_stitch)
    chosen = []
    for p in sorted(by_proc):
        chosen.extend(by_proc[p][:per])
    mesh = Mesh(np.asarray(chosen), (axis,))
    pid = int(jax.process_index())
    return mesh, (pid * per, (pid + 1) * per)


def put_process_sharded(mesh: Mesh, arr, lo: int, hi: int):
    """Adopt a `[Nd, ...]` block-partitioned array onto a process-spanning
    mesh by uploading ONLY the local partition's run blocks.

    Each process device_puts rows `[lo, hi)` onto its own mesh devices and
    assembles the global array from the single-device shards
    (jax.make_array_from_single_device_arrays) — no process materializes or
    uploads another host's blocks, which is what keeps per-process arena
    residency bounded by the local partition. Single-process falls through
    to a plain sharded device_put (identical placement, one call)."""
    axis = mesh.axis_names[0]
    spec = P(axis, *([None] * (arr.ndim - 1)))
    sharding = NamedSharding(mesh, spec)
    if int(jax.process_count()) <= 1:
        return jax.device_put(arr, sharding)
    local = [d for d in mesh.devices.flat
             if int(d.process_index) == int(jax.process_index())]
    if len(local) != hi - lo:
        raise MeshConstructionError(
            f"local partition [{lo}, {hi}) does not match the "
            f"{len(local)} local mesh devices"
        )
    shards = [jax.device_put(np.asarray(arr[i:i + 1]), d)
              for i, d in zip(range(lo, hi), local)]
    return jax.make_array_from_single_device_arrays(
        tuple(arr.shape), sharding, shards
    )


def mesh_sharded_call(mesh: Mesh, fn, in_shardings=None, out_shardings=None):
    """Compile `fn` for `mesh` with explicit shardings, or fall back to
    shard_map when no shardings are given (SNIPPETS [3] idiom).

    Passing exactly ONE of in_shardings/out_shardings is the classic
    half-specified pjit bug — the unspecified side gets inferred layouts
    that differ across jax versions — so it raises MeshConstructionError:
    pass both sharding arguments or omit them to use the shard_map
    fallback. The fallback maps `fn` per-shard over the mesh's first axis
    (inputs and outputs block-partitioned on their leading dim), which is
    the portable path for runtimes whose pjit cannot place a
    process-spanning NamedSharding."""
    if (in_shardings is None) != (out_shardings is None):
        raise MeshConstructionError(
            "one-sided shardings: pass both sharding arguments or omit "
            "them to use the shard_map fallback"
        )
    if in_shardings is not None:
        return jax.jit(
            fn, in_shardings=in_shardings, out_shardings=out_shardings
        )
    from jax.experimental.shard_map import shard_map

    axis = mesh.axis_names[0]
    spec = P(axis)
    mapped = shard_map(
        fn, mesh=mesh, in_specs=spec, out_specs=spec, check_rep=False
    )
    return jax.jit(mapped)


def replicate_args(args: tuple, batch: int, sharding=None) -> tuple:
    """Tile single-solve args to a batch (test/dryrun helper).

    Each base array uploads ONCE and broadcasts ON DEVICE — the former
    `np.broadcast_to(...).copy()` materialized a full [B, ...] host copy
    per arg, an O(batch) host-memory blowup at width 64+. Device-resident
    inputs (argument-arena buffers) skip the upload entirely; pass a
    NamedSharding to place the broadcast rows directly on a mesh.

    When the args are ALREADY batched and only pad lanes are needed (the
    cohort dispatch rounding up to its batch bucket), use `pad_batch` — it
    reuses the last real member's device buffers for the pad lanes instead
    of broadcasting the full tuple."""
    out = []
    for a in args:
        base = jnp.asarray(a)
        b = jnp.broadcast_to(base[None], (batch,) + base.shape)
        if sharding is not None:
            b = jax.device_put(b, sharding)
        out.append(b)
    return tuple(out)
