"""Virtual multi-process host mesh: subprocess workers for the multi-host
solve paths (ISSUE 18; SPEC.md "Federation semantics").

A TPU pod slice runs one jax process per host; this module is the
hardware-free stand-in that keeps the multi-process code paths runnable and
benchable on a dev box. Each worker is a REAL separate process (fresh
interpreter, own jax runtime pinned to CPU, own memory) speaking a
length-prefixed pickle protocol over its stdin/stdout pipes. Two job kinds:

- ``ffd_blocks`` — the mesh-solve leg: the worker scans its contiguous
  slice of the run-axis blocks (the same vmap-of-``ffd_solve`` lane body
  ``ffd_solve_sharded`` runs per device) and returns the lane-local
  FFDOutput; the parent stitches blocks host-side exactly as it would for
  an in-process mesh (backend._shard_stitch).
- ``solve`` — the federation leg: the worker holds a resident
  ReferenceSolver and serves whole solves, so a FederationRouter's hosts
  are genuinely separate processes and a host kill is a real SIGKILL.

The broadcast tables of an ``ffd_blocks`` job are cached worker-side under
a caller-chosen ``ctx`` token (the pipe analog of argument-arena
residency): repeat dispatches against the same context ship only the run
blocks.

jax fixes its device list at first backend init, so a parent that already
initialized jax can never emulate N hosts in-process — the subprocess
boundary here is load-bearing, not a convenience.
"""

from __future__ import annotations

import os
import pickle
import struct
import subprocess
import sys
import threading
from typing import Dict, List, Optional

_LEN = struct.Struct("<Q")


class WorkerDead(RuntimeError):
    """The worker process is gone (EOF/broken pipe mid-call): the caller
    must treat every outstanding job on this worker as failed and fail the
    host over — jobs are never silently retried here."""


def _write_frame(fh, obj) -> None:
    blob = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    fh.write(_LEN.pack(len(blob)))
    fh.write(blob)
    fh.flush()


def _read_exact(fh, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = fh.read(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def _read_frame(fh):
    head = _read_exact(fh, _LEN.size)
    if head is None:
        return None
    blob = _read_exact(fh, _LEN.unpack(head)[0])
    if blob is None:
        return None
    return pickle.loads(blob)


# -- worker side --------------------------------------------------------------


def _handle_ffd_blocks(job, ctx_cache: dict, jit_cache: dict):
    """Scan this worker's run blocks: vmap the UNJITTED ffd_solve lane over
    the [nb, Sblk] block axis with the broadcast tables closed over — the
    same lane body ffd_solve_sharded traces per mesh device."""
    import functools

    import jax
    import numpy as np

    from ..solver.tpu.ffd import ffd_solve, ffd_solve_sparse

    ctx = job.get("ctx")
    rest = job.get("rest")
    if rest is not None and ctx is not None:
        ctx_cache[ctx] = rest
    elif rest is None:
        rest = ctx_cache[ctx]
    rg = np.asarray(job["rg"])
    rc = np.asarray(job["rc"])
    max_claims = int(job["max_claims"])
    zone = bool(job.get("zone_engine", False))
    sq = job.get("sq")
    sv = job.get("sv")
    sparse_shapes = None
    if sq is not None:
        sq, sv = np.asarray(sq), np.asarray(sv)
        sparse_shapes = (sq.shape, sv.shape)
    key = (
        ctx, max_claims, rg.shape, zone, sparse_shapes,
        tuple((a.shape, str(a.dtype)) for a in rest),
    )
    fn = jit_cache.get(key)
    if fn is None:
        if sq is not None:
            lane = functools.partial(
                ffd_solve_sparse.__wrapped__,
                max_claims=max_claims, zone_engine=zone,
            )
            fn = jax.jit(jax.vmap(
                lambda q, v, g, c: lane(q, v, g, c, *rest)))
        else:
            lane = functools.partial(
                ffd_solve.__wrapped__,
                max_claims=max_claims, zone_engine=zone,
            )
            fn = jax.jit(jax.vmap(lambda g, c: lane(g, c, *rest)))
        jit_cache[key] = fn
    out = fn(sq, sv, rg, rc) if sq is not None else fn(rg, rc)
    return jax.tree_util.tree_map(np.asarray, out)


def worker_main(stdin=None, stdout=None) -> int:
    """Job loop: read a frame, run it, answer {"ok": ..., ...}. stdout is
    the protocol channel — anything chatty must go to stderr."""
    inb = stdin if stdin is not None else sys.stdin.buffer
    outb = stdout if stdout is not None else sys.stdout.buffer
    solver = None
    ctx_cache: dict = {}
    jit_cache: dict = {}
    while True:
        job = _read_frame(inb)
        if job is None or job.get("kind") == "exit":
            return 0
        try:
            kind = job.get("kind")
            if kind == "ping":
                result = {"pid": os.getpid()}
            elif kind == "ffd_blocks":
                result = _handle_ffd_blocks(job, ctx_cache, jit_cache)
            elif kind == "solve":
                if solver is None:
                    from ..solver.backend import ReferenceSolver

                    solver = ReferenceSolver()
                result = solver.solve(job["inp"])
                # simulated device-residency window: a TPU host spends most
                # of each solve waiting on the device with its CPU free —
                # the federation bench uses this so host scaling is
                # measurable even on a single-core dev box (where N
                # CPU-bound workers would just time-share one core)
                device_ms = job.get("device_ms")
                if device_ms:
                    import time

                    time.sleep(float(device_ms) / 1000.0)
            else:
                raise ValueError(f"unknown job kind: {kind!r}")
            reply = {"ok": True, "result": result}
        except BaseException as e:  # noqa: BLE001 — reply, don't die
            reply = {"ok": False, "error": f"{type(e).__name__}: {e}"}
        _write_frame(outb, reply)


# -- parent side --------------------------------------------------------------


class WorkerProc:
    """One worker host: a subprocess with its own jax runtime (CPU-pinned)
    behind a framed pickle pipe. Calls serialize per worker; workers are
    independent, so a pool issues to all of them concurrently."""

    def __init__(self, name: str = "host", env: Optional[Dict[str, str]] = None):
        self.name = name
        wenv = os.environ.copy()
        # the worker is a virtual HOST: its jax world is its own CPU device,
        # never the parent's accelerator (which the parent may hold open)
        wenv["JAX_PLATFORMS"] = "cpu"
        wenv.pop("XLA_FLAGS", None)
        if env:
            wenv.update(env)
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "karpenter_tpu.parallel.hostmesh"],
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            env=wenv,
        )
        self._lock = threading.Lock()
        self._ctx_seen: set = set()

    def alive(self) -> bool:
        return self.proc.poll() is None

    def call(self, job: dict):
        """Round-trip one job; raises WorkerDead on a broken pipe/EOF (a
        killed host), RuntimeError on a job-level failure."""
        return self._roundtrip(
            pickle.dumps(job, protocol=pickle.HIGHEST_PROTOCOL)
        )

    def call_pickled(self, blob: bytes):
        """Round-trip a PRE-SERIALIZED job frame: a caller issuing the same
        job many times (the federation soak's churn loop) pays the pickle
        cost once instead of per call — the parent's GIL share per solve
        drops to the pipe write."""
        return self._roundtrip(blob)

    def _roundtrip(self, blob: bytes):
        with self._lock:
            if not self.alive():
                raise WorkerDead(f"{self.name}: worker exited")
            try:
                self.proc.stdin.write(_LEN.pack(len(blob)))
                self.proc.stdin.write(blob)
                self.proc.stdin.flush()
                reply = _read_frame(self.proc.stdout)
            except (BrokenPipeError, OSError) as e:
                raise WorkerDead(f"{self.name}: {e}") from e
        if reply is None:
            raise WorkerDead(f"{self.name}: EOF mid-call")
        if not reply.get("ok"):
            raise RuntimeError(f"{self.name}: {reply.get('error')}")
        return reply.get("result")

    def kill(self) -> None:
        try:
            self.proc.kill()
        except OSError:
            pass

    def close(self) -> None:
        if self.alive():
            try:
                with self._lock:
                    _write_frame(self.proc.stdin, {"kind": "exit"})
            except (BrokenPipeError, OSError):
                pass
        try:
            self.proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            self.kill()


def _tree_concat(parts: list):
    """Concatenate a list of structurally-identical (possibly nested)
    namedtuple-of-ndarray trees along axis 0 — the parent-side gather that
    reassembles per-worker block slices into the [Nd, ...] stacked shape
    ffd_solve_sharded would have returned."""
    import numpy as np

    first = parts[0]
    if hasattr(first, "_fields"):
        return type(first)(*(
            _tree_concat([getattr(p, f) for p in parts])
            for f in first._fields
        ))
    return np.concatenate([np.asarray(p) for p in parts], axis=0)


class HostMeshPool:
    """N worker hosts forming a virtual 1-D host mesh over the run axis.

    `scatter_blocks` splits the [Nd, Sblk] block tables into contiguous
    per-host slices (the process-major layout make_process_mesh pins),
    dispatches them concurrently, and gathers the lane outputs back into
    one [Nd, ...] FFDOutput tree for the parent's stitch. Broadcast tables
    ride once per (host, ctx) and are served from the worker-side cache on
    repeat dispatches."""

    def __init__(self, n_hosts: int = 2, name_prefix: str = "host"):
        if n_hosts < 1:
            raise ValueError("n_hosts must be >= 1")
        self.workers: List[WorkerProc] = [
            WorkerProc(f"{name_prefix}{i}") for i in range(n_hosts)
        ]

    @property
    def width(self) -> int:
        return len(self.workers)

    def ping_all(self) -> List[dict]:
        return [w.call({"kind": "ping"}) for w in self.workers]

    def scatter_blocks(self, rgb, rcb, rest: tuple, max_claims: int,
                       ctx: Optional[str] = None, zone_engine: bool = False,
                       sqb=None, svb=None):
        import numpy as np

        rgb = np.asarray(rgb)
        rcb = np.asarray(rcb)
        Nd = int(rgb.shape[0])
        n = self.width
        if Nd % n:
            raise ValueError(f"{Nd} blocks do not divide across {n} hosts")
        per = Nd // n
        results: list = [None] * n
        errors: list = []

        def _dispatch(i: int) -> None:
            w = self.workers[i]
            send_rest = rest
            if ctx is not None and ctx in w._ctx_seen:
                send_rest = None
            try:
                results[i] = w.call({
                    "kind": "ffd_blocks",
                    "rg": rgb[i * per:(i + 1) * per],
                    "rc": rcb[i * per:(i + 1) * per],
                    "rest": send_rest,
                    "ctx": ctx,
                    "max_claims": int(max_claims),
                    "zone_engine": bool(zone_engine),
                    "sq": None if sqb is None
                    else sqb[i * per:(i + 1) * per],
                    "sv": None if svb is None
                    else svb[i * per:(i + 1) * per],
                })
                if ctx is not None:
                    w._ctx_seen.add(ctx)
            except BaseException as e:  # noqa: BLE001 — gathered below
                errors.append((i, e))

        threads = [
            threading.Thread(target=_dispatch, args=(i,), daemon=True)
            for i in range(n)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if errors:
            raise errors[0][1]
        return _tree_concat(results)

    def solve(self, host: int, inp):
        return self.workers[host].call({"kind": "solve", "inp": inp})

    def kill(self, host: int) -> None:
        self.workers[host].kill()

    def close(self) -> None:
        for w in self.workers:
            w.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


if __name__ == "__main__":
    sys.exit(worker_main())
