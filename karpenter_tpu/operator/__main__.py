"""karpenter-tpu controller binary (kwok configuration).

The stand-in for kwok/main.go:32-100: flags -> operator wiring -> metrics +
health endpoints -> controller loop. Runs the full hermetic control plane; a
demo NodePool and pods can be injected via --demo for a self-contained
smoke run.

Usage:
    python -m karpenter_tpu.operator [--solver-backend tpu|reference]
                                     [--metrics-port 8080] [--demo]
"""

from __future__ import annotations

import json
import logging
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..controllers import store as st
from ..metrics.registry import REGISTRY
from ..obs import anomaly as obsanomaly
from ..obs import explain as obsexplain
from ..obs import slo as obsslo
from ..obs import telemetry as obstelemetry
from ..obs import trace as obstrace
from ..obs.export import chrome_trace
from ..obs.logjson import JsonLogFormatter
from ..obs.recorder import FlightRecorder
from ..solver.backend import ReferenceSolver, TPUSolver
from . import options as opts
from .operator import new_kwok_operator


def serve_endpoints(port: int, health_port: int, enable_profiling: bool = False):
    """Prometheus metrics + health probes (operator manager equivalents);
    /debug/pprof/* sampling profiler behind --enable-profiling
    (settings.md:23); /debug/trace Chrome-trace export of recent solves."""

    class MetricsHandler(BaseHTTPRequestHandler):
        def do_GET(self):
            if self.path == "/metrics":
                body = REGISTRY.expose().encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
                self.end_headers()
                self.wfile.write(body)
            elif self.path in ("/healthz", "/readyz"):
                rec = obstrace.recorder()
                slo = obsslo.health()
                telem = obstelemetry.health()
                anom = obsanomaly.health()
                # worst-of across the health planes: SLO burn rates can
                # "page"; telemetry (hot-path recompiles, prewarm gaps) and
                # anomaly (baseline deviation) contribute "warn"
                rank = {"ok": 0, "warn": 1, "page": 2}
                status = max(
                    (slo["state"], telem["state"], anom["state"]),
                    key=lambda s: rank.get(s, 0),
                )
                body = json.dumps({
                    "status": status,
                    "flight_recorder": rec.health() if rec is not None else None,
                    # per-stage SLO burn-rate state (obs/slo.py): "ok" |
                    # "warn" | "page" overall, per-stage fast/slow rates
                    "slo": slo,
                    # runtime health plane (obs/telemetry.py + anomaly.py):
                    # compile/prewarm state + rolling-baseline deviations
                    "telemetry": telem,
                    "anomaly": anom,
                    # streaming delta-solve health when the operator
                    # registered its provider (journal lag, re-baselines)
                    "streaming": obstelemetry.provider_result("streaming"),
                    # solver vault health when a vault is wired (snapshot
                    # age/size, restore counters — solver/vault.py)
                    "vault": obstelemetry.provider_result("vault"),
                    # federation health when a router is wired (healthy
                    # hosts, replication lag — solver/federation.py)
                    "federation": obstelemetry.provider_result("federation"),
                }, default=str).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.end_headers()
                self.wfile.write(body)
            elif self.path.startswith("/debug/vars"):
                # in-process telemetry ring (obs/telemetry.py): the current
                # snapshot plus the last ?window= ring samples — JSON for
                # dashboards/dumps, 400 on a bad param like /debug/trace
                _, _, query = self.path.partition("?")
                window = None
                for part in query.split("&"):
                    if not part:
                        continue
                    key, _, val = part.partition("=")
                    if key == "window":
                        try:
                            window = max(1, int(val))
                        except ValueError:
                            self.send_response(400)
                            self.end_headers()
                            self.wfile.write(b"bad window\n")
                            return
                body = json.dumps(
                    obstelemetry.debug_vars(window), default=str
                ).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.end_headers()
                self.wfile.write(body)
            elif self.path.startswith("/debug/trace"):
                # Perfetto-loadable dump of the last N finished traces plus
                # every still-open (in-flight or wedged) solve; filterable
                # to one solve (?solve_id=) or one tenant's lanes (?tenant=)
                _, _, query = self.path.partition("?")
                last = None
                solve_id = tenant = None
                for part in query.split("&"):
                    if not part:
                        continue
                    key, _, val = part.partition("=")
                    if key == "last":
                        try:
                            last = max(1, int(val))
                        except ValueError:
                            self.send_response(400)
                            self.end_headers()
                            self.wfile.write(b"bad last\n")
                            return
                    elif key == "solve_id":
                        if not val:
                            self.send_response(400)
                            self.end_headers()
                            self.wfile.write(b"bad solve_id\n")
                            return
                        solve_id = val
                    elif key == "tenant":
                        if not val:
                            self.send_response(400)
                            self.end_headers()
                            self.wfile.write(b"bad tenant\n")
                            return
                        tenant = val
                traces = obstrace.recent(last) + obstrace.active_traces()
                if solve_id is not None:
                    traces = [t for t in traces if t.solve_id == solve_id]
                if tenant is not None:
                    traces = [t for t in traces if t.tenant_id == tenant]
                body = json.dumps(chrome_trace(traces)).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.end_headers()
                self.wfile.write(body)
            elif self.path.startswith("/debug/explain"):
                # decision provenance (obs/explain.py): ?solve_id= returns
                # that solve's record (404 when evicted/unknown), ?pod=
                # every retained record mentioning the pod, bare = the
                # most recent records
                _, _, query = self.path.partition("?")
                solve_id = pod = None
                for part in query.split("&"):
                    if not part:
                        continue
                    key, _, val = part.partition("=")
                    if key == "solve_id":
                        if not val:
                            self.send_response(400)
                            self.end_headers()
                            self.wfile.write(b"bad solve_id\n")
                            return
                        solve_id = val
                    elif key == "pod":
                        if not val:
                            self.send_response(400)
                            self.end_headers()
                            self.wfile.write(b"bad pod\n")
                            return
                        pod = val
                store = obsexplain.store()
                if solve_id is not None:
                    payload = store.get(solve_id)
                    if payload is None:
                        self.send_response(404)
                        self.end_headers()
                        self.wfile.write(b"unknown solve_id\n")
                        return
                elif pod is not None:
                    payload = store.by_pod(pod)
                else:
                    payload = store.recent(16)
                body = json.dumps(
                    {"enabled": obsexplain.enabled(), "result": payload},
                    default=str,
                ).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.end_headers()
                self.wfile.write(body)
            elif self.path.startswith("/debug/pprof/") and enable_profiling:
                from . import profiling

                path, _, query = self.path.partition("?")
                status, body = profiling.handle(path, query)
                self.send_response(status)
                self.send_header("Content-Type", "text/plain")
                self.end_headers()
                self.wfile.write(body.encode())
            else:
                self.send_response(404)
                self.end_headers()

        def log_message(self, *a):  # quiet
            pass

    srv = ThreadingHTTPServer(("127.0.0.1", port), MetricsHandler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


def main(argv=None) -> int:
    o = opts.parse(argv if argv is not None else sys.argv[1:])
    logging.basicConfig(
        level=getattr(logging, o.log_level.upper(), logging.INFO),
        format="%(asctime)s %(levelname)s %(name)s %(message)s",
    )
    if o.log_format == "json":
        for h in logging.getLogger().handlers:
            h.setFormatter(JsonLogFormatter())
    obstrace.configure(
        enabled=o.solver_tracing,
        ring=o.trace_ring_size,
        recorder=FlightRecorder(dir=o.flight_recorder_dir or None,
                                keep=o.flight_recorder_keep),
    )
    obsexplain.configure(enabled=o.solver_explain, top_k=o.explain_top_k,
                         ring=o.explain_ring_size)
    obsslo.configure(objectives=obsslo.parse_objectives(o.slo_objectives))
    # runtime health plane: compile observability + telemetry ring
    # (--telemetry) and rolling-baseline anomaly detection, threshold from
    # --anomaly-threshold (validated > 1.0 in options.parse)
    obstelemetry.configure(enabled=o.telemetry)
    obsanomaly.configure(enabled=o.telemetry, multiplier=o.anomaly_threshold)
    log = logging.getLogger("karpenter_tpu")
    # "ffd" aliases "tpu" (the greedy device kernel); "convex" layers the
    # global ADMM backend over that same kernel (solver/convex.py), so all
    # three are device-backed — only "reference" runs the host oracle
    device_backed = o.solver_backend in ("tpu", "ffd", "convex")
    solver = (
        TPUSolver(arena=o.solver_arena, resume=o.solver_resume,
                  ckpt_every=o.resume_checkpoint_interval,
                  device_decode=o.solver_device_decode,
                  relax_ladder=o.solver_relax_ladder,
                  arena_budget_mb=o.arena_budget_mb)
        if device_backed
        else ReferenceSolver()
    )
    op = new_kwok_operator(
        solver=solver,
        solver_convex=o.solver_backend == "convex",
        convex_max_iters=o.convex_max_iters,
        convex_tolerance=o.convex_tolerance,
        batch_idle_s=o.batch_idle_duration_s,
        batch_max_s=o.batch_max_duration_s,
        rate_limits=o.kwok_rate_limits,
        preference_policy=o.preference_policy,
        snapshot_path=o.snapshot_path or None,
        snapshot_interval_s=o.snapshot_interval_s,
        warm_start=o.warm_start and device_backed,
        aot_prewarm=o.aot_prewarm and device_backed,
        prewarm_scale_pods=o.prewarm_scale_pods,
        compile_cache_dir=o.compile_cache_dir or None,
        leader_elect=o.leader_elect,
        lease_path=o.lease_path or None,
        resilient=o.solver_resilient,
        solver_deadline_s=o.solver_deadline_s,
        breaker_threshold=o.solver_breaker_threshold,
        breaker_probe_s=o.solver_breaker_probe_s,
        solver_pipeline=o.solver_pipeline,
        pipeline_depth=o.pipeline_depth,
        probe_batch_max=o.probe_batch_max,
        solver_fleet_size=o.solver_fleet_size,
        canary_interval_s=o.canary_interval_s,
        fence_after_misses=o.fence_after_misses,
        solver_preemption=o.solver_preemption,
        solver_gang=o.solver_gang,
        solver_tenants=o.solver_tenants,
        tenant_weights=o.tenant_weights,
        tenant_max_queue_depth=o.tenant_max_queue_depth,
        solver_cohort=o.solver_cohort,
        solver_cohort_max=o.solver_cohort_max,
        solver_streaming=o.solver_streaming,
        streaming_epoch_every=o.streaming_epoch_every,
        solver_vault_dir=o.solver_vault_dir or None,
        vault_interval_s=o.vault_interval_s,
        vault_keep=o.vault_keep,
        federation_hosts=o.federation_hosts,
        federation_self=o.federation_self,
        journal_replicate=o.journal_replicate,
    )
    serve_endpoints(o.metrics_port, o.health_probe_port,
                    enable_profiling=o.enable_profiling)
    log.info("karpenter-tpu starting: solver=%s metrics=:%d", o.solver_backend, o.metrics_port)

    if o.demo:
        _inject_demo(op, log)

    op.manager.run(interval_s=0.5)
    try:
        while True:
            time.sleep(5)
            log.info(
                "nodes=%d nodeclaims=%d pending=%d",
                len(op.store.list(st.NODES)),
                len(op.store.list(st.NODECLAIMS)),
                len(op.cluster.pending_pods()),
            )
    except KeyboardInterrupt:
        op.manager.stop()
        return 0


def _inject_demo(op, log) -> None:
    from ..api.objects import NodePool, NodeClaimTemplate, ObjectMeta, Pod
    from ..utils.resources import Resources

    op.store.create(st.NODEPOOLS, NodePool(meta=ObjectMeta(name="demo"), template=NodeClaimTemplate()))
    for i in range(10):
        op.store.create(
            st.PODS,
            Pod(
                meta=ObjectMeta(name=f"demo-{i}", uid=f"demo-{i}"),
                requests=Resources.parse({"cpu": "500m", "memory": "512Mi"}),
            ),
        )
    log.info("injected demo nodepool + 10 pods")


if __name__ == "__main__":
    sys.exit(main() or 0)
