"""Operator: dependency wiring for the full control loop.

The stand-in for cmd/controller/main.go + kwok/main.go (SURVEY.md §3.5):
builds the store, fake cloud, cloud provider, cluster state, solver backend,
and registers every controller on the deterministic manager. `new_kwok_operator`
is the hermetic configuration used by tests and benchmarks (the reference's
kwok binary, kwok/main.go:32-100).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Optional, Sequence

from ..catalog.catalog import CatalogSpec, generate
from ..cloudprovider.types import InstanceType
from ..controllers import store as st
from ..controllers.binder import Binder
from ..controllers.garbagecollection import GarbageCollectionController
from ..controllers.podgc import PodGCController
from ..controllers.capacityreservation import CapacityReservationFlipController
from ..controllers.interruption import InterruptionController, InterruptionQueue
from ..controllers.manager import Manager
from ..controllers.nodeclass import DriftController, NodeClassController
from ..providers.capacityreservation import CapacityReservationProvider
from ..kwok.cloud import KwokCloud
from ..kwok.cloudprovider import KwokCloudProvider
from ..lifecycle.controller import (
    ExpirationController,
    InitializationController,
    LaunchController,
    LivenessController,
    RegistrationController,
)
from ..lifecycle.repair import RepairController
from ..obs import telemetry as obstelemetry
from ..provisioning.provisioner import Provisioner
from ..solver.backend import ReferenceSolver, Solver, TPUSolver
from ..state.cluster import Cluster
from ..termination.controller import TerminationController


@dataclass
class Operator:
    store: st.Store
    cloud: KwokCloud
    cloud_provider: KwokCloudProvider
    cluster: Cluster
    provisioner: Provisioner
    manager: Manager
    solver: Solver
    interruption_queue: InterruptionQueue = field(default_factory=InterruptionQueue)
    solve_service: Optional[object] = None  # solver/pipeline.py SolveService
    tenant_mux: Optional[object] = None  # solver/tenancy.py TenantMux
    recorder: Optional[object] = None  # events/recorder.py Recorder
    preemption: Optional[object] = None  # provisioning/preemption.py
    streaming: Optional[object] = None  # solver/streaming.py StreamingSolver
    vault: Optional[object] = None  # solver/vault.py SolverStateVault
    federation: Optional[object] = None  # solver/federation.py FederationRouter
    replicator: Optional[object] = None  # solver/federation.py JournalReplicator


def new_kwok_operator(
    instance_types: Optional[Sequence[InstanceType]] = None,
    solver: Optional[Solver] = None,
    batch_idle_s: float = 0.0,
    batch_max_s: float = 0.0,
    rate_limits: bool = False,
    clock=time.monotonic,
    disruption: bool = True,
    preference_policy: str = "Respect",
    snapshot_path: Optional[str] = None,
    snapshot_interval_s: float = 5.0,
    warm_start: bool = False,
    aot_prewarm: bool = False,
    prewarm_scale_pods: int = 50_000,
    compile_cache_dir: Optional[str] = None,
    leader_elect: bool = False,
    identity: str = "",
    lease_path: Optional[str] = None,
    lease_s: float = 15.0,
    renew_s: float = 10.0,
    shared_store: Optional[st.Store] = None,
    shared_cloud: Optional[KwokCloud] = None,
    resilient: bool = True,
    solver_deadline_s: float = 0.0,
    breaker_threshold: int = 3,
    breaker_probe_s: float = 30.0,
    solver_pipeline: bool = True,
    pipeline_depth: int = 2,
    probe_batch_max: int = 512,
    solver_fleet_size: int = 1,
    canary_interval_s: float = 5.0,
    fence_after_misses: int = 2,
    canary_deadline_s: float = 5.0,
    solver_preemption: bool = True,
    solver_gang: bool = True,
    solver_convex: bool = False,
    convex_max_iters: int = 400,
    convex_tolerance: float = 1e-3,
    solver_tenants: str = "",
    tenant_weights: str = "",
    tenant_max_queue_depth: int = 64,
    solver_cohort: bool = True,
    solver_cohort_max: int = 8,
    solver_streaming: bool = False,
    streaming_epoch_every: int = 64,
    solver_vault_dir: Optional[str] = None,
    vault_interval_s: float = 5.0,
    vault_keep: int = 3,
    federation_hosts: str = "",
    federation_self: str = "",
    journal_replicate: bool = False,
) -> Operator:
    store = shared_store if shared_store is not None else st.Store()
    # the operator's clock is authoritative for every age stamp, including a
    # shared store/cloud handed in by an HA peer — one clock per deployment
    store.clock = clock
    from ..api.validation import admission_validator

    store.set_validator(st.NODEPOOLS, admission_validator)
    store.set_validator(st.NODECLAIMS, admission_validator)
    types = list(instance_types) if instance_types is not None else generate(CatalogSpec())
    cloud = (
        shared_cloud
        if shared_cloud is not None
        else KwokCloud(store, types, rate_limits=rate_limits, clock=clock)
    )
    cloud.clock = clock  # same one-clock rule as store.clock above
    from ..providers.discovered import (
        DiscoveredCapacityCache,
        DiscoveredCapacityController,
    )

    discovered = DiscoveredCapacityCache()
    if snapshot_path is not None:
        # restore BEFORE any controller runs: the reference's kwok provider
        # hydrates instances from ConfigMaps at boot (kwok/ec2/ec2.go:112-232)
        from ..controllers.snapshot import restore_snapshot

        restore_snapshot(store, cloud, snapshot_path, now=clock())
    reservations = CapacityReservationProvider(clock=clock)
    cloud_provider = KwokCloudProvider(
        cloud, types, reservations=reservations, discovered=discovered
    )
    # metrics decorator (metrics.Decorate analog, main.go:42): every
    # CloudProvider call records duration + errors transparently
    from ..cloudprovider.metrics import decorate

    cloud_provider = decorate(cloud_provider)
    cluster = Cluster(store, clock=clock)
    solver = solver or ReferenceSolver()
    if solver_convex:
        # global-optimization backend (solver/convex.py): layered directly
        # over the configured backend — INSIDE the resilience wrap so the
        # invariant gate judges convex outputs and a device failure still
        # walks the fallback chain. Off (the default) = this line never
        # runs and the chain below is byte-identical.
        from ..solver.convex import ConvexSolver

        solver = ConvexSolver(
            solver, max_iters=convex_max_iters, tolerance=convex_tolerance
        )
    if resilient:
        # deadline + failure classification + invariant gate + circuit
        # breaker around whatever backend was configured; transparent on
        # success (solver/resilient.py) and attribute access delegates, so
        # warmup/prewarm/stats below still reach the wrapped backend
        from ..solver.resilient import ResilientSolver

        solver = ResilientSolver(
            solver,
            deadline_s=solver_deadline_s or None,
            breaker_threshold=breaker_threshold,
            breaker_probe_s=breaker_probe_s,
            clock=clock,
        )
    # scheduling classes (solver/scheduling_class.py): configure the module
    # knobs, then wrap the solver seam — OUTSIDE the resilience wrap (a
    # device failure inside a class re-solve still walks the fallback chain)
    # and INSIDE the pipeline/fleet (the service sees one Solver). With both
    # knobs off the wrapper is skipped entirely; with them on it is still
    # provably inert on priority-flat, gang-free batches (verbatim
    # delegation, including the inner async seam).
    from ..solver import scheduling_class as sc

    sc.configure(preemption=solver_preemption, gang=solver_gang)
    if solver_preemption or solver_gang:
        solver = sc.ClassAwareSolver(solver)
    solve_service = None
    fleet = None
    if solver_pipeline and solver_fleet_size >= 2:
        # solver fleet (solver/fleet.py): N independently health-checked
        # owners behind the SolveService surface — owner 0 is the solver
        # configured above; the other owners get a fresh backend of the
        # same kind (own ArgumentArena residency = own virtual host-mesh
        # slot), each behind its own resilience wrap when enabled
        from ..solver.fleet import SolverFleet, default_canary_input

        base_solver = solver

        def _owner_solver(i: int):
            if i == 0:
                return base_solver
            inner = base_solver
            while hasattr(inner, "__dict__") and "inner" in inner.__dict__:
                inner = inner.inner
            try:
                fresh: Solver = type(inner)()
            except Exception:  # noqa: BLE001 — degrade to the oracle owner
                fresh = ReferenceSolver()
            if solver_convex:
                # failover owners carry the same backend choice as owner 0
                from ..solver.convex import ConvexSolver

                fresh = ConvexSolver(
                    fresh, max_iters=convex_max_iters,
                    tolerance=convex_tolerance,
                )
            if resilient:
                from ..solver.resilient import ResilientSolver

                fresh = ResilientSolver(
                    fresh,
                    deadline_s=solver_deadline_s or None,
                    breaker_threshold=breaker_threshold,
                    breaker_probe_s=breaker_probe_s,
                    clock=clock,
                )
            if solver_preemption or solver_gang:
                # failover owners carry the same class semantics as owner 0
                fresh = sc.ClassAwareSolver(fresh)
            return fresh

        solve_service = SolverFleet(
            _owner_solver,
            size=solver_fleet_size,
            depth=pipeline_depth,
            clock=clock,
            canary_input_fn=lambda: default_canary_input(types),
            canary_interval_s=canary_interval_s,
            canary_deadline_s=canary_deadline_s,
            fence_after_misses=fence_after_misses,
            start_monitor=True,
            host=federation_self if federation_hosts else "",
        )
        fleet = solve_service
    elif solver_pipeline:
        # one owner for the device solve seam: controller solves queue
        # through the service's three-stage pipeline (encode ∥ compute ∥
        # decode), provisioning snapshots coalesce, and disruption probes
        # interleave fairly with pending-pod solves (solver/pipeline.py)
        from ..solver.pipeline import SolveService

        solve_service = SolveService(solver, depth=pipeline_depth, clock=clock)
    tenant_mux = None
    if solver_tenants and solve_service is not None:
        # multi-tenant mux (solver/tenancy.py): the operator's own
        # provisioner/disruption controllers become the FIRST registered
        # tenant's view; other clusters' streams attach via
        # tenant_mux.view(id)/submit(...). The mux owns the downstream
        # (close() cascades). Tenancy off = this block never runs and the
        # controllers hold the fleet/pipeline directly, byte-identical.
        from ..solver.tenancy import TenantMux, TenantRegistry

        registry = TenantRegistry.parse(
            solver_tenants, tenant_weights,
            max_queue_depth=tenant_max_queue_depth,
        )
        tenant_mux = TenantMux(
            solve_service, registry,
            breaker_threshold=breaker_threshold,
            breaker_probe_s=breaker_probe_s,
            clock=clock,
            cohort=solver_cohort,
            cohort_max=solver_cohort_max,
        )
        solve_service = tenant_mux.view(registry.first().tenant_id)
    streaming = None
    if solver_streaming:
        # streaming delta-solve (solver/streaming.py, ISSUE 13): the
        # provisioner folds journal event batches into a resident model
        # instead of snapshotting the store, and every TPU backend in the
        # deployment stages run-table edits as device scatters
        from ..solver.streaming import StreamingSolver

        streaming = StreamingSolver(
            cluster, cloud_provider,
            preference_policy=preference_policy,
            epoch_every=streaming_epoch_every, clock=clock,
        )
        # /healthz surfacing: serve_endpoints has no operator reference, so
        # streaming health rides the telemetry provider registry
        obstelemetry.register_provider("streaming", streaming.health)

        def _enable_stream_stage(s) -> None:
            inner = s
            while hasattr(inner, "__dict__") and "inner" in inner.__dict__:
                inner = inner.inner
            if hasattr(inner, "stream_run_events"):
                inner.stream_run_events = True

        _enable_stream_stage(solver)
        if fleet is not None:
            for o in fleet.owners:
                _enable_stream_stage(o.solver)
            # a fence invalidates the owner's arena: the streaming model
            # re-baselines so replays never extend presumed-resident state
            fleet.fence_listeners.append(streaming.on_fence)
    vault = None
    if solver_vault_dir:
        # durable SOLVER resident state (solver/vault.py, ISSUE 17): async
        # snapshots of the device-facing model into the vault dir, restored
        # HERE — before any controller runs — so the first encode adopts
        # the previous process's tables. Fail-closed off: with no dir the
        # vault object never exists and every path below is byte-identical.
        from ..solver.vault import SolverStateVault

        def _arena_of():
            obj = solver
            while obj is not None:
                d = getattr(obj, "__dict__", None) or {}
                if "arena" in d:
                    return d["arena"]
                obj = d.get("inner")
            return None

        vault = SolverStateVault(
            solver_vault_dir,
            interval_s=vault_interval_s,
            keep=vault_keep,
            journal=cluster.journal,
            store=store,
            streaming=streaming,
            arena_fn=_arena_of,
            clock=clock,
        )
        vault.restore(install=True)
        obstelemetry.register_provider("vault", vault.health)
        if fleet is not None:
            # fence recovery re-seeds from the vault instead of degrading
            # cold (solver/fleet.py _fence)
            fleet.vault = vault
    federation = None
    replicator = None
    if federation_hosts and solve_service is not None:
        # federated solver fleets (solver/federation.py, ISSUE 18): this
        # process's whole fleet/mux stack becomes ONE host of a federation;
        # tenants consistent-hash across hosts and a host loss requeues its
        # outstanding solves onto survivors in submission order. Fail-closed
        # off: with no host list the router never exists, the controllers
        # hold the fleet/pipeline/mux directly, byte-identical.
        from ..solver.federation import FederationRouter, JournalReplicator

        if journal_replicate:
            peers = [
                h for h in federation_hosts.split(",")
                if h.strip() and h.strip() != federation_self
            ]
            if peers:
                replicator = JournalReplicator(
                    cluster.journal, peers=[p.strip() for p in peers],
                )
        federation = FederationRouter(
            federation_hosts, self_host=federation_self,
            clock=clock, replicator=replicator,
        )
        federation.attach(federation_self, solve_service)
        obstelemetry.register_provider("federation", federation.health)
        # the controllers now submit THROUGH the router: local un-tenanted
        # traffic still lands on this host (route(None) = self), federated
        # tenants ride to whichever host attach() wires in
        solve_service = federation
    from ..events.recorder import Recorder
    from ..provisioning.preemption import PreemptionController

    recorder = Recorder(clock=clock)
    preemption = PreemptionController(store, recorder=recorder)
    provisioner = Provisioner(
        store,
        cluster,
        cloud_provider,
        solver,
        batch_idle_s=batch_idle_s,
        batch_max_s=batch_max_s,
        clock=clock,
        preference_policy=preference_policy,
        solve_service=solve_service,
        preemption=preemption,
        recorder=recorder,
        streaming=streaming,
    )
    from ..controllers.volume import VolumeTopologyController

    queue = InterruptionQueue()
    elector = None
    if leader_elect:
        from ..controllers.leaderelection import LeaderElector

        if not identity:
            # unique per process, like kube's hostname_uuid holder identity:
            # identity-match reclaims its own lease instantly, so two
            # processes must never share one by default (split-brain)
            import os as _os
            import uuid as _uuid

            identity = f"karpenter-tpu-{_os.getpid()}-{_uuid.uuid4().hex[:8]}"
        if lease_path:
            # cross-process HA: the lease lives in a flock'd file shared by
            # the replicas (deploy/render.py mounts it); renew_time must be
            # comparable across processes, so the elector runs on WALL time
            # regardless of the control-plane clock
            from ..controllers.filelease import FileLeaseBackend

            elector = LeaderElector(
                FileLeaseBackend(lease_path), identity=identity,
                lease_s=lease_s, renew_s=renew_s, clock=time.time,
            )
        else:
            elector = LeaderElector(
                store, identity=identity, lease_s=lease_s, renew_s=renew_s,
                clock=clock,
            )
    on_elected = None
    if snapshot_path is not None and lease_path:
        # cross-process mode ONLY: the standby's store is a cold boot-time
        # restore, so takeover re-hydrates from the dead leader's latest
        # snapshot. In-process shared-store HA must NOT run this — the
        # standby already shares the live store, and a clear-restore would
        # roll it back to the last snapshot cadence (r5 review finding).
        def on_elected():
            from ..controllers.snapshot import restore_snapshot

            restore_snapshot(store, cloud, snapshot_path, now=clock(), clear=True)
    manager = Manager(elector=elector, on_elected=on_elected)
    manager.register(
        VolumeTopologyController(store),
        provisioner,
        LaunchController(store, cloud_provider, clock=clock),
        RegistrationController(store, clock=clock),
        InitializationController(store, clock=clock),
        Binder(store, cluster),
        preemption,
        TerminationController(store, cloud_provider, clock=clock),
        LivenessController(store, clock=clock),
        ExpirationController(store, clock=clock),
        GarbageCollectionController(store, cloud, clock=clock),
        PodGCController(store),
        NodeClassController(store, catalog=types),
        DriftController(store),
        InterruptionController(store, queue, unavailable=cloud_provider.unavailable),
        RepairController(store, cloud_provider, clock=clock),
        CapacityReservationFlipController(store, cloud, reservations, clock=clock),
        DiscoveredCapacityController(store, discovered),
    )
    from ..controllers.offeringmetrics import OfferingMetricsController
    from ..controllers.tagging import TaggingController

    manager.register(
        TaggingController(store, cloud),
        OfferingMetricsController(cloud_provider, clock=clock),
    )
    if disruption:
        from ..disruption.controller import DisruptionController

        manager.register(
            DisruptionController(
                store, cluster, cloud_provider, solver, clock=clock,
                preference_policy=preference_policy,
                probe_batch_max=probe_batch_max,
                solve_service=solve_service,
            )
        )
    if snapshot_path is not None:
        from ..controllers.snapshot import SnapshotController

        manager.register(
            SnapshotController(
                store, cloud, snapshot_path,
                interval_s=snapshot_interval_s, clock=clock,
                # fenced writes under HA: a deposed leader's in-flight save
                # loses against the new leader's higher lease rv
                fence=(lambda: elector.fence_token) if elector is not None else None,
            )
        )
    if vault is not None:
        from ..solver.vault import VaultController

        manager.register(VaultController(vault))
    if compile_cache_dir:
        # persistent XLA compilation cache: compilations (jit AND the AOT
        # prewarm's) are keyed by HLO hash on disk, so a restarted replica
        # reuses them instead of recompiling (min cache-size/compile-time
        # floors dropped to zero — control-loop kernels are small but their
        # compiles are the entire first-solve stall)
        import jax

        jax.config.update("jax_compilation_cache_dir", compile_cache_dir)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    if (warm_start and hasattr(solver, "warmup")) or (
        aot_prewarm and hasattr(solver, "prewarm_aot")
    ):
        # pre-compile off the boot path: the AOT pass lowers the whole
        # claim-bucket lattice (incl. overflow-retry shapes) without touching
        # the device, then warm-start solves fill the in-process jit cache
        # for the standard pod buckets — first production solve hits a warm
        # cache instead of a compile stall
        import threading

        zones = sorted({o.zone for it in types for o in it.offerings})

        def _warm():
            if aot_prewarm and hasattr(solver, "prewarm_aot"):
                solver.prewarm_aot(types, zones,
                                   expected_pods=prewarm_scale_pods)
            if warm_start and hasattr(solver, "warmup"):
                solver.warmup(types, zones)
            # arm the hot-path recompile detector ONLY after BOTH warm
            # passes: warmup() executes real solves whose compiles are
            # legitimate prewarm events, so marking done inside
            # prewarm_aot would flag them as false hot-path defects
            obstelemetry.mark_prewarm_done()

        threading.Thread(target=_warm, daemon=True, name="solver-warmup").start()
    else:
        # no warm pass configured: every compile is by definition on the
        # dispatch path — arm the detector at boot so they are visible
        obstelemetry.mark_prewarm_done()
    return Operator(
        store=store,
        cloud=cloud,
        cloud_provider=cloud_provider,
        cluster=cluster,
        provisioner=provisioner,
        manager=manager,
        solver=solver,
        interruption_queue=queue,
        solve_service=solve_service,
        tenant_mux=tenant_mux,
        recorder=recorder,
        preemption=preemption,
        streaming=streaming,
        vault=vault,
        federation=federation,
        replicator=replicator,
    )
