"""Layered options/flag system.

Mirrors the reference's flag surface (pkg/operator/options/options.go:30-56 +
core settings, website/.../reference/settings.md:13-41): every option has a
flag name, an env-var default (KARPENTER_<NAME>), and a code default; feature
gates parse from a comma-separated string (settings.md:44-55). Provider
options inject the same way the reference's `coreoptions.Injectables` do —
register an Options subclass and it parses from the same argv/env layers.
"""

from __future__ import annotations

import argparse
import os
from dataclasses import dataclass, field, fields
from typing import Any, Dict, List, Optional, Sequence


def _env_name(flag: str) -> str:
    return "KARPENTER_" + flag.upper().replace("-", "_")


@dataclass
class Options:
    """Core options (subset mirroring settings.md:13-41)."""

    # batching (settings.md:15-16)
    batch_idle_duration_s: float = 1.0
    batch_max_duration_s: float = 10.0
    # client throughput analog (settings.md:29-30)
    kube_client_qps: int = 200
    kube_client_burst: int = 300
    # endpoints
    metrics_port: int = 8080
    health_probe_port: int = 8081
    # behavior
    log_level: str = "info"
    # log record shape: "text" (stdlib default) or "json" — one JSON object
    # per line, keyed by the solve's correlation token when one is ambient
    # (obs/logjson.py)
    log_format: str = "text"
    preference_policy: str = "Respect"  # settings.md:38
    enable_profiling: bool = False  # /debug/pprof/* (settings.md:23)
    # end-to-end solve tracing (obs/trace.py): span trees across
    # provisioner -> pipeline -> fleet -> backend, exported at /debug/trace
    # (Chrome-trace JSON) and feeding karpenter_solver_stage_seconds; the
    # off path is a shared no-op context (proven inert in bench.py)
    solver_tracing: bool = True
    # finished traces kept for /debug/trace and flight-recorder dumps
    trace_ring_size: int = 64
    # flight-recorder dump directory (invariant-gate reject / breaker open /
    # fleet fence write crash evidence here); empty = the system temp dir
    flight_recorder_dir: str = ""
    # on-disk flight-recorder dump cap: after each dump the oldest
    # karpenter-flightrec-*.json files beyond this count are pruned (the
    # per-reason throttle bounds rate; this bounds total disk)
    flight_recorder_keep: int = 32
    # decision provenance (obs/explain.py): per-solve explain records —
    # chosen node, top-K rejected candidates with typed reason codes,
    # preemption/gang rationale — served at /debug/explain and attached to
    # flight-recorder dumps. Off by default: the off path adds zero device
    # traffic and zero allocations (proven inert in bench.py --explain-suite)
    solver_explain: bool = False
    # rejected-candidate rows kept per group in each explain record
    explain_top_k: int = 8
    # explain records kept for /debug/explain (ring, newest wins)
    explain_ring_size: int = 256
    # per-stage latency SLOs (obs/slo.py): "stage=threshold_ms:target,..."
    # e.g. "solve=1000:0.99,backend.dispatch=500:0.995"; empty = defaults.
    # Burn rates export as karpenter_slo_burn_rate and gate /healthz
    slo_objectives: str = ""
    feature_gates: str = ""
    leader_elect: bool = True
    # solver backend: tpu | reference | ffd (alias of tpu: the greedy
    # device kernel) | convex (solver/convex.py: the global-optimization
    # ADMM backend layered over the device kernel; FFD remains the
    # fallback and the per-NodePool default via wellknown.
    # SOLVER_BACKEND_LABEL overrides)
    solver_backend: str = "tpu"
    # convex backend iteration budget: the jitted ADMM scan length. A solve
    # that has not converged within it falls back LOUDLY to FFD (counted +
    # flight-dumped), so this bounds worst-case convex latency
    convex_max_iters: int = 400
    # convex convergence tolerance on max |dX| between ADMM iterates
    convex_tolerance: float = 1e-3
    # resilient execution layer (solver/resilient.py): wrap the backend in
    # deadline + classification + invariant gate + circuit breaker
    solver_resilient: bool = True
    # device-resident argument arena (solver/arena.py): keep kernel args on
    # device across solves, uploading only stale entries as one packed
    # buffer; false = per-array re-upload every solve (debug escape hatch)
    solver_arena: bool = True
    # checkpointed-scan resume (solver/tpu/ffd.py + solver/SPEC.md "Resume
    # semantics"): device solves harvest FFDState snapshots into a
    # checkpoint ring so a warm re-solve replays only the changed run
    # suffix; requires solver_arena (checkpoints are an arena residency
    # class). false = every device solve replays the full scan.
    solver_resume: bool = True
    # scan steps between checkpoint-ring snapshots (>= 1, validated at
    # startup): smaller catches mid-list mutations closer to the change at
    # the cost of more HBM snapshot writes per solve
    resume_checkpoint_interval: int = 16
    # on-device decode (solver/SPEC.md "Decode & ladder semantics"): device
    # solves fetch a packed uint16 claim-delta instead of the dense take
    # tables, with an overflow-flag wide re-fetch carve-out; false = every
    # solve fetches the full O(S×E + S×M) tables (debug escape hatch)
    solver_device_decode: bool = True
    # device-resident relax ladder: preference-relaxation rungs are
    # pre-materialized at encode time and one kernel dispatch scans them,
    # committing the first rung at which each failing pod places; false =
    # the host redispatches once per dropped preference (_relax_solve loop)
    solver_relax_ladder: bool = True
    # scheduling classes (solver/scheduling_class.py): preemption plans
    # evictions of strictly-lower-priority bound pods for unplaced pending
    # pods; gang makes GANG_LABEL co-scheduling atomic (all-or-nothing with
    # rollback). Both default on and are provably inert on priority-flat,
    # gang-free fleets (the class sort keys and the solve passes only engage
    # when the batch carries >1 distinct priority or a gang).
    solver_preemption: bool = True
    solver_gang: bool = True
    # pipelined solve service (solver/pipeline.py): one device owner, host
    # encode / device compute / host decode of independent solves overlap,
    # provisioning snapshots coalesce on newer cluster-state revisions;
    # false = each controller blocks on its own solve round-trip
    solver_pipeline: bool = True
    # in-flight bound for the pipeline (solves dispatched but not decoded)
    pipeline_depth: int = 2
    # widest speculative-probe frontier one batched disruption dispatch may
    # carry (all O(n) candidate prefixes batch when they fit; fleets up to
    # ~probe_batch_max² resolve in two dispatches)
    probe_batch_max: int = 512
    # solver fleet (solver/fleet.py): N independently health-checked device
    # owners with breaker-driven failover; 1 = no fleet, the single
    # SolveService path (the default — fleet mode is for multi-device or
    # reliability-critical deployments)
    solver_fleet_size: int = 1
    # seconds between liveness-canary passes over the fleet's owners
    canary_interval_s: float = 5.0
    # consecutive canary deadline misses before an owner is fenced and its
    # work re-routed (the fleet breaker's threshold)
    fence_after_misses: int = 2
    # multi-tenant solver service (solver/tenancy.py): comma-separated
    # tenant ids sharing this operator's owner pool behind a weighted-fair
    # mux with per-tenant breakers/oracles; empty = tenancy off, the
    # provisioner holds the fleet/pipeline directly (byte-identical path)
    solver_tenants: str = ""
    # per-tenant WFQ weights, "id=float,..." (unlisted tenants weigh 1.0);
    # ids must appear in --solver-tenants — validated fail-closed at boot
    tenant_weights: str = ""
    # per-tenant admission bound: open solve requests (queued + in flight)
    # above this raise TenantAdmissionReject instead of enqueueing
    tenant_max_queue_depth: int = 64
    # cross-tenant fused cohort dispatch (SPEC.md "Cohort semantics"): the
    # mux extends each WFQ winner into a same-quantum-bucket cohort that
    # rides ONE kernel launch; off = byte-identical legacy single-head path
    solver_cohort: bool = True
    # cohort width cap (members per fused dispatch); validated fail-closed
    solver_cohort_max: int = 8
    # streaming delta-solve (solver/streaming.py): the provisioner folds
    # ClusterJournal event batches into a resident incremental model and
    # assembles solve inputs from it (event-rate-proportional host cost),
    # with the backend shipping run-table edits as device scatters. Default
    # off (snapshot path, byte-identical) → soak → on; decisions are
    # bit-identical either way (tests/test_streaming_solve.py parity).
    solver_streaming: bool = False
    # applied event batches between full re-encode parity checks of the
    # streaming model (epoch protocol; drift re-baselines). 0 = never.
    streaming_epoch_every: int = 64
    # runtime health plane (obs/telemetry.py + obs/anomaly.py): compile/
    # recompile observability, telemetry ring at /debug/vars, HBM gauges.
    # Off = the instrumented kernel hooks tail-call straight through
    # (allocation-free off path, proven in bench.py)
    telemetry: bool = True
    # arena residency byte budget, MiB (solver/arena.py): > 0 bounds TOTAL
    # accounted device residency (all classes x tenants) with LRU
    # whole-bucket eviction; 0 = unbounded (the max_buckets cap still holds)
    arena_budget_mb: int = 0
    # rolling-baseline anomaly multiplier (obs/anomaly.py): a stage trips
    # perf_anomaly when its duration sustains above
    # multiplier * max(ewma + 3*dev, ~p95); must be > 1.0
    anomaly_threshold: float = 3.0
    # bench regression baseline: path to a BENCH_rNN.json record for
    # tools/bench_gate.py comparisons; empty = gate off. Validated at boot
    # (must exist and parse as JSON) so a typo'd path fails the deploy, not
    # the nightly gate run.
    bench_baseline: str = ""
    # per-solve deadline on the device path, seconds; 0 = no deadline
    solver_deadline_s: float = 0.0
    # breaker opens after this many consecutive device-path failures
    solver_breaker_threshold: int = 3
    # half-open probe interval once open, seconds
    solver_breaker_probe_s: float = 30.0
    max_launch_instance_types: int = 60  # instance.go:60
    # kwok provider
    kwok_rate_limits: bool = False
    vm_memory_overhead_percent: float = 0.075  # options.go:36-56
    # pre-compile solver shape buckets at boot (background thread)
    warm_start: bool = True
    # ahead-of-time compile the claim-bucket lattice at boot (no device
    # execution; covers overflow-retry shapes warm_start's solves never hit)
    aot_prewarm: bool = True
    # claim-bucket lattice is sized for surges up to this many pods
    prewarm_scale_pods: int = 50_000
    # persistent XLA compilation cache directory (jax_compilation_cache_dir):
    # compilations — including the AOT prewarm's — survive process restarts,
    # so a fresh replica boots with zero compile stalls. Empty = in-process
    # jit cache only.
    compile_cache_dir: str = ""
    # durability: periodic store+cloud snapshot with boot-time restore
    # (kwok ConfigMap-backup analog, kwok/ec2/ec2.go:112-232); empty = off
    snapshot_path: str = ""
    snapshot_interval_s: float = 5.0
    # durable SOLVER resident state (solver/vault.py): async snapshots of
    # the device-facing model (encode donors, arena manifest, journal seq)
    # into this directory, restored at boot / fence so restart-to-first-
    # solve is journal-lag-bounded. Empty = vault off (fail-closed: the
    # byte-identical pre-vault path; the interval/keep knobs then must not
    # pretend to be in effect)
    solver_vault_dir: str = ""
    # seconds between vault snapshots (> 0, validated at startup)
    vault_interval_s: float = 5.0
    # newest vault files retained on disk (>= 1, validated at startup)
    vault_keep: int = 3
    # federated solver fleets (solver/federation.py): comma-separated host
    # names forming the federation; tenants consistent-hash onto hosts and
    # cross-host failover requeues a fenced host's solves onto survivors.
    # Empty = federation off (fail-closed: no router constructed, the
    # byte-identical single-host path)
    federation_hosts: str = ""
    # this process's host name — required when --federation-hosts is set,
    # must be a member of it (validated fail-closed at startup)
    federation_self: str = ""
    # replicate the ClusterJournal tail to peer hosts so a host loss
    # re-baselines its tenants on a peer from replicated state; requires
    # --federation-hosts (replication without a federation is a typo)
    journal_replicate: bool = False
    # cross-process HA: flock'd lease file shared by replicas (empty = the
    # in-process lease, single-process HA only)
    lease_path: str = ""
    # self-contained smoke run (inject a demo nodepool + pods)
    demo: bool = False

    def gates(self) -> Dict[str, bool]:
        out: Dict[str, bool] = {}
        for part in self.feature_gates.split(","):
            part = part.strip()
            if not part:
                continue
            k, _, v = part.partition("=")
            out[k] = v.lower() != "false"
        return out


def parse(argv: Optional[Sequence[str]] = None, cls=Options) -> Options:
    """argv > env (KARPENTER_*) > dataclass default."""
    # KTPU_DEBUG_EVENTS rewires the solver kernel's `leftover` output to
    # while-loop event counts at TRACE time (solver/tpu/ffd.py) — every
    # solve in the process returns garbage placements. A perf session's
    # leaked env var must never reach a serving operator: fail closed here,
    # before any controller wiring.
    if os.environ.get("KTPU_DEBUG_EVENTS", "").lower() in ("1", "true", "yes"):
        raise SystemExit(
            "refusing to start: KTPU_DEBUG_EVENTS is set — solver leftover "
            "outputs would be event counts, not placements (unset it; the "
            "flag exists only for offline kernel perf probes)"
        )
    parser = argparse.ArgumentParser(prog="karpenter-tpu")
    for f in fields(cls):
        flag = "--" + f.name.replace("_", "-")
        env = os.environ.get(_env_name(f.name))
        default = f.default
        if env is not None:
            if f.type in ("bool", bool):
                default = env.lower() in ("1", "true", "yes")
            elif f.type in ("int", int):
                default = int(env)
            elif f.type in ("float", float):
                default = float(env)
            else:
                default = env
        if f.type in ("bool", bool):
            parser.add_argument(flag, type=lambda s: s.lower() in ("1", "true", "yes"),
                                default=default)
        elif f.type in ("int", int):
            parser.add_argument(flag, type=int, default=default)
        elif f.type in ("float", float):
            parser.add_argument(flag, type=float, default=default)
        else:
            parser.add_argument(flag, type=str, default=default)
    ns = parser.parse_args(list(argv) if argv is not None else [])
    out = cls(**vars(ns))
    # resume tunable sanity, validated before any controller wiring: an
    # interval < 1 would divide-by-zero the kernel's slot schedule at trace
    # time, deep inside the first device solve — fail closed at startup
    # with an actionable message instead.
    interval = getattr(out, "resume_checkpoint_interval", None)
    if interval is not None and int(interval) < 1:
        raise SystemExit(
            "refusing to start: --resume-checkpoint-interval must be >= 1 "
            f"(got {interval}); it is the number of FFD scan steps between "
            "checkpoint-ring snapshots (operator/options.py)"
        )
    # solver-backend knob sanity (same fail-closed rule): an unknown
    # backend name must refuse startup, not silently run the default —
    # "ffd" is an accepted alias of "tpu" (the greedy device kernel)
    backend = getattr(out, "solver_backend", None)
    if backend is not None and backend not in ("tpu", "reference", "ffd", "convex"):
        raise SystemExit(
            "refusing to start: --solver-backend must be one of "
            f"tpu|reference|ffd|convex (got {backend}); ffd aliases tpu, "
            "convex layers the global ADMM backend over it "
            "(solver/convex.py)"
        )
    cvx_iters = getattr(out, "convex_max_iters", None)
    if cvx_iters is not None and int(cvx_iters) < 1:
        raise SystemExit(
            "refusing to start: --convex-max-iters must be >= 1 "
            f"(got {cvx_iters}); it is the jitted ADMM scan length — "
            "non-convergence within it falls back to FFD "
            "(solver/convex.py)"
        )
    cvx_tol = getattr(out, "convex_tolerance", None)
    if cvx_tol is not None and float(cvx_tol) <= 0:
        raise SystemExit(
            "refusing to start: --convex-tolerance must be > 0 "
            f"(got {cvx_tol}); it is the ADMM convergence threshold on "
            "max |dX| between iterates (solver/convex.py)"
        )
    # fleet knob sanity (same fail-closed rule as the resume interval): a
    # zero/negative fleet size or fence threshold would wedge routing deep
    # inside the first failover instead of at startup with a clear message
    fleet_size = getattr(out, "solver_fleet_size", None)
    if fleet_size is not None and int(fleet_size) < 1:
        raise SystemExit(
            "refusing to start: --solver-fleet-size must be >= 1 "
            f"(got {fleet_size}); 1 disables the fleet (single owner), "
            ">= 2 enables health-probed failover (solver/fleet.py)"
        )
    misses = getattr(out, "fence_after_misses", None)
    if misses is not None and int(misses) < 1:
        raise SystemExit(
            "refusing to start: --fence-after-misses must be >= 1 "
            f"(got {misses}); it is the consecutive canary-miss count that "
            "fences a solver owner (solver/fleet.py)"
        )
    interval_s = getattr(out, "canary_interval_s", None)
    if interval_s is not None and float(interval_s) <= 0:
        raise SystemExit(
            "refusing to start: --canary-interval-s must be > 0 "
            f"(got {interval_s}); it is the liveness-probe period of the "
            "solver fleet watchdog (solver/fleet.py)"
        )
    # tenancy knob sanity (same fail-closed rule): a malformed tenant list
    # or weight map must refuse startup, not silently mis-weight a tenant
    # or serve an unknown one — TenantRegistry.parse raises ValueError on
    # duplicates, unknown weight keys, and non-positive values
    tenants_str = getattr(out, "solver_tenants", "") or ""
    weights_str = getattr(out, "tenant_weights", "") or ""
    tenant_depth = getattr(out, "tenant_max_queue_depth", None)
    if weights_str.strip() and not tenants_str.strip():
        raise SystemExit(
            "refusing to start: --tenant-weights is set but --solver-tenants "
            "is empty; weights only apply to registered tenants "
            "(solver/tenancy.py)"
        )
    if tenant_depth is not None and int(tenant_depth) < 1:
        raise SystemExit(
            "refusing to start: --tenant-max-queue-depth must be >= 1 "
            f"(got {tenant_depth}); it bounds one tenant's open solve "
            "requests at the mux (solver/tenancy.py)"
        )
    if tenants_str.strip():
        from ..solver.tenancy import TenantRegistry

        try:
            TenantRegistry.parse(
                tenants_str, weights_str,
                max_queue_depth=int(tenant_depth or 64),
            )
        except ValueError as e:
            raise SystemExit(f"refusing to start: {e}") from None
    cohort_max = getattr(out, "solver_cohort_max", None)
    if cohort_max is not None and int(cohort_max) < 1:
        raise SystemExit(
            "refusing to start: --solver-cohort-max must be >= 1 "
            f"(got {cohort_max}); it caps members per fused cohort "
            "dispatch (solver/tenancy.py)"
        )
    fmt = getattr(out, "log_format", None)
    if fmt is not None and fmt not in ("text", "json"):
        raise SystemExit(
            "refusing to start: --log-format must be 'text' or 'json' "
            f"(got {fmt!r}); json emits one object per line keyed by "
            "solve_id (obs/logjson.py)"
        )
    ring = getattr(out, "trace_ring_size", None)
    if ring is not None and int(ring) < 1:
        raise SystemExit(
            "refusing to start: --trace-ring-size must be >= 1 "
            f"(got {ring}); it bounds the finished-trace ring backing "
            "/debug/trace and flight-recorder dumps (obs/trace.py)"
        )
    # decode/ladder knob sanity: these gate correctness-critical solver
    # paths, so a typo'd env value ("ture", "on") must not silently become
    # False and mask the fast path being off in prod — fail closed like the
    # resume interval above instead of inheriting bool()'s permissiveness.
    # explain/SLO knob sanity (same fail-closed rule as the rings above)
    keep = getattr(out, "flight_recorder_keep", None)
    if keep is not None and int(keep) < 1:
        raise SystemExit(
            "refusing to start: --flight-recorder-keep must be >= 1 "
            f"(got {keep}); it caps on-disk flight-recorder dumps "
            "(obs/recorder.py)"
        )
    topk = getattr(out, "explain_top_k", None)
    if topk is not None and int(topk) < 1:
        raise SystemExit(
            "refusing to start: --explain-top-k must be >= 1 "
            f"(got {topk}); it is the rejected-candidate rows kept per "
            "group in each explain record (obs/explain.py)"
        )
    ering = getattr(out, "explain_ring_size", None)
    if ering is not None and int(ering) < 1:
        raise SystemExit(
            "refusing to start: --explain-ring-size must be >= 1 "
            f"(got {ering}); it bounds the explain-record ring backing "
            "/debug/explain (obs/explain.py)"
        )
    epoch = getattr(out, "streaming_epoch_every", None)
    if epoch is not None and int(epoch) < 0:
        raise SystemExit(
            "refusing to start: --streaming-epoch-every must be >= 0 "
            f"(got {epoch}); it is the applied-batch count between the "
            "streaming model's full parity checks, 0 = never "
            "(solver/streaming.py)"
        )
    # vault knob sanity (same fail-closed rule): a zero/negative snapshot
    # cadence or retention would spin the writer or delete every snapshot —
    # refuse startup instead of degrading durability silently
    vinterval = getattr(out, "vault_interval_s", None)
    if vinterval is not None and float(vinterval) <= 0:
        raise SystemExit(
            "refusing to start: --vault-interval-s must be > 0 "
            f"(got {vinterval}); it is the seconds between solver vault "
            "snapshots (solver/vault.py)"
        )
    vkeep = getattr(out, "vault_keep", None)
    if vkeep is not None and int(vkeep) < 1:
        raise SystemExit(
            "refusing to start: --vault-keep must be >= 1 "
            f"(got {vkeep}); it is the newest vault snapshots retained on "
            "disk (solver/vault.py)"
        )
    # federation knob sanity (same fail-closed rule): a federation with no
    # self identity, a self host outside the member list, or replication
    # without a federation would misroute tenants or silently replicate to
    # nobody — refuse startup with the exact fix instead
    fhosts = (getattr(out, "federation_hosts", "") or "").strip()
    fself = (getattr(out, "federation_self", "") or "").strip()
    freplicate = bool(getattr(out, "journal_replicate", False))
    if fhosts:
        from ..solver.federation import FederationConfigError, parse_hosts

        try:
            members = parse_hosts(fhosts)
        except FederationConfigError as e:
            raise SystemExit(f"refusing to start: {e}") from None
        if not fself:
            raise SystemExit(
                "refusing to start: --federation-hosts is set but "
                "--federation-self is empty; every federated process must "
                "name itself so tenant routing knows which host it is "
                "(solver/federation.py)"
            )
        if fself not in members:
            raise SystemExit(
                f"refusing to start: --federation-self {fself!r} is not a "
                f"member of --federation-hosts {members}; a process outside "
                "the ring would strand every tenant hashed to it "
                "(solver/federation.py)"
            )
    else:
        if fself:
            raise SystemExit(
                "refusing to start: --federation-self is set but "
                "--federation-hosts is empty; a self identity without a "
                "federation is a typo'd deploy (solver/federation.py)"
            )
        if freplicate:
            raise SystemExit(
                "refusing to start: --journal-replicate requires "
                "--federation-hosts; replicating the journal tail with no "
                "peer hosts replicates to nobody (solver/federation.py)"
            )
    # health-plane knob sanity (same fail-closed rule as everything above)
    budget = getattr(out, "arena_budget_mb", None)
    if budget is not None and int(budget) < 0:
        raise SystemExit(
            "refusing to start: --arena-budget-mb must be >= 0 "
            f"(got {budget}); > 0 bounds arena residency with LRU eviction, "
            "0 = unbounded (solver/arena.py)"
        )
    thresh = getattr(out, "anomaly_threshold", None)
    if thresh is not None and float(thresh) <= 1.0:
        raise SystemExit(
            "refusing to start: --anomaly-threshold must be > 1.0 "
            f"(got {thresh}); it multiplies each stage's rolling baseline — "
            "<= 1.0 would flag normal latency as anomalous (obs/anomaly.py)"
        )
    baseline = getattr(out, "bench_baseline", "") or ""
    if baseline.strip():
        import json as _json

        try:
            with open(baseline) as f:
                _json.load(f)
        except (OSError, ValueError) as e:
            raise SystemExit(
                f"refusing to start: --bench-baseline {baseline!r} is not a "
                f"readable JSON bench record ({e}); point it at a "
                "BENCH_rNN.json (tools/bench_gate.py)"
            ) from None
    slo_spec = getattr(out, "slo_objectives", None)
    if slo_spec:
        from ..obs.slo import parse_objectives

        try:
            parse_objectives(slo_spec)
        except ValueError as e:
            raise SystemExit(f"refusing to start: {e}") from None
    for name in (
        "solver_device_decode", "solver_relax_ladder",
        "solver_preemption", "solver_gang", "solver_explain",
        "solver_streaming", "solver_cohort", "telemetry",
    ):
        if not hasattr(out, name):
            continue
        env = os.environ.get(_env_name(name))
        if env is not None and env.lower() not in (
            "1", "true", "yes", "0", "false", "no",
        ):
            raise SystemExit(
                f"refusing to start: {_env_name(name)}={env!r} is not a "
                "recognized boolean (use 1/true/yes or 0/false/no); "
                "guessing here would silently disable a solver fast path"
            )
    return out
