"""Profiling endpoints — the `--enable-profiling` pprof analog.

The reference exposes Go pprof on the metrics endpoint behind
`--enable-profiling` (website/.../reference/settings.md:23). Go's CPU
profile is a sampling profiler; the Python analog here samples
`sys._current_frames()` across ALL threads on a fixed interval and
aggregates inclusive/self hit counts per function — no dependencies, works
on the live controller loop, and unlike `cProfile` it observes every
thread (manager loop, batcher, snapshot, HTTP server), not just the caller.

Endpoints (wired by operator/__main__.py when enabled):
  /debug/pprof/profile?seconds=N  — sample for N seconds (default 5, max
                                    60), return a flat text report sorted
                                    by self samples
  /debug/pprof/stacks             — instantaneous dump of every thread's
                                    stack (the goroutine-profile analog)
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from collections import Counter
from typing import Tuple

SAMPLE_INTERVAL_S = 0.01  # 100 Hz, pprof's default sampling rate

# one CPU profile at a time: two concurrent sampling loops would double the
# profiler's own overhead AND each would see the other's loop as the hottest
# frame — the handler returns 429 instead of queuing
_PROFILE_LOCK = threading.Lock()


def sample_profile(seconds: float, interval_s: float = SAMPLE_INTERVAL_S,
                   clock=time.monotonic, sleep=time.sleep) -> str:
    """Sample all thread stacks for `seconds`; flat report by self-samples.

    The schedule is drift-free: each tick sleeps toward an ABSOLUTE deadline
    (`start + tick * interval_s`), so per-tick work (walking every thread's
    stack) doesn't stretch the effective period — a naive `sleep(interval)`
    after each pass samples at interval + walk_cost, silently under-reporting
    busy processes exactly when profiling them matters most.
    """
    seconds = max(0.1, min(float(seconds), 60.0))
    me = threading.get_ident()
    self_hits: Counter = Counter()
    incl_hits: Counter = Counter()
    n_samples = 0
    start = clock()
    deadline = start + seconds
    tick = 0
    while True:
        now = clock()
        if now >= deadline:
            break
        for tid, frame in sys._current_frames().items():
            if tid == me:
                continue  # the profiler's own sampling loop is noise
            n_samples += 1
            leaf = True
            seen = set()
            while frame is not None:
                code = frame.f_code
                key = (code.co_filename, code.co_name)
                if leaf:
                    self_hits[key] += 1
                    leaf = False
                if key not in seen:  # count recursion once per sample
                    incl_hits[key] += 1
                    seen.add(key)
                frame = frame.f_back
        tick += 1
        next_at = start + tick * interval_s
        now = clock()
        if next_at > now:
            sleep(next_at - now)
    lines = [
        f"# sampling profile: {seconds:.1f}s @ {1 / interval_s:.0f}Hz, "
        f"{n_samples} thread-samples",
        f"{'self':>8} {'self%':>7} {'incl':>8}  function",
    ]
    total = max(n_samples, 1)
    for key, self_n in self_hits.most_common(60):
        fn, name = key
        lines.append(
            f"{self_n:>8} {100.0 * self_n / total:>6.1f}% {incl_hits[key]:>8}"
            f"  {name} ({fn})"
        )
    return "\n".join(lines) + "\n"


def dump_stacks() -> str:
    """Instantaneous all-thread stack dump (goroutine-profile analog)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for tid, frame in sys._current_frames().items():
        out.append(f"--- thread {tid} ({names.get(tid, '?')}) ---")
        out.extend(line.rstrip() for line in traceback.format_stack(frame))
    return "\n".join(out) + "\n"


def handle(path: str, query: str) -> Tuple[int, str]:
    """Route a /debug/pprof request; returns (status, body)."""
    if path == "/debug/pprof/profile":
        seconds = 5.0
        for part in query.split("&"):
            if part.startswith("seconds="):
                try:
                    seconds = float(part.split("=", 1)[1])
                except ValueError:
                    return 400, "bad seconds\n"
        if not _PROFILE_LOCK.acquire(blocking=False):
            return 429, "profile already in progress\n"
        try:
            return 200, sample_profile(seconds)
        finally:
            _PROFILE_LOCK.release()
    if path == "/debug/pprof/stacks":
        return 200, dump_stacks()
    return 404, "unknown profile endpoint\n"
