"""TTL caches with the reference's documented consistency windows.

Mirror of pkg/cache/cache.go:19-59: each cache names its TTL so the staleness
window is explicit. Defaults: 1m default, 5m instance types/offerings, 3m ICE
(in unavailable.py), 24h SSM-analog, 60d discovered capacity, 10m validation.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Generic, Hashable, Optional, Tuple, TypeVar

V = TypeVar("V")

# cache.go:19-59
DEFAULT_TTL_S = 60.0
INSTANCE_TYPES_TTL_S = 5 * 60.0
UNAVAILABLE_OFFERINGS_TTL_S = 3 * 60.0
DISCOVERED_CAPACITY_TTL_S = 60 * 24 * 3600.0
VALIDATION_TTL_S = 10 * 60.0


class TTLCache(Generic[V]):
    def __init__(self, ttl_s: float = DEFAULT_TTL_S, clock=time.monotonic):
        self.ttl_s = ttl_s
        self.clock = clock
        self._data: Dict[Hashable, Tuple[float, V]] = {}
        self._lock = threading.Lock()

    def get(self, key: Hashable) -> Optional[V]:
        with self._lock:
            ent = self._data.get(key)
            if ent is None:
                return None
            exp, val = ent
            if exp <= self.clock():
                del self._data[key]
                return None
            return val

    def set(self, key: Hashable, value: V, ttl_s: Optional[float] = None) -> None:
        with self._lock:
            self._data[key] = (self.clock() + (ttl_s if ttl_s is not None else self.ttl_s), value)

    def get_or_compute(self, key: Hashable, fn: Callable[[], V]) -> V:
        val = self.get(key)
        if val is None:
            val = fn()
            self.set(key, val)
        return val

    def invalidate(self, key: Hashable) -> None:
        with self._lock:
            self._data.pop(key, None)

    def flush(self) -> None:
        with self._lock:
            self._data.clear()

    def __len__(self) -> int:
        now = self.clock()
        with self._lock:
            return sum(1 for exp, _ in self._data.values() if exp > now)
