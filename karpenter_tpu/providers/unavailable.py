"""UnavailableOfferings — the ICE (insufficient capacity) cache.

Mirrors pkg/cache/unavailableofferings.go:33-101: keyed
`capacityType:instanceType:zone`, TTL 3 minutes (pkg/cache/cache.go:29), with
a SeqNum bumped on every change so downstream offering caches (and the TPU
solver's availability masks) invalidate cheaply — the SeqNum protocol from
SURVEY.md §7 "staleness windows": the solver sidecar re-derives masks only
when the SeqNum moved.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

from ..metrics.registry import ICE_CACHE_SIZE

DEFAULT_TTL_S = 180.0  # 3m, cache.go:29


class UnavailableOfferings:
    def __init__(self, ttl_s: float = DEFAULT_TTL_S, clock=time.monotonic):
        self._ttl = ttl_s
        self._clock = clock
        self._lock = threading.Lock()
        self._entries: Dict[Tuple[str, str, str], float] = {}  # key -> expiry
        self.seq_num = 0

    @staticmethod
    def _key(capacity_type: str, instance_type: str, zone: str) -> Tuple[str, str, str]:
        return (capacity_type, instance_type, zone)

    def mark_unavailable(self, capacity_type: str, instance_type: str, zone: str) -> None:
        with self._lock:
            self._entries[self._key(capacity_type, instance_type, zone)] = (
                self._clock() + self._ttl
            )
            self.seq_num += 1
            ICE_CACHE_SIZE.set(float(len(self._entries)))

    def is_unavailable(self, capacity_type: str, instance_type: str, zone: str) -> bool:
        with self._lock:
            k = self._key(capacity_type, instance_type, zone)
            exp = self._entries.get(k)
            if exp is None:
                return False
            if exp <= self._clock():
                del self._entries[k]
                self.seq_num += 1
                return False
            return True

    def flush_expired(self) -> None:
        with self._lock:
            now = self._clock()
            dead = [k for k, exp in self._entries.items() if exp <= now]
            for k in dead:
                del self._entries[k]
            if dead:
                self.seq_num += 1
                ICE_CACHE_SIZE.set(float(len(self._entries)))

    def count(self) -> int:
        with self._lock:
            return len(self._entries)
