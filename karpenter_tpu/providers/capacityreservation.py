"""Capacity reservations — the `reserved` capacity type.

Mirror of pkg/providers/capacityreservation (SURVEY.md §2.2): on-demand
capacity reservation (ODCR-analog) discovery plus available-instance-count
bookkeeping (MarkLaunched / MarkTerminated / MarkUnavailable,
provider.go:34-40). Reserved offerings are injected priced at
odPrice/10_000_000 — "nearly free" so price ordering always prefers them,
while remaining ordered among themselves (offering/offering.go:96-179).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from ..api import wellknown as wk
from ..cloudprovider.types import InstanceType, Offering
from ..scheduling.requirements import IN, Requirement

RESERVED_PRICE_DIVISOR = 10_000_000  # offering.go reserved pricing rule


@dataclass
class CapacityReservation:
    id: str
    instance_type: str
    zone: str
    total: int
    available: int
    expires_at: Optional[float] = None  # monotonic deadline; None = no expiry

    def active(self, now: float) -> bool:
        return self.expires_at is None or now < self.expires_at


class CapacityReservationProvider:
    def __init__(self, clock=time.monotonic):
        self.clock = clock
        self._lock = threading.Lock()
        self._reservations: Dict[str, CapacityReservation] = {}

    # -- discovery ----------------------------------------------------------

    def add(self, res: CapacityReservation) -> None:
        with self._lock:
            self._reservations[res.id] = res

    def list(self) -> List[CapacityReservation]:
        now = self.clock()
        with self._lock:
            return [r for r in self._reservations.values() if r.active(now)]

    def get(self, res_id: str) -> Optional[CapacityReservation]:
        with self._lock:
            return self._reservations.get(res_id)

    # -- bookkeeping (provider.go:34-40) -------------------------------------

    def mark_launched(self, res_id: str) -> bool:
        with self._lock:
            r = self._reservations.get(res_id)
            if r is None or r.available <= 0:
                return False
            r.available -= 1
            return True

    def mark_terminated(self, res_id: str) -> None:
        with self._lock:
            r = self._reservations.get(res_id)
            if r is not None:
                r.available = min(r.total, r.available + 1)

    def mark_unavailable(self, res_id: str) -> None:
        with self._lock:
            r = self._reservations.get(res_id)
            if r is not None:
                r.available = 0

    # -- offering injection ---------------------------------------------------

    def inject(self, instance_types: Sequence[InstanceType]) -> None:
        """Append reserved offerings (and widen the capacity-type requirement)
        for types with active reservations. Mutates the given (already-copied)
        catalog view — call on the ICE-masked copy, not the shared catalog."""
        by_type: Dict[str, List[CapacityReservation]] = {}
        for r in self.list():
            by_type.setdefault(r.instance_type, []).append(r)
        for it in instance_types:
            rs = by_type.get(it.name)
            if not rs:
                continue
            od = {
                o.zone: o.price
                for o in it.offerings
                if o.capacity_type == wk.CAPACITY_TYPE_ON_DEMAND
            }
            for r in rs:
                base = od.get(r.zone)
                if base is None:
                    continue
                it.offerings.append(
                    Offering(
                        zone=r.zone,
                        capacity_type=wk.CAPACITY_TYPE_RESERVED,
                        price=base / RESERVED_PRICE_DIVISOR,
                        available=r.available > 0,
                        reservation_capacity=r.available,
                        reservation_id=r.id,
                    )
                )
            cts = sorted({o.capacity_type for o in it.offerings})
            # widen (replace, not intersect) the capacity-type domain
            it.requirements[wk.CAPACITY_TYPE_LABEL] = Requirement.create(
                wk.CAPACITY_TYPE_LABEL, IN, cts
            )
