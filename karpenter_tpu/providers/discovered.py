"""Discovered-capacity learning (instancetype.go:320-344 behaviorally).

The catalog's memory capacity is an ESTIMATE (VM overhead percent); real
nodes report their true capacity at registration. The cache learns observed
memory per instance type from live Nodes and the provider folds it into the
served catalog — so the scheduler packs against reality, not the estimate.
A seq number invalidates the provider's masked-catalog cache on change
(same protocol as the ICE cache).
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from ..api import wellknown as wk
from ..controllers import store as st
from ..utils.resources import MEMORY


class DiscoveredCapacityCache:
    def __init__(self):
        self._memory: Dict[str, int] = {}
        self.seq = 0

    def record(self, instance_type: str, memory_bytes: int) -> None:
        # Keep the MINIMUM observation per type: deterministic whatever order
        # nodes are listed in (two nodes reporting different memory cannot
        # flip-flop the value — and a flip-flop would bump seq every
        # reconcile, forcing the provider to rebuild the ~600-type catalog on
        # every get_instance_types call), and conservative (the scheduler
        # never packs against more memory than some live node reported).
        if memory_bytes <= 0:
            return
        cur = self._memory.get(instance_type)
        if cur is None or memory_bytes < cur:
            self._memory[instance_type] = memory_bytes
            self.seq += 1

    def memory(self, instance_type: str) -> Optional[int]:
        return self._memory.get(instance_type)


class DiscoveredCapacityController:
    """Hydrates the cache from registered Nodes (the reference's
    providers/instancetype/capacity controller, capacity/controller.go:54-96)."""

    name = "providers.instancetype.capacity"

    def __init__(self, store: st.Store, cache: DiscoveredCapacityCache):
        self.store = store
        self.cache = cache

    def reconcile(self) -> bool:
        before = self.cache.seq
        for node in self.store.list(st.NODES):
            if not node.ready:
                continue
            it = node.meta.labels.get(wk.INSTANCE_TYPE_LABEL)
            if not it:
                continue
            mem = node.capacity.get(MEMORY)
            if mem:
                self.cache.record(it, int(mem))
        return False  # learning is not cluster progress (seq drives rebuilds)
