"""Pricing provider.

Mirror of pkg/providers/pricing (SURVEY.md §2.2): on-demand prices refreshed
on a 12h cadence from the price source, spot prices per (type, zone) on the
same loop, with the generated static tables as fallback when the source is
unreachable (the reference ships static price tables per partition). Here the
"source" is pluggable: the synthetic catalog is the static table, and tests/
simulations can inject live price movements (spot market drift) that flow
into offerings on the next refresh.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..api import wellknown as wk
from ..cloudprovider.types import InstanceType

REFRESH_INTERVAL_S = 12 * 3600.0  # providers/pricing/controller.go:59


class PricingProvider:
    def __init__(
        self,
        instance_types: Sequence[InstanceType],
        live_source: Optional[Callable[[], Dict[Tuple[str, str, str], float]]] = None,
        clock=time.monotonic,
    ):
        self._lock = threading.Lock()
        self.clock = clock
        self.live_source = live_source
        self._last_refresh = -REFRESH_INTERVAL_S
        # static fallback tables from the catalog (the generated-price-table
        # analog): (instance_type, zone, capacity_type) -> $/hr
        self._static: Dict[Tuple[str, str, str], float] = {}
        for it in instance_types:
            for o in it.offerings:
                self._static[(it.name, o.zone, o.capacity_type)] = o.price
        self._live: Dict[Tuple[str, str, str], float] = {}

    # -- refresh loop (12h cadence) -----------------------------------------

    def refresh_if_due(self) -> bool:
        if self.clock() - self._last_refresh < REFRESH_INTERVAL_S:
            return False
        return self.refresh()

    def refresh(self) -> bool:
        self._last_refresh = self.clock()
        if self.live_source is None:
            return False
        try:
            updates = self.live_source()
        except Exception:
            return False  # static fallback stays authoritative
        with self._lock:
            self._live.update(updates)
        return bool(updates)

    # -- queries -------------------------------------------------------------

    def on_demand_price(self, instance_type: str, zone: str) -> Optional[float]:
        return self.price(instance_type, zone, wk.CAPACITY_TYPE_ON_DEMAND)

    def spot_price(self, instance_type: str, zone: str) -> Optional[float]:
        return self.price(instance_type, zone, wk.CAPACITY_TYPE_SPOT)

    def price(self, instance_type: str, zone: str, capacity_type: str) -> Optional[float]:
        key = (instance_type, zone, capacity_type)
        with self._lock:
            if key in self._live:
                return self._live[key]
        return self._static.get(key)

    def apply(self, instance_types: Sequence[InstanceType]) -> None:
        """Rewrite offering prices in place from current tables (the analog
        of offering injection reading the pricing provider,
        offering/offering.go:119-126)."""
        for it in instance_types:
            for o in it.offerings:
                p = self.price(it.name, o.zone, o.capacity_type)
                if p is not None:
                    o.price = p
