"""Observability runtime: request-scoped tracing + flight recorder.

Zero-dependency. `trace` is the span/tracer core (solve_id correlation,
per-thread context, ring buffer of finished traces), `recorder` the
crash-dump flight recorder, `export` the Chrome-trace/Perfetto JSON
exporter, `logjson` the solve_id-keyed structured log formatter.

The module is inert until `trace.configure(enabled=True)`: every
production hook is a no-op returning a shared null object — no
allocation, no lock — so the tracing-off path costs one module-global
read per span site (bench.py guards this with `trace_overhead_pct`).
"""

from . import export, logjson, recorder, trace  # noqa: F401
