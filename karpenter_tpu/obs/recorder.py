"""Flight recorder: crash-dump the last N solve traces on failure.

The tracer's finished-trace ring (trace.py) plus the still-active
partial traces ARE the flight record — this module snapshots them to a
JSON file when something goes wrong enough that the evidence is about
to be destroyed:

- a fleet owner is fenced (fleet.py `_fence` — the fence stops the
  owner's service and force-resolves its tickets, erasing the wedged
  solve's live state);
- the per-request circuit breaker opens (resilient.py — the device
  path is about to be bypassed entirely);
- the invariant gate rejects a result (resilient.py — a garbage decode
  was caught; the inputs that produced it are in the trace attributes).

Each dump carries the trace snapshots (including the wedged solve's
PARTIAL span tree — open spans have `t1: null`), the recent canary
verdict history, and the trigger's tags (owner, fault site, violation
count). Dumps are throttled per reason (`min_interval_s`) so a crash
loop cannot fill the disk; the most recent dump's metadata is kept on
`last_dump` and surfaced through the operator's health endpoint.
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
import threading
import time
from collections import deque
from typing import Dict, Optional

from ..metrics.registry import FLIGHT_RECORDER_DUMPS
from . import trace as _trace

log = logging.getLogger("karpenter_tpu")


class FlightRecorder:
    # per-reason throttle overrides (seconds): health-plane reasons fire
    # from hot paths (every dispatch / every trace.finish), so they hold a
    # longer floor than the fence/breaker default regardless of how low an
    # operator tunes `min_interval_s` for crash forensics
    REASON_INTERVALS: Dict[str, float] = {
        "recompile": 60.0,
        "perf_anomaly": 60.0,
    }

    def __init__(self, dir: Optional[str] = None, capacity: int = 32,
                 min_interval_s: float = 30.0, clock=time.monotonic,
                 keep: int = 32):
        self.dir = dir or tempfile.gettempdir()
        self.capacity = max(1, int(capacity))
        self.min_interval_s = float(min_interval_s)
        self.reason_intervals: Dict[str, float] = dict(self.REASON_INTERVALS)
        self.clock = clock
        self.keep = max(1, int(keep))
        self._lock = threading.Lock()
        self._seq = 0
        self._last_by_reason: Dict[str, float] = {}
        self._canary: deque = deque(maxlen=64)
        self.dumps = 0
        self.throttled = 0
        self.last_dump: Optional[Dict[str, object]] = None

    def note_canary(self, owner: str, verdict: str,
                    latency_s: Optional[float] = None) -> None:
        """Record a liveness-probe verdict (ring of the last 64): the dump
        shows what the watchdog saw in the run-up to a fence."""
        with self._lock:
            self._canary.append({
                "wall": time.time(), "owner": owner, "verdict": verdict,
                "latency_s": latency_s,
            })

    def dump(self, reason: str, tags: Optional[Dict[str, object]] = None
             ) -> Optional[str]:
        """Write the flight record; returns the path, or None when the
        per-reason throttle suppressed it."""
        now = self.clock()
        interval = self.reason_intervals.get(reason, self.min_interval_s)
        with self._lock:
            last = self._last_by_reason.get(reason)
            if last is not None and now - last < interval:
                self.throttled += 1
                return None
            self._last_by_reason[reason] = now
            self._seq += 1
            seq = self._seq
            canary = list(self._canary)
        path = os.path.join(
            self.dir, f"karpenter-flightrec-{os.getpid()}-{seq:03d}-{reason}.json"
        )
        # payload construction included: the triggers (fence, breaker open,
        # gate reject) are recovery paths — snapshotting live traces from
        # other threads must never be able to abort them
        try:
            traces = _trace.recent(self.capacity)
            partial = _trace.active_traces()
            # tenancy attribution (solver/tenancy.py): which tenants' solves
            # are in this record — lets an operator triage a fence/breaker
            # dump straight to the affected cluster(s) without walking spans
            tenants: Dict[str, Dict[str, int]] = {}
            # streaming attribution (solver/streaming.py): the journal-seq
            # window the record covers — with no snapshot solve_id boundary,
            # "which event batches were in flight when it broke" is the
            # triage coordinate the journal seq range answers
            jseqs: list = []
            for t in traces + partial:
                js = getattr(t, "journal_seq", None)
                if js is not None:
                    jseqs.append(int(js))
                tid = t.tenant_id
                if tid is None:
                    continue
                ent = tenants.setdefault(tid, {"finished": 0, "partial": 0})
                ent["partial" if not t.done else "finished"] += 1
            journal = (
                {"min_seq": min(jseqs), "max_seq": max(jseqs),
                 "streamed_traces": len(jseqs)}
                if jseqs else None
            )
            payload = {
                "reason": reason,
                "tags": {k: _trace._jsonable(v)
                         for k, v in (tags or {}).items()},
                "wall_time": time.time(),
                "monotonic": time.monotonic(),
                "canary_history": canary,
                "tenants": tenants,
                "journal": journal,
                "partial_traces": [t.snapshot() for t in partial],
                "traces": [t.snapshot() for t in traces],
            }
            try:
                # decision provenance riding the crash dump: the most recent
                # explain records (why each pod landed where it did) for the
                # solves whose traces are being snapshotted. default=str
                # round-trip so a stray non-JSON value degrades to a string
                # instead of failing the whole dump.
                from . import explain as _explain
                payload["explain"] = json.loads(
                    json.dumps(_explain.store().recent(8), default=str)
                )
            except Exception:  # noqa: BLE001
                payload["explain"] = None
            try:
                # runtime health context (obs/telemetry.py): last-window
                # gauges, compile/hot-path state, anomaly baselines — so a
                # recompile/perf_anomaly dump is self-contained and a fence
                # dump shows whether the health plane saw it coming
                from . import telemetry as _telemetry
                payload["telemetry"] = json.loads(
                    json.dumps(_telemetry.dump_payload(), default=str)
                )
            except Exception:  # noqa: BLE001
                payload["telemetry"] = None
            with open(path, "w") as f:
                json.dump(payload, f, indent=1)
        except Exception as e:  # noqa: BLE001 — a dump must never crash a fence
            log.error("flight recorder: dump to %s failed: %s", path, e)
            return None
        self._prune()
        with self._lock:
            self.dumps += 1
            self.last_dump = {
                "reason": reason, "path": path, "wall_time": payload["wall_time"],
                "traces": len(traces), "partial_traces": len(partial),
            }
        FLIGHT_RECORDER_DUMPS.inc(reason=reason)
        log.warning(
            "flight recorder: dumped %d finished + %d partial trace(s) to %s "
            "(reason: %s)", len(traces), len(partial), path, reason,
        )
        return path

    def _prune(self) -> None:
        """Cap on-disk dumps at `keep` (oldest-first by mtime, across every
        process writing to the same dir — the glob is pid-agnostic). The
        throttle bounds RATE; this bounds TOTAL, so a long-lived crash loop
        cannot creep past the per-reason interval and fill the disk. Best
        effort: pruning runs on fence/breaker recovery paths and must never
        raise past them."""
        try:
            prefix = "karpenter-flightrec-"
            entries = []
            for name in os.listdir(self.dir):
                if not name.startswith(prefix) or not name.endswith(".json"):
                    continue
                p = os.path.join(self.dir, name)
                try:
                    entries.append((os.path.getmtime(p), p))
                except OSError:
                    continue  # raced with another pruner
            entries.sort()
            for _, p in entries[:max(0, len(entries) - self.keep)]:
                try:
                    os.unlink(p)
                except OSError:
                    pass  # raced; the file is gone either way
        except Exception as e:  # noqa: BLE001 — never fail a recovery path
            log.error("flight recorder: prune in %s failed: %s", self.dir, e)

    def health(self) -> Dict[str, object]:
        """Summary surfaced by the operator's health endpoint."""
        with self._lock:
            return {
                "dumps": self.dumps,
                "throttled": self.throttled,
                "last_dump": dict(self.last_dump) if self.last_dump else None,
            }
