"""Span/Tracer core: one solve = one span tree across every thread.

A `Trace` is minted at ticket creation (SolveService.submit /
SolverFleet.submit / provisioner.reconcile) and carries a `solve_id`
correlation token. The minting layer OWNS completion (it calls
`finish()` at ticket delivery); every other layer only ATTACHES: the
pipeline dispatcher/decoder threads, the fleet placement path, the
resilience wrappers and the backend all run inside `attached(trace)`
blocks, so their `span()` calls nest under the one root — one solve
yields one rooted span tree no matter how many threads touched it.

Threading model: span creation appends under the trace's own lock;
the per-thread context is a plain list on a `threading.local`. The
finished-trace ring is a `deque(maxlen=N)` — appends are single
bytecode ops under the GIL, so readers (the /debug/trace exporter, the
flight recorder) never block a solve.

Off path: `configure(enabled=False)` (the import-time default) makes
`span()` return a shared null context manager and `begin()` return
None — no allocation anywhere on the solve path. `span()` also
returns the null object when the calling thread has no attached trace,
so direct `solver.solve()` calls outside a ticket stay untraced rather
than producing orphan fragments.

Timestamps are `time.monotonic()` — durations are exact; the exporter
anchors them to wall time once per export.
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from ..metrics.registry import SOLVER_STAGE_SECONDS

log = logging.getLogger("karpenter_tpu")

_ENABLED = False
_LOCK = threading.Lock()
_SEQ = itertools.count(1)
_ACTIVE: "Dict[str, Trace]" = {}  # solve_id -> unfinished trace
_ACTIVE_MAX = 256  # wedged-forever traces evict oldest-first past this
_RING: deque = deque(maxlen=64)  # finished traces, oldest evicted
_RECORDER = None  # FlightRecorder (recorder.py) or None
_TLS = threading.local()  # .stack: [(trace, span), ...]


class Span:
    """One timed operation inside a trace. `end()` is idempotent and
    callable from any thread (cross-thread spans: pipeline.queue starts
    on the submitting thread and ends on the dispatcher)."""

    __slots__ = ("span_id", "parent_id", "name", "t0", "t1", "thread",
                 "status", "attrs", "_lk")

    def __init__(self, span_id: int, parent_id: Optional[int], name: str,
                 lock: Optional[threading.RLock] = None):
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.t0 = time.monotonic()
        self.t1: Optional[float] = None
        self.thread = threading.current_thread().name
        self.status = "open"
        self.attrs: Dict[str, object] = {}
        # the owning trace's lock: attrs writes and snapshot reads both
        # take it, so a reader (flight-recorder dump, /debug/trace) never
        # iterates a dict mid-mutation
        self._lk = lock if lock is not None else threading.RLock()

    def set(self, **attrs) -> None:
        with self._lk:
            self.attrs.update(attrs)

    def end(self, status: str = "ok") -> None:
        if self.t1 is None:
            self.t1 = time.monotonic()
            self.status = status

    @property
    def duration_s(self) -> Optional[float]:
        return None if self.t1 is None else self.t1 - self.t0

    def snapshot(self) -> Dict[str, object]:
        with self._lk:
            attrs = dict(self.attrs)
        return {
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "t0": self.t0,
            "t1": self.t1,
            "thread": self.thread,
            "status": self.status,
            "attrs": {k: _jsonable(v) for k, v in attrs.items()},
        }


class Trace:
    """All spans of one solve_id, rooted at the `solve` span created by
    `begin()`. Links (e.g. requeued_from) record cross-owner history
    that is not itself a timed operation."""

    __slots__ = ("solve_id", "kind", "tenant_id", "journal_seq", "spans",
                 "links", "root", "status", "done", "created_wall", "_lock")

    def __init__(self, solve_id: str, kind: str):
        self.solve_id = solve_id
        self.kind = kind
        # tenancy attribution (solver/tenancy.py): set once by the minting
        # layer via set_tenant(); read by logjson/recorder/debug exports
        self.tenant_id: Optional[str] = None
        # streaming attribution (solver/streaming.py): seq of the journal
        # event batch this solve folded in — the solve's identity when no
        # snapshot boundary exists; set via set_journal()
        self.journal_seq: Optional[int] = None
        # reentrant: Trace.snapshot holds it while Span.snapshot (same
        # lock, shared with every span) re-acquires for the attrs copy
        self._lock = threading.RLock()
        self.spans: List[Span] = []
        self.links: Dict[str, List[str]] = {}
        self.status = "open"
        self.done = False
        self.created_wall = time.time()
        self.root = self.start_span("solve", parent=None)

    def start_span(self, name: str, parent: Optional[Span]) -> Span:
        with self._lock:
            sp = Span(len(self.spans) + 1,
                      parent.span_id if parent is not None else None, name,
                      lock=self._lock)
            self.spans.append(sp)
        return sp

    def add_link(self, key: str, value: str) -> None:
        with self._lock:
            self.links.setdefault(key, []).append(value)

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            spans = [sp.snapshot() for sp in self.spans]
            links = {k: list(v) for k, v in self.links.items()}
        return {
            "solve_id": self.solve_id,
            "kind": self.kind,
            "tenant_id": self.tenant_id,
            "journal_seq": self.journal_seq,
            "status": self.status,
            "done": self.done,
            "created_wall": self.created_wall,
            "links": links,
            "spans": spans,
        }


def _jsonable(v):
    return v if isinstance(v, (str, int, float, bool, type(None))) else repr(v)


# -- configuration -------------------------------------------------------------


def configure(enabled: bool = True, ring: int = 64, recorder=None) -> None:
    """(Re)configure the runtime; resets the ring and active set — call
    once at operator boot, or per-test for isolation."""
    global _ENABLED, _RING, _RECORDER
    with _LOCK:
        _ENABLED = bool(enabled)
        _RING = deque(maxlen=max(1, int(ring)))
        _ACTIVE.clear()
        _RECORDER = recorder


def enabled() -> bool:
    return _ENABLED


def recorder():
    return _RECORDER


# -- trace lifecycle (owned by the minting layer) ------------------------------


def begin(kind: str = "solve", solve_id: Optional[str] = None) -> Optional[Trace]:
    """Mint a trace + its root span. Returns None when tracing is off."""
    if not _ENABLED:
        return None
    sid = solve_id or f"s{next(_SEQ):06d}"
    tr = Trace(sid, kind)
    with _LOCK:
        _ACTIVE[sid] = tr
        # bound the active set: a trace wedged forever (never finished)
        # must not leak — evict oldest-first into the ring as "abandoned"
        while len(_ACTIVE) > _ACTIVE_MAX:
            oldest = next(iter(_ACTIVE))
            stale = _ACTIVE.pop(oldest)
            stale.status, stale.done = "abandoned", True
            _RING.append(stale)
    return tr


def adopt_or_begin(kind: str):
    """(trace, owned): reuse the calling thread's attached trace (a layer
    above already minted it — it owns completion), else mint one here."""
    cur = current_trace()
    if cur is not None:
        return cur, False
    tr = begin(kind)
    return tr, tr is not None


def finish(trace: Optional[Trace], status: str = "ok") -> None:
    """Complete a trace: close its root, move it active -> ring, feed the
    per-stage latency histograms. Idempotent; None-safe."""
    if trace is None or trace.done:
        return
    trace.root.end(status)
    trace.status = status
    trace.done = True
    with _LOCK:
        _ACTIVE.pop(trace.solve_id, None)
        _RING.append(trace)
    for sp in list(trace.spans):
        if sp.t1 is not None:
            SOLVER_STAGE_SECONDS.observe(sp.t1 - sp.t0, stage=sp.name)
    # same span walk feeds the SLO burn-rate windows + tenant metering
    # (obs/slo.py) — one timing source for histograms, SLOs and billing
    try:
        from . import slo as _slo

        _slo.observe_trace(trace)
    except Exception:  # noqa: BLE001 — diagnostics never fail a solve
        log.exception("trace: SLO feed failed — continuing")
    # ... and the rolling-baseline anomaly detector (obs/anomaly.py):
    # sustained per-stage deviation trips perf_anomaly in /healthz
    try:
        from . import anomaly as _anomaly

        _anomaly.observe_trace(trace)
    except Exception:  # noqa: BLE001
        log.exception("trace: anomaly feed failed — continuing")


def status_of(error: Optional[BaseException]) -> str:
    """Map a ticket resolution error to a trace status."""
    if error is None:
        return "ok"
    name = type(error).__name__
    if name == "Superseded":
        return "superseded"
    if name == "ServiceStopped":
        return "stopped"
    return "error"


# -- per-thread context --------------------------------------------------------


def _stack() -> list:
    st = getattr(_TLS, "stack", None)
    if st is None:
        st = _TLS.stack = []
    return st


class _NullCtx:
    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL = _NullCtx()


class _Attach:
    __slots__ = ("_trace",)

    def __init__(self, trace: Trace):
        self._trace = trace

    def __enter__(self):
        _stack().append((self._trace, self._trace.root))
        return self._trace

    def __exit__(self, *exc):
        _stack().pop()
        return False


def attached(trace: Optional[Trace]):
    """Enter `trace`'s context on this thread: span() calls nest under
    its root until exit. None-safe (no-op context)."""
    if trace is None:
        return _NULL
    return _Attach(trace)


class _SpanCtx:
    __slots__ = ("_name", "_span")

    def __init__(self, name: str):
        self._name = name
        self._span = None

    def __enter__(self):
        stack = _stack()
        trace, parent = stack[-1]
        self._span = trace.start_span(self._name, parent)
        stack.append((trace, self._span))
        return self._span

    def __exit__(self, et, ev, tb):
        _stack().pop()
        self._span.end("error" if et is not None else "ok")
        return False


def span(name: str):
    """Context manager for a child span of the thread's current span.
    Returns the shared null context (zero allocation) when tracing is
    off or the thread has no attached trace."""
    if not _ENABLED:
        return _NULL
    if not getattr(_TLS, "stack", None):
        return _NULL
    return _SpanCtx(name)


def current() -> Optional[Span]:
    st = getattr(_TLS, "stack", None)
    return st[-1][1] if st else None


def current_trace() -> Optional[Trace]:
    st = getattr(_TLS, "stack", None)
    return st[-1][0] if st else None


def current_solve_id() -> Optional[str]:
    st = getattr(_TLS, "stack", None)
    return st[-1][0].solve_id if st else None


def current_tenant_id() -> Optional[str]:
    st = getattr(_TLS, "stack", None)
    return st[-1][0].tenant_id if st else None


def set_tenant(trace: Optional[Trace], tenant_id: Optional[str]) -> None:
    """Stamp tenant attribution on a trace + its root span. Called by the
    minting layer (pipeline/fleet submit, TenantMux); None-safe both ways
    so the single-tenant path allocates nothing extra."""
    if trace is None or tenant_id is None:
        return
    trace.tenant_id = tenant_id
    trace.root.set(tenant_id=tenant_id)


def current_journal_seq() -> Optional[int]:
    st = getattr(_TLS, "stack", None)
    return st[-1][0].journal_seq if st else None


def set_journal(trace: Optional[Trace], seq: Optional[int]) -> None:
    """Stamp journal attribution (solver/streaming.py) on a trace + its root
    span: `seq` is the newest ClusterJournal event this solve's universe
    folds in, the streamed solve's identity when no snapshot solve_id
    boundary exists. None-safe both ways, like set_tenant — the snapshot
    path allocates nothing extra."""
    if trace is None or seq is None:
        return
    trace.journal_seq = seq
    trace.root.set(journal_seq=seq)


def annotate(**attrs) -> None:
    """Set attributes on the current span (no-op outside a trace)."""
    st = getattr(_TLS, "stack", None)
    if st:
        st[-1][1].set(**attrs)


def event(name: str, **attrs) -> None:
    """Instantaneous marker span under the current span (no-op outside
    a trace) — requeue links, fault fires."""
    st = getattr(_TLS, "stack", None)
    if not st:
        return
    trace, parent = st[-1]
    sp = trace.start_span(name, parent)
    sp.set(**attrs)
    sp.end()


# -- export / recorder feeds ---------------------------------------------------


def recent(n: Optional[int] = None) -> List[Trace]:
    """Last `n` finished traces, oldest first."""
    with _LOCK:
        out = list(_RING)
    return out if n is None else out[-int(n):]


def active_traces() -> List[Trace]:
    """Unfinished traces (partial span trees — what a wedge looks like)."""
    with _LOCK:
        return list(_ACTIVE.values())


def dump(reason: str, **tags) -> Optional[str]:
    """Trigger a flight-recorder dump (no-op when none is configured).
    Never raises: the triggers are recovery paths (fleet fence, breaker
    open, gate reject) whose forward progress must not depend on
    diagnostics succeeding."""
    rec = _RECORDER
    if rec is None:
        return None
    try:
        return rec.dump(reason, tags=tags)
    except Exception:  # noqa: BLE001 — diagnostics must never abort recovery
        log.exception(
            "trace: flight-recorder dump failed (reason: %s) — continuing",
            reason,
        )
        return None


def note_canary(owner: str, verdict: str, latency_s: Optional[float] = None) -> None:
    rec = _RECORDER
    if rec is not None:
        rec.note_canary(owner, verdict, latency_s)
