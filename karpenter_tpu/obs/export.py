"""Chrome-trace (Perfetto-loadable) JSON export of solve traces.

Emits the Trace Event Format's "X" (complete) events — one per closed
span — and "B" (begin, no end) events for spans still open, so a wedged
solve renders as an unterminated bar. Span timestamps are monotonic;
the export anchors them to wall time once (`anchor`) so absolute times
in the UI are meaningful. Threads map to Perfetto tracks via `tid` +
thread-name metadata events; the solve_id, span tree (parent ids) and
every span attribute ride in `args`.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional


def chrome_trace(traces, anchor: Optional[Dict[str, float]] = None) -> dict:
    """Convert Trace objects (finished or partial) to a Chrome-trace dict.
    `anchor` maps monotonic->wall once per export; defaults to now."""
    if anchor is None:
        anchor = {"monotonic": time.monotonic(), "wall": time.time()}
    off_us = (anchor["wall"] - anchor["monotonic"]) * 1e6
    events: List[dict] = []
    tids: Dict[str, int] = {}
    for tr in traces:
        snap = tr.snapshot() if hasattr(tr, "snapshot") else tr
        # multi-tenant reads: attributed traces get their own track lane
        # (`tenant/<id>/<thread>`) so one tenant's solves line up visually
        # instead of interleaving with every other tenant on shared worker
        # threads; unattributed traces keep the bare thread lane
        tenant = snap.get("tenant_id")
        lane_prefix = f"tenant/{tenant}/" if tenant else ""
        for sp in snap["spans"]:
            tid = tids.setdefault(lane_prefix + sp["thread"], len(tids) + 1)
            args = dict(sp["attrs"])
            args.update(
                solve_id=snap["solve_id"], span_id=sp["span_id"],
                parent_id=sp["parent_id"], status=sp["status"],
            )
            if tenant:
                args["tenant_id"] = tenant
            if snap["links"]:
                args["links"] = snap["links"]
            ev = {
                "name": sp["name"], "cat": snap["kind"], "pid": 1,
                "tid": tid, "ts": sp["t0"] * 1e6 + off_us, "args": args,
            }
            if sp["t1"] is not None:
                ev["ph"] = "X"
                ev["dur"] = (sp["t1"] - sp["t0"]) * 1e6
            else:
                ev["ph"] = "B"  # still open: a wedged / in-flight span
            events.append(ev)
    for name, tid in tids.items():
        events.append({
            "name": "thread_name", "ph": "M", "pid": 1, "tid": tid,
            "args": {"name": name},
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}
