"""Structured logging: one-line JSON records keyed by solve_id.

Opt-in via `--log-format=json` (operator/options.py). The formatter
joins logs to traces on the same correlation token two ways: an
explicit `extra={"solve_id": ...}` on the record wins; otherwise the
calling thread's attached trace (obs/trace.py context) supplies it —
which covers the pipeline dispatcher/decoder and resilience log sites
for free, since they already run inside `attached(trace)` blocks.
"""

from __future__ import annotations

import json
import logging
import time
import traceback

from . import trace as _trace


class JsonLogFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": round(record.created, 3),
            "iso": time.strftime("%Y-%m-%dT%H:%M:%S",
                                 time.localtime(record.created)),
            "level": record.levelname.lower(),
            "logger": record.name,
            "thread": record.threadName,
            "msg": record.getMessage(),
        }
        solve_id = getattr(record, "solve_id", None) or _trace.current_solve_id()
        if solve_id is not None:
            out["solve_id"] = solve_id
        # tenancy (solver/tenancy.py): same two-way join as solve_id — an
        # explicit extra wins, else the attached trace's tenant stamp
        tenant_id = getattr(record, "tenant_id", None) or _trace.current_tenant_id()
        if tenant_id is not None:
            out["tenant_id"] = tenant_id
        if record.exc_info:
            out["exc"] = "".join(
                traceback.format_exception(*record.exc_info)
            ).rstrip()
        return json.dumps(out, default=repr)
