"""Decision provenance: per-solve explain records + the ExplainStore ring.

A placement is only operable if it can answer "why did pod p land on node
n — and why not the others?". This module defines the CANONICAL explain
record: a pure, deterministic function of (encoded input, final decisions),
so the python oracle, the native core and the TPU kernel produce
bit-identical records whenever they produce identical decisions — which
turns the record into a parity-debugging weapon: diff two legs' records
and the first divergent field names the disagreement.

Layout of one record (all-JSON, canonically ordered):

  pods[uid]    = {group, chosen}            chosen: ["node", id] |
                                            ["claim", idx] | None
  groups[g]    = {n_rejected, rejected}     rejected: top-K [node_id,
                                            reason] rows, ascending node
                                            input order
  preemptions  = [{node, victim, victim_priority, for_pod}]  plan order ==
                                            the minimal-prefix eviction
                                            rationale (scheduling_class)
  gangs        = {gang_id: {committed, placed, min_ranks}}
  gangs_unschedulable, unplaced             sorted lists

The rejection table is computed by `reason_codes` (numpy) — the exact twin
of the device kernel `tpu/ffd.explain_pack`; both use int32 arithmetic and
the same fixed reason precedence, so the device wire decodes to the same
bits the host deriver produces. Reason names here MUST stay in sync with
`tpu/ffd.EXPLAIN_REASONS` (pinned by tests/test_arg_spec_drift.py and the
SPEC.md reason table).

Off path: `configure(enabled=False)` (the default) makes every hook a
cheap early return — no allocation, no encode, no device traffic.

On path, capture is LAZY: the per-solve hook stores references (input,
result, wire table, notes) in the ring — microseconds — and the record
materializes on first read (store get/by_pod/recent, i.e. /debug/explain,
the parity suite, a flight-recorder dump). Building a record walks every
pod, which would tax the hot solve path O(pods) for provenance nobody may
ever read; deferring it keeps explain-on overhead under the bench's 2%
budget. The held `enc` is the encode cache's own object, so the ring
extends lifetimes without duplicating the tensors.
"""

from __future__ import annotations

import hashlib
import itertools
import json
import logging
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

log = logging.getLogger("karpenter_tpu")

# -- reason codes (decoder-side names for tpu/ffd.EXPLAIN_REASONS) -------------
#
# Precedence is part of the wire contract: when several causes apply the
# SMALLEST nonzero code wins, so both sides evaluate in this order.

REASON_FEASIBLE = 0       # node admits + still fits one more pod of the group
REASON_ZONE = 1           # node zone outside the group's allowed zone set
REASON_CAPACITY_TYPE = 2  # capacity type (spot/on-demand) excluded
REASON_TAINT = 3          # labels/taints admission failed beyond zone/ct
REASON_RESOURCES = 4      # admits, but post-solve free < one more pod
REASON_TOPOLOGY = 5       # statically feasible; group owns a spread engine
REASON_AFFINITY = 6       # statically feasible; group owns affinity terms

REASON_NAMES: Dict[int, str] = {
    REASON_FEASIBLE: "feasible",
    REASON_ZONE: "zone",
    REASON_CAPACITY_TYPE: "capacity_type",
    REASON_TAINT: "taint",
    REASON_RESOURCES: "resources",
    REASON_TOPOLOGY: "topology",
    REASON_AFFINITY: "affinity",
}


# -- configuration -------------------------------------------------------------

_ENABLED = False
_TOP_K = 8
_LOCK = threading.Lock()
_XSEQ = itertools.count(1)  # solve keys when no trace is attached
_TLS = threading.local()    # .notes: class-pass annotations awaiting capture


class ExplainStore:
    """Ring of explain entries keyed by solve_id (newest evicts oldest).

    `put` merges: a later capture for the same solve_id replaces the
    record but unions annotations, so the class pass can re-derive over a
    backend capture without losing the backend's wire provenance."""

    def __init__(self, ring: int = 256):
        self._ring = max(1, int(ring))
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, dict]" = OrderedDict()

    def put(self, solve_id: str, entry: dict) -> dict:
        with self._lock:
            prev = self._entries.pop(solve_id, None)
            if prev is not None:
                merged = dict(prev.get("annotations") or {})
                merged.update(entry.get("annotations") or {})
                entry = dict(entry, annotations=merged)
            self._entries[solve_id] = entry
            while len(self._entries) > self._ring:
                self._entries.popitem(last=False)
        return entry

    def get(self, solve_id: str) -> Optional[dict]:
        with self._lock:
            e = self._entries.get(solve_id)
        return _materialize(e) if e is not None else None

    def by_pod(self, uid: str) -> List[dict]:
        with self._lock:
            entries = list(self._entries.values())
        entries = [_materialize(e) for e in entries]
        return [e for e in entries if uid in e["record"]["pods"]]

    def recent(self, n: Optional[int] = None) -> List[dict]:
        with self._lock:
            out = list(self._entries.values())
        out = out if n is None else out[-int(n):]
        return [_materialize(e) for e in out]

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


_STORE = ExplainStore()


def configure(enabled: bool = True, top_k: int = 8, ring: int = 256) -> None:
    """(Re)configure the runtime; resets the store — call once at operator
    boot, or per-test for isolation."""
    global _ENABLED, _TOP_K, _STORE
    with _LOCK:
        _ENABLED = bool(enabled)
        _TOP_K = max(1, int(top_k))
        _STORE = ExplainStore(ring=ring)


def enabled() -> bool:
    return _ENABLED


def top_k() -> int:
    return _TOP_K


def store() -> ExplainStore:
    return _STORE


# -- the reason deriver (numpy twin of tpu/ffd.explain_pack) -------------------


def reason_codes(take_e, run_group, group_req, node_free, node_compat,
                 node_zone, node_ct, group_zone, group_ct,
                 group_topo, group_aff) -> np.ndarray:
    """[G, E] int32 reason code per (group, node). int32 arithmetic and
    precedence identical to the device kernel, so a wire-decoded table and
    a host-derived table agree bit-for-bit on equal inputs."""
    take_e = np.asarray(take_e, dtype=np.int32)
    run_group = np.asarray(run_group, dtype=np.int32)
    group_req = np.asarray(group_req, dtype=np.int32)
    node_free = np.asarray(node_free, dtype=np.int32)
    G = group_req.shape[0]
    req_s = group_req[run_group]                       # [S, R]
    usage = take_e.T.astype(np.int32) @ req_s          # [E, R]
    free_final = node_free - usage
    group_zone = np.asarray(group_zone, bool).reshape(G, -1)
    group_ct = np.asarray(group_ct, bool).reshape(G, -1)
    # zero-width axes (no zones / capacity types known) pad to one all-False
    # column; node_zone/node_ct are -1 there so the where() never reads it —
    # the device dispatch pads identically, keeping the tables bit-equal
    if group_zone.shape[1] == 0:
        group_zone = np.zeros((G, 1), dtype=bool)
    if group_ct.shape[1] == 0:
        group_ct = np.zeros((G, 1), dtype=bool)
    Z, C = group_zone.shape[1], group_ct.shape[1]
    zid = np.clip(node_zone, 0, Z - 1)
    cid = np.clip(node_ct, 0, C - 1)
    zone_ok = np.where(node_zone[None, :] >= 0, group_zone[:, zid], True)
    ct_ok = np.where(node_ct[None, :] >= 0, group_ct[:, cid], True)
    compat = np.asarray(node_compat, bool)
    fits = np.all(free_final[None, :, :] >= group_req[:, None, :], axis=-1)
    ghot = (run_group[None, :] == np.arange(G, dtype=np.int32)[:, None])
    placed = (ghot.astype(np.int32) @ take_e) > 0      # [G, E]
    code = np.where(
        ~zone_ok, REASON_ZONE,
        np.where(~ct_ok, REASON_CAPACITY_TYPE,
        np.where(~compat, REASON_TAINT,
        np.where(~fits, REASON_RESOURCES,
        np.where(np.asarray(group_topo, bool)[:, None], REASON_TOPOLOGY,
        np.where(np.asarray(group_aff, bool)[:, None], REASON_AFFINITY,
                 REASON_FEASIBLE))))))
    # a node the group actually landed pods on is never "rejected"
    return np.where(placed, REASON_FEASIBLE, code).astype(np.int32)


def rejection_table(codes: np.ndarray, k: int) -> Tuple[np.ndarray, np.ndarray]:
    """(n_rejected [G] i32, words [G, k] i32) — word = e | (code << 16),
    -1 marks an empty slot; entries ascend by node input order. Identical
    packing to the device wire body."""
    G, E = codes.shape
    rej = codes > 0
    n_rej = rej.sum(axis=1).astype(np.int32)
    e_idx = np.arange(E, dtype=np.int32)
    key = np.where(rej, e_idx[None, :], E)
    order = np.argsort(key, axis=1, kind="stable")[:, :k]
    ent_e = np.take_along_axis(key, order, axis=1)
    ent_c = np.take_along_axis(codes, order, axis=1)
    valid = ent_e < E
    words = np.where(valid, ent_e | (ent_c << 16), -1).astype(np.int32)
    if words.shape[1] < k:  # fewer nodes than top-k: pad empty slots
        pad = np.full((G, k - words.shape[1]), -1, dtype=np.int32)
        words = np.concatenate([words, pad], axis=1)
    return n_rej, words


def takes_from_result(enc, placements: Dict[str, tuple]) -> np.ndarray:
    """Reconstruct the dense [S, E] run→node take table from final
    placements (the inverse of backend.decode's codes stream) — how the
    oracle/native legs recover the tensor the kernel emits natively."""
    S = int(enc.run_group.shape[0])
    E = len(enc.node_ids)
    node_rank = {nid: e for e, nid in enumerate(enc.node_ids)}
    take = np.zeros((S, E), dtype=np.int32)
    pos = 0
    for s in range(S):
        c = int(enc.run_count[s])
        for uid in enc.sorted_uids[pos:pos + c]:
            t = placements.get(uid)
            if t is not None and t[0] == "node":
                e = node_rank.get(t[1])
                if e is not None:
                    take[s, e] += 1
        pos += c
    return take


def host_table(enc, placements: Dict[str, tuple], k: int):
    """Full host derivation: final takes → reason codes → packed table.
    Consumes the same side tables the device kernel dispatches over
    (encode.explain_tables), so the two outputs are bit-comparable."""
    from ..solver.encode import explain_tables

    take = takes_from_result(enc, placements)
    codes = reason_codes(take, **explain_tables(enc))
    return rejection_table(codes, k)


# -- record assembly -----------------------------------------------------------


def build_record(enc, res, k: Optional[int] = None,
                 table: Optional[Tuple[np.ndarray, np.ndarray]] = None,
                 notes: Optional[Dict[str, list]] = None) -> dict:
    """The canonical record. `table` injects a wire-decoded (n_rej, words)
    pair (TPU leg); None derives it on the host — both must be bit-equal,
    which the parity suite asserts."""
    k = _TOP_K if k is None else int(k)
    if table is None:
        table = host_table(enc, res.placements, k)
    n_rej, words = table
    node_ids = list(enc.node_ids)
    G = int(enc.group_req.shape[0])
    groups: List[dict] = []
    for g in range(G):
        rejected = []
        for w in words[g]:
            w = int(w)
            if w < 0:
                continue
            e, code = w & 0xFFFF, (w >> 16) & 0xFFFF
            name = REASON_NAMES.get(code, f"code{code}")
            nid = node_ids[e] if e < len(node_ids) else f"e{e}"
            rejected.append([nid, name])
        groups.append({"n_rejected": int(n_rej[g]), "rejected": rejected})
    pods: Dict[str, dict] = {}
    if int(enc.run_group.shape[0]):
        # run→pod expansion vectorized; per-pod work is one dict lookup
        uid_group = np.repeat(np.asarray(enc.run_group, dtype=np.int64),
                              np.asarray(enc.run_count, dtype=np.int64))
        get = res.placements.get
        for uid, g in zip(enc.sorted_uids, uid_group.tolist()):
            t = get(uid)
            pods[str(uid)] = {
                "group": g,
                "chosen": [t[0], t[1]] if t is not None else None,
            }
    preemptions = [
        {
            "node": ev.node_id,
            "victim": ev.pod_uid,
            "victim_priority": int(ev.victim_priority),
            "for_pod": ev.for_pod,
        }
        for ev in getattr(res, "evictions", ())
    ]
    gangs: Dict[str, dict] = {}
    for n in (notes or {}).get("gang", ()):
        gangs[n["gang"]] = {
            "committed": bool(n["committed"]),
            "placed": int(n["placed"]),
            "min_ranks": int(n["min_ranks"]),
        }
    return {
        "top_k": k,
        "n_groups": G,
        "pods": pods,
        "groups": groups,
        "preemptions": preemptions,
        "gangs": gangs,
        "gangs_unschedulable": sorted(set(getattr(res, "gangs_unschedulable", ()))),
        "unplaced": sorted(u for u in pods if pods[u]["chosen"] is None),
    }


def fingerprint(record: dict) -> str:
    """Stable content hash — two legs agree iff their fingerprints do."""
    blob = json.dumps(record, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()


def diff_records(a: dict, b: dict) -> List[str]:
    """First-divergence paths between two records (parity debugging)."""
    out: List[str] = []

    def walk(x, y, path):
        if len(out) >= 32:
            return
        if isinstance(x, dict) and isinstance(y, dict):
            for kk in sorted(set(x) | set(y)):
                if kk not in x:
                    out.append(f"{path}.{kk}: missing in A")
                elif kk not in y:
                    out.append(f"{path}.{kk}: missing in B")
                else:
                    walk(x[kk], y[kk], f"{path}.{kk}")
        elif isinstance(x, list) and isinstance(y, list):
            if len(x) != len(y):
                out.append(f"{path}: len {len(x)} != {len(y)}")
            for i, (xi, yi) in enumerate(zip(x, y)):
                walk(xi, yi, f"{path}[{i}]")
        elif x != y:
            out.append(f"{path}: {x!r} != {y!r}")

    walk(a, b, "$")
    return out


# -- capture hooks (called by the solver legs) ---------------------------------


def note(kind: str, payload: dict) -> None:
    """Stage a class-pass annotation (gang verdict, preemption rationale)
    for the enclosing class-level capture. No-op when explain is off."""
    if not _ENABLED:
        return
    notes = getattr(_TLS, "notes", None)
    if notes is None:
        notes = _TLS.notes = {}
    notes.setdefault(kind, []).append(payload)


def _drain_notes() -> Dict[str, list]:
    notes = getattr(_TLS, "notes", None)
    _TLS.notes = {}
    return notes or {}


def _materialize(entry: dict) -> dict:
    """Build a deferred entry's record in place (idempotent). Reads are
    rare — the debug endpoint, the parity suite, a crash dump — so the
    O(pods) record assembly runs here instead of on the solve path."""
    if entry.get("_defer") is None:
        return entry
    with _LOCK:
        d = entry.pop("_defer", None)
        if d is None:
            return entry
        inp, enc, res, table, notes, k = d
        try:
            if enc is None:
                from ..solver.encode import encode, quantize_input
                enc = encode(quantize_input(inp))
            record = build_record(enc, res, k=k, table=table, notes=notes)
            entry["record"] = record
            entry["fingerprint"] = fingerprint(record)
        except Exception:  # noqa: BLE001 — diagnostics never abort a read
            log.exception("explain: deferred record build failed")
            entry["record"] = {
                "top_k": k, "n_groups": 0, "pods": {}, "groups": [],
                "preemptions": [], "gangs": {}, "gangs_unschedulable": [],
                "unplaced": [], "error": "materialize failed",
            }
            entry["fingerprint"] = None
    return entry


def capture(inp, res, backend: str, enc=None,
            table: Optional[Tuple[np.ndarray, np.ndarray]] = None,
            annotations: Optional[dict] = None,
            drain_notes: bool = False) -> Optional[dict]:
    """Store the explain entry for one solve. Never raises: provenance
    must not fail a solve. The stored entry is DEFERRED — only references
    are kept here; the record builds on first store read. Returns the
    stored entry (tests) or None when disabled/failed."""
    if not _ENABLED:
        return None
    try:
        from ..metrics.registry import SOLVER_EXPLAIN_RECORDS
        from ..obs import trace as obstrace

        notes = _drain_notes() if drain_notes else None
        ann = dict(annotations or {})
        ann.setdefault("source", "device" if table is not None else "host")
        ann["backend"] = backend
        jseq = obstrace.current_journal_seq()
        if jseq is not None:
            # streaming attribution (solver/streaming.py): a streamed solve
            # has no snapshot boundary — the journal seq of the event batch
            # that triggered it is how /debug/explain answers "which solve"
            ann.setdefault("journal_seq", jseq)
        sid = obstrace.current_solve_id() or f"x{next(_XSEQ):06d}"
        entry = {
            "solve_id": sid,
            "tenant_id": obstrace.current_tenant_id(),
            "journal_seq": jseq,
            "annotations": ann,
            "_defer": (inp, enc, res, table, notes, _TOP_K),
        }
        SOLVER_EXPLAIN_RECORDS.inc(source=ann["source"])
        return _STORE.put(sid, entry)
    except Exception:  # noqa: BLE001 — diagnostics never abort a solve
        log.exception("explain: capture failed (backend=%s) — continuing",
                      backend)
        return None
