"""Rolling-baseline latency anomaly detection per trace stage.

SLO burn rates (obs/slo.py) alert on absolute objectives an operator
wrote down; this module alerts on DEVIATION FROM THE STAGE'S OWN
HISTORY, so a recompile storm, arena-thrash, or fold-drift re-baseline
loop surfaces within seconds of starting — even when the absolute
latency is still inside its SLO.

Baseline math (solver/SPEC.md "Telemetry semantics"):

- `mean`  — EWMA of the stage duration (alpha 0.1);
- `dev`   — EWMA of |x - mean| (the mean absolute deviation);
- `q`     — streaming ~p95: an asymmetric-step quantile walk (up-steps
            19x the down-step, both proportional to `dev`), so the
            estimate needs no sample buffer and adapts as the stage
            drifts.

An observation BREACHES when x > multiplier * max(mean + 3*dev, q)
after `min_samples` warm-up observations. `sustain` consecutive
breaches TRIP the stage (counter + gauge + /healthz WARN + one
throttled flight-recorder dump with reason `perf_anomaly`); `recover`
consecutive clean observations clear it. While breaching, the baseline
updates at alpha/8 — resistant enough not to chase a regression, alive
enough that a legitimate workload shift re-baselines instead of paging
forever.

Feed: `observe_trace()` is called by obs/trace.finish for every
completed trace — the same spans that feed the histograms and SLOs, no
second timing source. The clock is injectable (`configure(clock=...)`)
so tests drive trip/recover/throttle deterministically.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, Optional

from ..metrics.registry import SOLVER_PERF_ANOMALIES, SOLVER_PERF_ANOMALY_STATE

log = logging.getLogger("karpenter_tpu")

_ALPHA = 0.1
_Q_LR = 0.05  # quantile step = dev * _Q_LR (x19 upward)
_MAX_STAGES = 64


class _Baseline:
    __slots__ = ("n", "mean", "dev", "q", "breach_streak", "ok_streak",
                 "anomalous", "trips", "last_dump")

    def __init__(self):
        self.n = 0
        self.mean = 0.0
        self.dev = 0.0
        self.q = 0.0
        self.breach_streak = 0
        self.ok_streak = 0
        self.anomalous = False
        self.trips = 0
        self.last_dump: Optional[float] = None

    def threshold(self, multiplier: float) -> float:
        return multiplier * max(self.mean + 3.0 * self.dev, self.q)

    def observe(self, x: float, multiplier: float, min_samples: int) -> bool:
        """Fold one duration; returns True when it breached the baseline."""
        warm = self.n >= min_samples
        breach = warm and x > self.threshold(multiplier)
        alpha = _ALPHA / 8.0 if breach else _ALPHA
        if self.n == 0:
            self.mean = x
            self.q = x
        else:
            diff = x - self.mean
            self.mean += alpha * diff
            self.dev += alpha * (abs(diff) - self.dev)
            step = max(self.dev, abs(self.mean) * 0.01, 1e-9) * _Q_LR
            if x > self.q:
                self.q += 19.0 * step
            else:
                self.q -= step
        self.n += 1
        return breach


_LOCK = threading.Lock()
_ENABLED = True
_CLOCK = time.monotonic
_MULTIPLIER = 3.0
_SUSTAIN = 5
_RECOVER = 10
_MIN_SAMPLES = 20
_DUMP_INTERVAL_S = 60.0
_STAGES: Dict[str, _Baseline] = {}


def configure(enabled: bool = True, multiplier: float = 3.0, sustain: int = 5,
              recover: int = 10, min_samples: int = 20,
              dump_interval_s: float = 60.0, clock=time.monotonic) -> None:
    """(Re)configure the detector; resets every stage baseline — call once
    at operator boot (multiplier from --anomaly-threshold), or per-test."""
    global _ENABLED, _MULTIPLIER, _SUSTAIN, _RECOVER, _MIN_SAMPLES
    global _DUMP_INTERVAL_S, _CLOCK
    with _LOCK:
        _ENABLED = bool(enabled)
        _MULTIPLIER = float(multiplier)
        _SUSTAIN = max(1, int(sustain))
        _RECOVER = max(1, int(recover))
        _MIN_SAMPLES = max(1, int(min_samples))
        _DUMP_INTERVAL_S = float(dump_interval_s)
        _CLOCK = clock
        _STAGES.clear()


def enabled() -> bool:
    return _ENABLED


def observe(stage: str, duration_s: float) -> None:
    """Fold one stage duration into its rolling baseline; trip/recover the
    stage's anomaly state and fire the (throttled) flight dump on a trip."""
    if not _ENABLED:
        return
    dump_tags = None
    with _LOCK:
        base = _STAGES.get(stage)
        if base is None:
            if len(_STAGES) >= _MAX_STAGES:
                return  # bounded: never let stage-name churn grow state
            base = _STAGES[stage] = _Baseline()
        if base.observe(duration_s, _MULTIPLIER, _MIN_SAMPLES):
            base.breach_streak += 1
            base.ok_streak = 0
        else:
            base.ok_streak += 1
            base.breach_streak = 0
            if base.anomalous and base.ok_streak >= _RECOVER:
                base.anomalous = False
                SOLVER_PERF_ANOMALY_STATE.set(0, stage=stage)
                log.info("anomaly: stage %s recovered (baseline %.1f ms)",
                         stage, base.mean * 1000.0)
        if base.breach_streak >= _SUSTAIN and not base.anomalous:
            base.anomalous = True
            base.trips += 1
            SOLVER_PERF_ANOMALIES.inc(stage=stage)
            SOLVER_PERF_ANOMALY_STATE.set(1, stage=stage)
            now = _CLOCK()
            if base.last_dump is None or now - base.last_dump >= _DUMP_INTERVAL_S:
                base.last_dump = now
                dump_tags = {
                    "stage": stage,
                    "observed_ms": round(duration_s * 1000.0, 2),
                    "baseline_ms": round(base.mean * 1000.0, 2),
                    "threshold_ms": round(
                        base.threshold(_MULTIPLIER) * 1000.0, 2),
                }
    if dump_tags is not None:
        log.warning(
            "anomaly: PERF ANOMALY on stage %s — %.1f ms sustained vs "
            "baseline %.1f ms (threshold %.1f ms)", dump_tags["stage"],
            dump_tags["observed_ms"], dump_tags["baseline_ms"],
            dump_tags["threshold_ms"],
        )
        from . import trace as _trace

        _trace.dump("perf_anomaly", **dump_tags)


def observe_trace(trace) -> None:
    """Feed one finished trace's closed spans (obs/trace.finish hook);
    never raises past it."""
    if not _ENABLED:
        return
    for sp in list(trace.spans):
        if sp.t1 is not None:
            observe(sp.name, sp.t1 - sp.t0)


def health() -> dict:
    """The /healthz "anomaly" object: warn while any stage is tripped."""
    with _LOCK:
        stages = {}
        worst = "ok"
        for name, b in sorted(_STAGES.items()):
            if b.n == 0:
                continue
            stages[name] = {
                "mean_ms": round(b.mean * 1000.0, 3),
                "p95_ms": round(b.q * 1000.0, 3),
                "samples": b.n,
                "anomalous": b.anomalous,
                "trips": b.trips,
            }
            if b.anomalous:
                worst = "warn"
    return {"state": worst, "stages": stages}
