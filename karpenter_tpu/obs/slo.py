"""Per-stage latency SLOs with multi-window burn rates + tenant metering.

Raw histograms (karpenter_solver_stage_seconds) answer "how slow was it";
an operator paging decision needs "how fast am I spending the error
budget". This module keeps, per SLO stage, a rolling 1-hour ring of 10s
buckets of (observations, threshold breaches) and evaluates the classic
multi-window burn rate:

    burn(window) = breach_fraction(window) / (1 - target)

over a FAST 5m window (catches a sudden regression within minutes) and a
SLOW 1h window (filters one-bucket blips). Alert states follow the
standard pairing — page when fast >= 14.4 AND slow >= 6 (budget gone in
hours), warn when fast >= 6 AND slow >= 3 — exported as
`karpenter_slo_burn_rate{stage,window}` gauges and the /healthz "slo"
object.

Feed: `observe_trace()` is called by obs/trace.finish for every completed
trace, so SLOs measure exactly what the spans measure — no second timing
source. The same hook meters per-tenant usage (solves, device-dispatch
milliseconds); the transfer ledger (solver/arena.py) meters per-tenant
h2d/d2h bytes through `meter_bytes()`. Unattributed solves meter under
tenant "default" so the series always exists.

The clock is injectable (`configure(clock=...)`) so tests drive window
rotation deterministically.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

from ..metrics.registry import (
    SLO_BREACHES,
    SLO_BURN_RATE,
    TENANT_METER_D2H_BYTES,
    TENANT_METER_DEVICE_MS,
    TENANT_METER_H2D_BYTES,
    TENANT_METER_SOLVES,
)

FAST_WINDOW_S = 300
SLOW_WINDOW_S = 3600
_BUCKET_S = 10
_N_BUCKETS = SLOW_WINDOW_S // _BUCKET_S

# multi-window alert thresholds (burn-rate pairs)
PAGE_FAST, PAGE_SLOW = 14.4, 6.0
WARN_FAST, WARN_SLOW = 6.0, 3.0

# stage -> (latency threshold seconds, target success fraction)
DEFAULT_OBJECTIVES: Dict[str, Tuple[float, float]] = {
    "solve": (1.0, 0.99),
    "pipeline.queue": (0.5, 0.99),
    "backend.dispatch": (0.5, 0.99),
}


def parse_objectives(spec: str) -> Dict[str, Tuple[float, float]]:
    """Parse the operator knob: "stage=threshold_ms:target,..." — e.g.
    "solve=1000:0.99,backend.dispatch=500:0.995". Empty string means the
    defaults. Raises ValueError on malformed entries (options.py turns
    that into a fail-closed SystemExit)."""
    if not spec.strip():
        return dict(DEFAULT_OBJECTIVES)
    out: Dict[str, Tuple[float, float]] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        stage, _, rest = part.partition("=")
        ms_s, _, target_s = rest.partition(":")
        stage = stage.strip()
        if not stage or not ms_s or not target_s:
            raise ValueError(f"bad SLO objective {part!r} "
                             "(want stage=threshold_ms:target)")
        ms = float(ms_s)
        target = float(target_s)
        if ms <= 0 or not (0.0 < target < 1.0):
            raise ValueError(f"bad SLO objective {part!r} "
                             "(threshold_ms > 0, 0 < target < 1)")
        out[stage] = (ms / 1000.0, target)
    return out


class _StageWindow:
    """Ring of 10s buckets over the slow window; head advances lazily on
    observe/read so idle stages decay to zero without a timer thread."""

    __slots__ = ("threshold_s", "target", "total", "breached", "_cur")

    def __init__(self, threshold_s: float, target: float):
        self.threshold_s = float(threshold_s)
        self.target = min(max(float(target), 0.0), 0.999999)
        self.total = [0] * _N_BUCKETS
        self.breached = [0] * _N_BUCKETS
        self._cur: Optional[int] = None  # absolute bucket id of the head

    def _advance(self, now: float) -> None:
        b = int(now // _BUCKET_S)
        if self._cur is None:
            self._cur = b
            return
        d = b - self._cur
        if d <= 0:
            return
        for i in range(min(d, _N_BUCKETS)):
            idx = (self._cur + 1 + i) % _N_BUCKETS
            self.total[idx] = 0
            self.breached[idx] = 0
        self._cur = b

    def observe(self, duration_s: float, now: float) -> bool:
        self._advance(now)
        idx = self._cur % _N_BUCKETS
        self.total[idx] += 1
        breach = duration_s > self.threshold_s
        if breach:
            self.breached[idx] += 1
        return breach

    def _fraction(self, window_s: int) -> float:
        n = window_s // _BUCKET_S
        tot = br = 0
        for i in range(n):
            idx = (self._cur - i) % _N_BUCKETS
            tot += self.total[idx]
            br += self.breached[idx]
        return br / tot if tot else 0.0

    def rates(self, now: float) -> Tuple[float, float]:
        self._advance(now)
        budget = 1.0 - self.target
        return (self._fraction(FAST_WINDOW_S) / budget,
                self._fraction(SLOW_WINDOW_S) / budget)


_LOCK = threading.Lock()
_CLOCK = time.monotonic
_STAGES: Dict[str, _StageWindow] = {}


def configure(objectives: Optional[Dict[str, Tuple[float, float]]] = None,
              clock=time.monotonic) -> None:
    """(Re)configure stage objectives; resets all windows — call once at
    operator boot, or per-test for isolation."""
    global _CLOCK, _STAGES
    with _LOCK:
        _CLOCK = clock
        obj = DEFAULT_OBJECTIVES if objectives is None else objectives
        _STAGES = {s: _StageWindow(th, tg) for s, (th, tg) in obj.items()}


configure()


def record(stage: str, duration_s: float, now: Optional[float] = None) -> None:
    """One span observation against its stage objective (no-op for stages
    without one). Pushes the stage's burn-rate gauges on every record so
    /metrics never lags the windows."""
    win = _STAGES.get(stage)
    if win is None:
        return
    with _LOCK:
        t = _CLOCK() if now is None else now
        if win.observe(duration_s, t):
            SLO_BREACHES.inc(stage=stage)
        fast, slow = win.rates(t)
    SLO_BURN_RATE.set(fast, stage=stage, window="fast")
    SLO_BURN_RATE.set(slow, stage=stage, window="slow")


def _state(fast: float, slow: float) -> str:
    if fast >= PAGE_FAST and slow >= PAGE_SLOW:
        return "page"
    if fast >= WARN_FAST and slow >= WARN_SLOW:
        return "warn"
    return "ok"


def burn_rates() -> Dict[str, Dict[str, float]]:
    with _LOCK:
        t = _CLOCK()
        return {s: dict(zip(("fast", "slow"), w.rates(t)))
                for s, w in _STAGES.items()}


def health() -> dict:
    """The /healthz "slo" object: per-stage burn rates + alert state,
    overall = the worst stage."""
    rates = burn_rates()
    stages = {}
    worst = "ok"
    order = {"ok": 0, "warn": 1, "page": 2}
    for s, r in sorted(rates.items()):
        st = _state(r["fast"], r["slow"])
        stages[s] = {"fast": round(r["fast"], 4), "slow": round(r["slow"], 4),
                     "state": st}
        if order[st] > order[worst]:
            worst = st
    return {"state": worst, "stages": stages}


# -- per-tenant metering -------------------------------------------------------


def observe_trace(trace) -> None:
    """Feed one finished trace: per-stage SLO observations + the tenant
    usage ledger (solves, device-dispatch ms). Called by obs/trace.finish;
    never raises past it."""
    tenant = getattr(trace, "tenant_id", None) or "default"
    TENANT_METER_SOLVES.inc(tenant=tenant)
    with _LOCK:
        now = _CLOCK()
    dispatch_ms = 0.0
    for sp in list(trace.spans):
        if sp.t1 is None:
            continue
        d = sp.t1 - sp.t0
        record(sp.name, d, now=now)
        if sp.name == "backend.dispatch":
            dispatch_ms += d * 1000.0
    if dispatch_ms:
        TENANT_METER_DEVICE_MS.inc(dispatch_ms, tenant=tenant)


def meter_bytes(tenant: Optional[str], h2d: int = 0, d2h: int = 0) -> None:
    """Transfer-ledger feed (solver/arena.py): per-tenant tunnel bytes."""
    t = tenant or "default"
    if h2d:
        TENANT_METER_H2D_BYTES.inc(h2d, tenant=t)
    if d2h:
        TENANT_METER_D2H_BYTES.inc(d2h, tenant=t)
