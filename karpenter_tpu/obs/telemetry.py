"""Runtime health plane: compile/recompile observability + telemetry ring.

The paper's latency story assumes the frozen ARG_SPEC kernel signature
never triggers a hot-path recompile after `prewarm_aot` — a single XLA
compile on the dispatch path costs more than a thousand steady-state
solves. Nothing watched that invariant; this module does, without
touching JAX internals:

- every public jitted entry point in solver/tpu/ffd.py is rebound to a
  `_KernelHook` proxy (`instrument()`) that derives a dispatch signature
  ((shape, dtype) per array argument + the static kwargs) and treats the
  first sighting of a signature as a compile event. That is exactly
  jit's own cache key granularity for this repo (no weak-type or
  sharding-only churn exists on these call sites), so the detector is
  deterministic on any backend — including CPU CI where a persistent
  compile cache would hide real compile latency.
- `mark_prewarm_done()` is the phase boundary (the operator's warm-up
  thread calls it after prewarm_aot + warmup): compiles before it count
  as kind=prewarm (expected), compiles after it on the dispatch path
  count as kind=hot_path — a defect that WARNs /healthz, dumps the
  flight recorder (reason `recompile`, throttled per reason), and
  attaches the offending signature's diff against the nearest known one.
- `lower()` calls proxy through to a `_LoweredHook` whose `.compile()`
  registers the signature as prewarmed — AOT lowers are never hot-path.
- the AOT coverage gauge + failure counter make a partially-broken
  prewarm visible at startup (`note_prewarm`, `note_prewarm_failure`).

The same module keeps the in-process telemetry ring served at
`/debug/vars?window=` and attached to flight-recorder dumps: periodic
samples of the health-plane gauges (`maybe_sample()` is called from the
pipeline's decode loop; `set_gauge()` lets the arena/ledger publish
scalars without coupling), a bounded event log (`note_event`: fleet
fences, arena evictions), and named health providers (`register_provider`
— the operator registers the streaming solver's health here so /healthz
can reach it through the same module-global pattern it uses for
obs/slo.py).

Off path: `configure(enabled=False)` makes the kernel hooks a single
module-global read + tail call — no signature tuple is built, nothing
allocates (bench.py guards this with sys.getallocatedblocks, like the
trace-off path). `__wrapped__` on every hook stays the inner plain
traceable function, so consolidate.py / parallel/sharded.py vmap it
directly and tests/test_arg_spec_drift.py introspects it unchanged.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from ..metrics.registry import (
    SOLVER_COMPILE_SECONDS,
    SOLVER_COMPILES,
    SOLVER_HBM_BYTES,
    SOLVER_PREWARM_COVERAGE,
    SOLVER_PREWARM_FAILURES,
)

log = logging.getLogger("karpenter_tpu")

_LOCK = threading.RLock()
_ENABLED = True
_CLOCK = time.monotonic
_SAMPLE_INTERVAL_S = 10.0

# kernel -> {signature: True} (insertion-ordered; bounded)
_SEEN: Dict[str, Dict[tuple, bool]] = {}
_SEEN_MAX = 512
# kernel -> ARG_SPEC-style names for signature diffs
_ARG_NAMES: Dict[str, Tuple[str, ...]] = {}
_PREWARM_DONE = False
_PREWARM = {"requested": 0, "compiled": 0, "failures": 0}
_PREWARM_FAIL_LOGGED: set = set()
# hot-path recompile records (newest last, bounded)
_HOT: deque = deque(maxlen=32)
_RING: deque = deque(maxlen=128)
_EVENTS: deque = deque(maxlen=64)
_GAUGES: Dict[str, float] = {}
_PROVIDERS: Dict[str, Callable[[], object]] = {}
_LAST_SAMPLE = 0.0
stats: Dict[str, int] = {"checks": 0, "compiles": 0, "hot_path_compiles": 0,
                         "samples": 0}


def configure(enabled: bool = True, ring: int = 128,
              sample_interval_s: float = 10.0, clock=time.monotonic) -> None:
    """(Re)configure the health plane; resets every counter, the seen-
    signature sets, the prewarm phase, and the ring — call once at operator
    boot, or per-test for isolation. Resetting the signature sets means the
    next dispatch of each bucket records one (prewarm-phase) compile event
    even when jit's in-process cache is still warm — the detector counts
    signature sightings, not XLA invocations (solver/SPEC.md "Telemetry
    semantics")."""
    global _ENABLED, _CLOCK, _SAMPLE_INTERVAL_S, _RING, _PREWARM_DONE
    global _LAST_SAMPLE
    with _LOCK:
        _ENABLED = bool(enabled)
        _CLOCK = clock
        _SAMPLE_INTERVAL_S = float(sample_interval_s)
        _RING = deque(maxlen=max(1, int(ring)))
        _SEEN.clear()
        _PREWARM_DONE = False
        _PREWARM.update(requested=0, compiled=0, failures=0)
        _PREWARM_FAIL_LOGGED.clear()
        _HOT.clear()
        _EVENTS.clear()
        _GAUGES.clear()
        _PROVIDERS.clear()
        _LAST_SAMPLE = 0.0
        stats.update(checks=0, compiles=0, hot_path_compiles=0, samples=0)


def enabled() -> bool:
    return _ENABLED


# -- dispatch signatures -------------------------------------------------------


def _sig_of(x) -> object:
    """Hashable signature of one call argument: (shape, dtype) for arrays
    and ShapeDtypeStructs, recursive for (Named)tuples (FFDState), the value
    itself for hashable statics. The dtype OBJECT (hashable, interned per
    type) goes in verbatim — stringifying 36 dtypes per dispatch would
    dominate the check cost (bench telemetry_overhead_pct guard)."""
    shp = getattr(x, "shape", None)
    if shp is not None:
        return (shp if type(shp) is tuple else tuple(shp),
                getattr(x, "dtype", None))
    if isinstance(x, tuple):
        return tuple(_sig_of(e) for e in x)
    try:
        hash(x)
        return x
    except TypeError:
        return repr(x)[:64]


def _signature(args: tuple, kwargs: dict) -> tuple:
    return (
        tuple(_sig_of(a) for a in args),
        tuple(sorted((k, _sig_of(v)) for k, v in kwargs.items())),
    )


def _sig_diff(name: str, sig: tuple) -> List[dict]:
    """The offending arg-signature diff: positions where `sig` departs from
    the NEAREST known signature of the same kernel (fewest differing
    entries), labeled with ARG_SPEC names when the kernel registered them."""
    known = _SEEN.get(name, {})
    args, kw = sig
    best, best_score = None, None
    for cand in known:
        cargs, ckw = cand
        if len(cargs) != len(args):
            continue
        score = sum(a != b for a, b in zip(args, cargs)) + (kw != ckw)
        if best_score is None or score < best_score:
            best, best_score = cand, score
    if best is None:
        return [{"arg": "*", "got": "no same-arity signature on record",
                 "want": None}]
    names = _ARG_NAMES.get(name, ())
    out = []
    for i, (got, want) in enumerate(zip(args, best[0])):
        if got != want:
            out.append({"arg": names[i] if i < len(names) else i,
                        "got": repr(got), "want": repr(want)})
        if len(out) >= 8:
            break
    if best[1] != kw:
        out.append({"arg": "statics", "got": repr(kw), "want": repr(best[1])})
    return out


def _note_compile(name: str, sig: tuple, seconds: float, kind: str) -> None:
    """Record one compile event; on kind=hot_path also record the defect
    (detector state + throttled flight dump with the signature diff)."""
    diff = None
    with _LOCK:
        seen = _SEEN.setdefault(name, {})
        if kind == "hot_path":
            diff = _sig_diff(name, sig)
            _HOT.append({"wall": time.time(), "kernel": name,
                         "compile_s": round(seconds, 4), "diff": diff})
            stats["hot_path_compiles"] += 1
        if sig not in seen:
            while len(seen) >= _SEEN_MAX:
                seen.pop(next(iter(seen)))
            seen[sig] = True
        stats["compiles"] += 1
    SOLVER_COMPILES.inc(kernel=name, kind=kind)
    SOLVER_COMPILE_SECONDS.observe(seconds, kernel=name, kind=kind)
    if kind == "hot_path":
        log.warning(
            "telemetry: HOT-PATH recompile of %s (%.0f ms) — post-prewarm "
            "dispatch hit an uncompiled signature; diff vs nearest known: %s",
            name, seconds * 1000.0, diff,
        )
        from . import trace as _trace

        _trace.dump("recompile", kernel=name,
                    compile_ms=round(seconds * 1000.0, 1), diff=repr(diff))


class _LoweredHook:
    """Proxy for a jit Lowered object: `.compile()` records a prewarm
    compile event and registers the signature as known (an AOT lower is by
    definition never a hot-path compile)."""

    __slots__ = ("_name", "_sig", "_lowered")

    def __init__(self, name: str, sig: tuple, lowered):
        self._name = name
        self._sig = sig
        self._lowered = lowered

    def compile(self, *a, **kw):
        t0 = time.perf_counter()
        out = self._lowered.compile(*a, **kw)
        _note_compile(self._name, self._sig, time.perf_counter() - t0,
                      "prewarm")
        return out

    def __getattr__(self, item):
        return getattr(self._lowered, item)


class _KernelHook:
    """Compile-observability proxy around one jitted entry point. Preserves
    `__wrapped__` (the plain traceable function) and passes every other
    attribute through to the jit object."""

    def __init__(self, name: str, fn, arg_names: Tuple[str, ...] = ()):
        self._name = name
        self._fn = fn
        self.__wrapped__ = fn.__wrapped__
        self.__name__ = name
        _ARG_NAMES[name] = tuple(arg_names)

    def __call__(self, *args, **kwargs):
        if not _ENABLED:
            return self._fn(*args, **kwargs)
        sig = _signature(args, kwargs)
        seen = _SEEN.get(self._name)
        stats["checks"] += 1
        if seen is not None and sig in seen:
            return self._fn(*args, **kwargs)
        t0 = time.perf_counter()
        out = self._fn(*args, **kwargs)
        _note_compile(self._name, sig, time.perf_counter() - t0,
                      "hot_path" if _PREWARM_DONE else "prewarm")
        return out

    def lower(self, *args, **kwargs):
        low = self._fn.lower(*args, **kwargs)
        if not _ENABLED:
            return low
        return _LoweredHook(self._name, _signature(args, kwargs), low)

    def __getattr__(self, item):
        return getattr(self._fn, item)


def instrument(name: str, fn, arg_names: Tuple[str, ...] = ()):
    """Wrap one jitted entry point (idempotent: re-instrumenting a hook
    returns it unchanged — module reloads must not stack proxies)."""
    if isinstance(fn, _KernelHook):
        return fn
    return _KernelHook(name, fn, arg_names)


# -- prewarm phase -------------------------------------------------------------


def note_prewarm(requested: int, compiled: int) -> None:
    """AOT prewarm coverage accounting (backend.prewarm_aot): lattice points
    compiled vs requested; the gauge and /healthz WARN derive from the
    running totals (prewarm may run once per mesh/bucket refresh)."""
    with _LOCK:
        _PREWARM["requested"] += int(requested)
        _PREWARM["compiled"] += int(compiled)
        req, comp = _PREWARM["requested"], _PREWARM["compiled"]
    SOLVER_PREWARM_COVERAGE.set(comp / req if req else 1.0)


def note_prewarm_failure(bucket: str, exc: BaseException) -> None:
    """Count one failed prewarm lattice point; logged once per bucket so a
    broken compile path is visible without a crash-loop's worth of spam."""
    with _LOCK:
        _PREWARM["failures"] += 1
        first = bucket not in _PREWARM_FAIL_LOGGED
        _PREWARM_FAIL_LOGGED.add(bucket)
    SOLVER_PREWARM_FAILURES.inc()
    if first:
        log.warning("telemetry: AOT prewarm failed at %s: %s: %s "
                    "(logged once per bucket; coverage < 100%% WARNs "
                    "/healthz)", bucket, type(exc).__name__, exc)


def mark_prewarm_done() -> None:
    """Arm the hot-path recompile detector: every signature first seen on a
    dispatch after this call is a defect. Called by the operator's warm-up
    thread after prewarm_aot + warmup complete."""
    global _PREWARM_DONE
    with _LOCK:
        _PREWARM_DONE = True


def prewarm_done() -> bool:
    return _PREWARM_DONE


def hot_path_records() -> List[dict]:
    with _LOCK:
        return list(_HOT)


# -- gauges / events / providers ----------------------------------------------


def set_gauge(name: str, value: float) -> None:
    """Publish one scalar into the telemetry ring's gauge map (arena bytes,
    ledger rates — anything a dashboard wants per sample window)."""
    if not _ENABLED:
        return
    _GAUGES[name] = float(value)


def note_event(name: str, **tags) -> None:
    """Append one bounded-log event (fleet fence, arena eviction): shows up
    in ring samples and flight-recorder dump payloads."""
    if not _ENABLED:
        return
    with _LOCK:
        _EVENTS.append({"wall": time.time(), "event": name, **tags})


def register_provider(name: str, fn: Callable[[], object]) -> None:
    """Register a named health provider (e.g. the streaming solver's
    health()); pulled by snapshot()/healthz through this module's globals —
    the endpoint handler has no operator reference (operator/__main__.py)."""
    _PROVIDERS[name] = fn


def provider_result(name: str) -> Optional[object]:
    fn = _PROVIDERS.get(name)
    if fn is None:
        return None
    try:
        return fn()
    except Exception as e:  # noqa: BLE001 — health must never take down /healthz
        return {"error": f"{type(e).__name__}: {e}"}


def hbm_stats() -> Optional[Dict[str, int]]:
    """JAX allocator watermarks when the runtime reports them (real devices
    and some CPU builds); pushes the karpenter_solver_hbm_bytes gauges.
    None — silently — everywhere memory_stats() is unsupported."""
    try:
        import jax

        ms = jax.devices()[0].memory_stats()
        if not ms:
            return None
        out = {k: int(v) for k, v in ms.items()
               if k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")}
        for k, v in out.items():
            SOLVER_HBM_BYTES.set(v, kind=k)
        return out or None
    except Exception:  # noqa: BLE001 — diagnostics never fail a solve
        return None


# -- ring / snapshots ----------------------------------------------------------


def _compile_totals() -> Dict[str, Dict[str, float]]:
    out: Dict[str, Dict[str, float]] = {}
    for kernel in _SEEN:
        ent = {}
        for kind in ("prewarm", "hot_path"):
            v = SOLVER_COMPILES.value(kernel=kernel, kind=kind)
            if v:
                ent[kind] = v
        if ent:
            out[kernel] = ent
    return out


def snapshot() -> Dict[str, object]:
    """Point-in-time health-plane state: compile totals, detector state,
    prewarm coverage, published gauges, provider results, HBM watermarks."""
    with _LOCK:
        prew = dict(_PREWARM)
        prew["done"] = _PREWARM_DONE
        body = {
            "enabled": _ENABLED,
            "stats": dict(stats),
            "compiles": _compile_totals(),
            "hot_path": list(_HOT)[-8:],
            "prewarm": prew,
            "gauges": dict(_GAUGES),
            "events": list(_EVENTS)[-16:],
        }
        providers = list(_PROVIDERS)
    body["providers"] = {n: provider_result(n) for n in providers}
    hbm = hbm_stats()
    if hbm:
        body["hbm"] = hbm
    return body


def sample(now: Optional[float] = None) -> Dict[str, object]:
    """Append one snapshot to the telemetry ring (the /debug/vars series)."""
    global _LAST_SAMPLE
    snap = snapshot()
    with _LOCK:
        t = _CLOCK() if now is None else now
        snap["wall"] = time.time()
        snap["monotonic"] = t
        _RING.append(snap)
        _LAST_SAMPLE = t
        stats["samples"] += 1
    return snap


def maybe_sample() -> None:
    """Throttled ring advance — called from the pipeline's decode loop (one
    cheap clock read per solve in the steady state)."""
    if not _ENABLED:
        return
    now = _CLOCK()
    if now - _LAST_SAMPLE < _SAMPLE_INTERVAL_S:
        return
    try:
        sample(now)
    except Exception:  # noqa: BLE001 — diagnostics never fail a solve
        log.exception("telemetry: ring sample failed — continuing")


def recent_samples(n: Optional[int] = None) -> List[dict]:
    with _LOCK:
        out = list(_RING)
    return out if n is None else out[-int(n):]


def debug_vars(window: Optional[int] = None) -> Dict[str, object]:
    """The /debug/vars payload: current snapshot + the last `window` ring
    samples (all retained samples when no window is given)."""
    return {"now": snapshot(), "samples": recent_samples(window)}


def dump_payload() -> Dict[str, object]:
    """What a flight-recorder dump attaches: the live snapshot, the last
    few ring samples, and the anomaly engine's state."""
    out = {"snapshot": snapshot(), "samples": recent_samples(4)}
    try:
        from . import anomaly as _anomaly

        out["anomaly"] = _anomaly.health()
    except Exception:  # noqa: BLE001
        out["anomaly"] = None
    return out


def health() -> Dict[str, object]:
    """The /healthz "telemetry" object: ok unless the recompile detector
    tripped or AOT prewarm coverage is short of the requested lattice."""
    with _LOCK:
        hot = list(_HOT)[-4:]
        hot_n = stats["hot_path_compiles"]
        prew = dict(_PREWARM)
        prew["done"] = _PREWARM_DONE
    warnings = []
    if hot_n:
        warnings.append("hot_path_recompiles")
    req = prew["requested"]
    coverage = prew["compiled"] / req if req else None
    if coverage is not None and coverage < 1.0:
        warnings.append("prewarm_coverage")
    if prew["failures"]:
        warnings.append("prewarm_failures")
    return {
        "state": "warn" if warnings else "ok",
        "warnings": warnings,
        "hot_path_compiles": hot_n,
        "recent_hot_path": hot,
        "prewarm": {**prew, "coverage": coverage},
    }
