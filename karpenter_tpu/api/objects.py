"""Core object model: the k8s objects the control loop consumes/produces.

This is a deliberately small, hermetic re-expression of the object surface the
reference interacts with through the kube API (SURVEY.md §1: "Kubernetes API
server is the message bus"). Objects are plain dataclasses stored in the
in-process API store (`karpenter_tpu.controllers.store`) with watch semantics,
so the whole control loop closes without a cluster — the same trick the
reference's kwok provider uses (kwok/ec2/ec2.go:374-628 creates Node objects
directly).
"""

from __future__ import annotations

import itertools
import time
from dataclasses import dataclass, field, replace
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

from ..scheduling.requirements import IN, NOT_IN, EXISTS, Requirement, Requirements
from ..utils.resources import Resources
from . import wellknown as wk

_uid_counter = itertools.count(1)


def new_uid(prefix: str = "uid") -> str:
    return f"{prefix}-{next(_uid_counter)}"


# Field metadata marking control-plane-clock timestamps: snapshot restore
# discovers these by dataclass introspection and rebases them by the
# restart's clock delta (controllers/snapshot.py) — a new timestamp field
# declared with this marker rebases automatically instead of silently
# skewing age math after restore (VERDICT r4 weak #4).
CLOCK = {"clock": True}


@dataclass
class ObjectMeta:
    name: str
    namespace: str = "default"
    uid: str = field(default_factory=lambda: new_uid())
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    finalizers: List[str] = field(default_factory=list)
    owner_refs: List[str] = field(default_factory=list)  # uids
    # None = "not yet persisted": Store.create stamps it from the store's
    # injected clock, so age math (GC grace, disruption ranking, expiry)
    # always compares against the same clock — a wall-clock default here
    # silently breaks every sim-clock deployment (r5 review finding)
    creation_timestamp: Optional[float] = field(default=None, metadata=CLOCK)
    deletion_timestamp: Optional[float] = field(default=None, metadata=CLOCK)
    resource_version: int = 0

    @property
    def deleting(self) -> bool:
        return self.deletion_timestamp is not None


@dataclass(frozen=True)
class Taint:
    key: str
    effect: str
    value: str = ""

    def as_tuple(self) -> Tuple[str, str, str]:
        return (self.key, self.value, self.effect)


@dataclass(frozen=True)
class Toleration:
    key: str = ""  # empty key + Exists tolerates everything
    operator: str = "Equal"  # Equal | Exists
    value: str = ""
    effect: str = ""  # empty matches all effects

    def tolerates(self, taint: Taint) -> bool:
        if self.effect and self.effect != taint.effect:
            return False
        if self.operator == EXISTS or self.operator == "Exists":
            return not self.key or self.key == taint.key
        return self.key == taint.key and self.value == taint.value


def tolerates_all(tolerations: Sequence[Toleration], taints: Sequence[Taint]) -> bool:
    """Pod schedulability gate: every NoSchedule/NoExecute taint must be
    tolerated (PreferNoSchedule is advisory and ignored, matching
    kube-scheduler semantics the reference simulates)."""
    for t in taints:
        if t.effect == wk.EFFECT_PREFER_NO_SCHEDULE:
            continue
        if not any(tol.tolerates(t) for tol in tolerations):
            return False
    return True


@dataclass
class TopologySpreadConstraint:
    max_skew: int
    topology_key: str
    when_unsatisfiable: str = "DoNotSchedule"  # or ScheduleAnyway
    label_selector: Dict[str, str] = field(default_factory=dict)
    min_domains: Optional[int] = None


@dataclass
class PodAffinityTerm:
    label_selector: Dict[str, str]
    topology_key: str
    anti: bool = False
    # weight != None => preferred (soft); reference treats preferred terms via
    # relaxation (website/.../scheduling.md:212-219)
    weight: Optional[int] = None
    # Internal marker set ONLY by the relax loop (solver/relax.py) when it
    # materializes an ACTIVE weighted anti term: the term blocks this pod's
    # own admission like a required anti, but must NOT register as an owned
    # anti at placement — the oracle's bookkeeping records only the original
    # pod's required terms, so satisfied preferences never constrain later
    # pods. Encodes as a kind-3 (blocking-only) domain sig.
    admission_only: bool = False


# Pod fields that feed the solver's cached signature / FFD sort key; assigning
# any of them drops the caches (see Pod.__setattr__).
_POD_SIG_FIELDS = frozenset(
    {
        "meta",
        "requests",
        "node_selector",
        "node_affinity",
        "preferred_node_affinity",
        "tolerations",
        "topology_spread",
        "affinity_terms",
        "priority",
        "volume_zones",
    }
)
_POD_CACHE_KEYS = ("_solver_sig", "_ffd_key", "_sig_num", "_mib_aligned")

# Global pod-mutation epoch: bumped when a pod that has been through the
# encoder (it carries cache keys) is mutated in place. Cross-solve encode
# caches key on (epoch, identity-fingerprint of the pod set): any in-place
# mutation of an encoded pod invalidates them. Fresh pods have no cache keys
# yet, so construction does not bump the epoch.
_POD_MUTATION_EPOCH = 0


def pod_mutation_epoch() -> int:
    return _POD_MUTATION_EPOCH


@dataclass
class Pod:
    meta: ObjectMeta
    requests: Resources = field(default_factory=Resources)
    node_selector: Dict[str, str] = field(default_factory=dict)
    # requiredDuringScheduling node affinity: list of OR'd term-groups, each a
    # Requirements conjunction.
    node_affinity: List[Requirements] = field(default_factory=list)
    preferred_node_affinity: List[Tuple[int, Requirements]] = field(default_factory=list)
    tolerations: List[Toleration] = field(default_factory=list)
    topology_spread: List[TopologySpreadConstraint] = field(default_factory=list)
    affinity_terms: List[PodAffinityTerm] = field(default_factory=list)
    node_name: Optional[str] = None  # binding
    phase: str = "Pending"
    priority: int = 0
    scheduling_gated: bool = False
    owner_kind: str = ""  # "DaemonSet" pods get special handling
    # PV zonal topology (website/.../concepts/scheduling.md:430+):
    # volume_claims names the pod's PVCs; volume_zones is the resolved zone
    # restriction from BOUND zonal PVs (maintained by
    # controllers/volume.VolumeTopologyController; None = unrestricted)
    volume_claims: List[str] = field(default_factory=list)
    volume_zones: Optional[Tuple[str, ...]] = None

    def __setattr__(self, name, value):
        object.__setattr__(self, name, value)
        if name in _POD_SIG_FIELDS:
            d = self.__dict__
            dropped = False
            for k in _POD_CACHE_KEYS:
                if d.pop(k, None) is not None:
                    dropped = True
            if dropped:
                global _POD_MUTATION_EPOCH
                _POD_MUTATION_EPOCH += 1

    def invalidate_solver_cache(self) -> None:
        """Drop cached solver signature/sort keys. Field ASSIGNMENT does this
        automatically (__setattr__); call this after mutating a nested
        container in place (e.g. `pod.meta.labels[...] = ...`), which
        __setattr__ cannot observe."""
        d = self.__dict__
        dropped = False
        for k in _POD_CACHE_KEYS:
            if d.pop(k, None) is not None:
                dropped = True
        if dropped:
            global _POD_MUTATION_EPOCH
            _POD_MUTATION_EPOCH += 1

    def scheduling_requirements(self) -> Requirements:
        """nodeSelector + ALL required node-affinity terms folded into one
        conjunction. NOTE: OR'd terms folded this way over-constrain; the
        scheduler handles alternatives properly via
        `Scheduler._pod_requirement_alternatives`. This fold is only used
        where a single conservative conjunction is acceptable (daemonset
        matching)."""
        reqs = Requirements.from_labels(self.node_selector)
        for term in self.node_affinity:
            reqs = reqs.union(term)
        if self.volume_zones is not None:
            # an EMPTY tuple (conflicting bound volumes) is an unsatisfiable
            # In-[] requirement, not "unrestricted"
            reqs.add(Requirement.create(wk.ZONE_LABEL, IN, list(self.volume_zones)))
        return reqs

    @property
    def bound(self) -> bool:
        return self.node_name is not None

    def gang(self) -> Optional[Tuple[str, int, int]]:
        """(gang_id, size, min_ranks) from the gang labels, or None. A
        malformed size/min-ranks label (non-integer, < 1) voids the gang —
        the pod schedules as an ordinary singleton rather than wedging a
        whole gang on a typo. min_ranks defaults to size and is clamped to
        it (a gang can never need more placements than members)."""
        gid = self.meta.labels.get(wk.GANG_LABEL)
        if not gid:
            return None
        try:
            size = int(self.meta.labels.get(wk.GANG_SIZE_LABEL, ""))
        except ValueError:
            return None
        if size < 1:
            return None
        raw = self.meta.labels.get(wk.GANG_MIN_RANKS_LABEL)
        try:
            min_ranks = min(size, int(raw)) if raw is not None else size
        except ValueError:
            min_ranks = size
        if min_ranks < 1:
            return None
        return (gid, size, min_ranks)


@dataclass
class Node:
    meta: ObjectMeta
    capacity: Resources = field(default_factory=Resources)
    allocatable: Resources = field(default_factory=Resources)
    taints: List[Taint] = field(default_factory=list)
    ready: bool = False
    provider_id: str = ""
    unschedulable: bool = False
    # node conditions: type -> status ("True"/"False"/"Unknown"), with the
    # last transition time per type (drives the repair controller)
    conditions: Dict[str, str] = field(default_factory=dict)
    # CLOCK marker on a DICT field: every value is a control-plane stamp;
    # snapshot rebase shifts each one (repair tolerations read these ages)
    condition_since: Dict[str, float] = field(default_factory=dict, metadata=CLOCK)

    def set_condition(self, ctype: str, status: str, now: float) -> None:
        if self.conditions.get(ctype) != status:
            self.conditions[ctype] = status
            self.condition_since[ctype] = now

    @property
    def name(self) -> str:
        return self.meta.name

    def labels(self) -> Dict[str, str]:
        return self.meta.labels


@dataclass
class PersistentVolume:
    """Zonal persistent volume: `zones` mirrors the PV's nodeAffinity zone
    terms (scheduling.md:430+ — a pod using a zonal PV must schedule in the
    PV's zone). Empty zones = non-zonal (no restriction)."""

    meta: ObjectMeta
    zones: List[str] = field(default_factory=list)
    storage_class: str = ""


@dataclass
class PersistentVolumeClaim:
    """Claim; `volume_name` set = bound. Unbound claims follow
    WaitForFirstConsumer semantics: no restriction during scheduling, then
    the volume controller binds a PV in the zone the pod landed in."""

    meta: ObjectMeta
    volume_name: Optional[str] = None
    storage_class: str = ""


@dataclass
class PodDisruptionBudget:
    meta: ObjectMeta
    selector: Dict[str, str] = field(default_factory=dict)
    min_available: Optional[int] = None
    max_unavailable: Optional[int] = None

    def matches(self, pod: Pod) -> bool:
        return all(pod.meta.labels.get(k) == v for k, v in self.selector.items())


# ---------------------------------------------------------------------------
# karpenter.sh API types
# ---------------------------------------------------------------------------


@dataclass
class Budget:
    """Disruption budget (website/.../disruption.md:274-330): `nodes` is a
    count or percentage; optional cron schedule+duration; optional reasons."""

    nodes: str = "10%"
    schedule: Optional[str] = None
    duration_s: Optional[float] = None
    reasons: Optional[List[str]] = None  # None => all reasons


@dataclass
class Disruption:
    consolidation_policy: str = "WhenEmptyOrUnderutilized"  # or WhenEmpty
    consolidate_after_s: float = 0.0
    budgets: List[Budget] = field(default_factory=lambda: [Budget()])


@dataclass
class NodeClaimTemplate:
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    taints: List[Taint] = field(default_factory=list)
    startup_taints: List[Taint] = field(default_factory=list)
    requirements: Requirements = field(default_factory=Requirements)
    node_class_ref: str = "default"
    expire_after_s: Optional[float] = None
    termination_grace_period_s: Optional[float] = None


@dataclass
class NodePool:
    """NodePool spec per website/.../nodepools.md:33-165,268-330,363-413."""

    meta: ObjectMeta
    template: NodeClaimTemplate = field(default_factory=NodeClaimTemplate)
    disruption: Disruption = field(default_factory=Disruption)
    limits: Resources = field(default_factory=Resources)
    weight: int = 0

    @property
    def name(self) -> str:
        return self.meta.name

    def scheduling_requirements(self) -> Requirements:
        """Template labels + requirements + the implied nodepool label."""
        reqs = Requirements.from_labels(self.template.labels)
        reqs = reqs.union(self.template.requirements)
        reqs.add(Requirement.create(wk.NODEPOOL_LABEL, IN, [self.name]))
        return reqs


@dataclass
class NodeClaim:
    """The node-intent object: created by the provisioner, fulfilled by the
    cloud provider, tracked through registration/initialization
    (website/.../concepts/nodeclaims.md)."""

    meta: ObjectMeta
    nodepool: str = ""
    node_class_ref: str = "default"
    requirements: Requirements = field(default_factory=Requirements)
    resource_requests: Resources = field(default_factory=Resources)  # scheduled pod sum
    taints: List[Taint] = field(default_factory=list)
    startup_taints: List[Taint] = field(default_factory=list)
    expire_after_s: Optional[float] = None
    termination_grace_period_s: Optional[float] = None
    # instance types the scheduler found viable, cheapest-first at launch
    instance_type_options: List[str] = field(default_factory=list)

    # status
    provider_id: str = ""
    instance_type: str = ""
    zone: str = ""
    capacity_type: str = ""
    price: float = 0.0
    capacity: Resources = field(default_factory=Resources)
    allocatable: Resources = field(default_factory=Resources)
    node_name: Optional[str] = None
    launched: bool = False
    registered: bool = False
    initialized: bool = False
    drifted: Optional[str] = None  # drift reason
    # None = "not yet persisted" — Store.create stamps it (same sim-clock
    # discipline as ObjectMeta.creation_timestamp)
    last_transition: Optional[float] = field(default=None, metadata=CLOCK)

    @property
    def name(self) -> str:
        return self.meta.name
