"""Well-known label/annotation/taint vocabulary.

Mirrors the karpenter.sh domain vocabulary consumed throughout the reference
(kwok/ec2/ec2.go:44,890; website/content/en/preview/concepts/nodepools.md,
scheduling.md:383-387) — the three topology keys the scheduler supports, the
capacity-type domain, and the control-flow taints/annotations.
"""

GROUP = "karpenter.sh"

# Labels
NODEPOOL_LABEL = "karpenter.sh/nodepool"
CAPACITY_TYPE_LABEL = "karpenter.sh/capacity-type"
INSTANCE_TYPE_LABEL = "node.kubernetes.io/instance-type"
ZONE_LABEL = "topology.kubernetes.io/zone"
REGION_LABEL = "topology.kubernetes.io/region"
HOSTNAME_LABEL = "kubernetes.io/hostname"
ARCH_LABEL = "kubernetes.io/arch"
OS_LABEL = "kubernetes.io/os"
INITIALIZED_LABEL = "karpenter.sh/initialized"
REGISTERED_LABEL = "karpenter.sh/registered"
NODECLASS_LABEL = "karpenter.tpu/nodeclass"
# Per-NodePool solver-backend override (solver/convex.py): "ffd" pins the
# pool to the greedy device kernel, "convex" to the global ADMM backend;
# absent = the operator-level --solver-backend default. Read off NodePool
# metadata by the provisioner, carried on NodePoolSpec.solver_backend.
SOLVER_BACKEND_LABEL = "karpenter.sh/solver-backend"

# The exactly-three topology keys supported for topology spread
# (website/.../scheduling.md:383-387).
TOPOLOGY_KEYS = (ZONE_LABEL, HOSTNAME_LABEL, CAPACITY_TYPE_LABEL)

# Capacity types
CAPACITY_TYPE_ON_DEMAND = "on-demand"
CAPACITY_TYPE_SPOT = "spot"
CAPACITY_TYPE_RESERVED = "reserved"

# Gang (co-scheduling) labels — LABELS, not annotations, deliberately: labels
# ride the pod's solver signature (api/objects._POD_SIG_FIELDS via `meta`), so
# a gang edit invalidates exactly the affected encode-cache runs with no extra
# cache plumbing. A gang is the set of pending pods sharing a GANG_LABEL
# value; GANG_SIZE_LABEL declares the member count the gang needs and
# GANG_MIN_RANKS_LABEL (optional, default = size) the minimum members that
# must place for the gang to commit. GANG_TOPOLOGY_LABEL (optional; one of
# TOPOLOGY_KEYS) asks for rank-aware co-location: members gain a preferred
# self-affinity on that key, relaxed by the ordinary preference ladder.
GANG_LABEL = "scheduling.karpenter.sh/gang"
GANG_SIZE_LABEL = "scheduling.karpenter.sh/gang-size"
GANG_MIN_RANKS_LABEL = "scheduling.karpenter.sh/gang-min-ranks"
GANG_TOPOLOGY_LABEL = "scheduling.karpenter.sh/gang-topology"

# Annotations
DO_NOT_DISRUPT_ANNOTATION = "karpenter.sh/do-not-disrupt"
POD_DELETION_COST_ANNOTATION = "controller.kubernetes.io/pod-deletion-cost"
NODEPOOL_HASH_ANNOTATION = "karpenter.sh/nodepool-hash"
NODEPOOL_HASH_VERSION_ANNOTATION = "karpenter.sh/nodepool-hash-version"
NODECLASS_HASH_ANNOTATION = "karpenter.tpu/nodeclass-hash"

# Taints (key, effect)
UNREGISTERED_TAINT_KEY = "karpenter.sh/unregistered"
DISRUPTED_TAINT_KEY = "karpenter.sh/disrupted"
EFFECT_NO_SCHEDULE = "NoSchedule"
EFFECT_PREFER_NO_SCHEDULE = "PreferNoSchedule"
EFFECT_NO_EXECUTE = "NoExecute"

# Restricted label domains a NodePool may not set directly.
RESTRICTED_LABELS = frozenset({NODEPOOL_LABEL, HOSTNAME_LABEL})

# Finalizers
TERMINATION_FINALIZER = "karpenter.sh/termination"
