"""Admission validation — the CEL-rule analog of the reference's CRD schemas.

The reference injects x-kubernetes-validations into its CRDs
(hack/validation/{requirements,labels,kubelet}.sh →
pkg/apis/crds/karpenter.sh_nodepools.yaml) so the API server rejects invalid
NodePools before any controller sees them. This framework's store IS the API
server, so the same rules run as an admission hook (Store.set_validator):

  - restricted requirement keys / template label domains (karpenter.sh,
    kubernetes.io, k8s.io, and this provider's karpenter.tpu domain — with
    the same well-known allowlists the reference carves out);
  - operator shape rules: In needs values; Gt/Lt need a single positive
    integer; minValues needs at least that many values for In (and a sane
    bound);
  - budgets: nodes is a count or 0-100%; schedule must be set with duration
    (karpenter.sh_nodepools.yaml:140);
  - nodeClassRef name may not be empty.
"""

from __future__ import annotations

import re
from typing import List

from ..scheduling.requirements import DOES_NOT_EXIST, EXISTS, GT, IN, LT, NOT_IN
from . import wellknown as wk


class ValidationError(Exception):
    def __init__(self, errors: List[str]):
        super().__init__("; ".join(errors))
        self.errors = list(errors)


# keys the reference allows inside its own restricted domains
# (karpenter.sh_nodepools.yaml:199-209 allowlists, incl. the legacy beta set)
_WELLKNOWN_ALLOWED = {
    wk.ZONE_LABEL,
    "topology.kubernetes.io/region",
    wk.ARCH_LABEL,
    wk.OS_LABEL,
    wk.INSTANCE_TYPE_LABEL,
    wk.CAPACITY_TYPE_LABEL,
    "beta.kubernetes.io/instance-type",
    "beta.kubernetes.io/os",
    "beta.kubernetes.io/arch",
    "failure-domain.beta.kubernetes.io/zone",
    "failure-domain.beta.kubernetes.io/region",
    "node.kubernetes.io/windows-build",
}
_TPU_DOMAIN_ALLOWED_SUFFIXES = (
    "instance-family",
    "instance-size",
    "instance-generation",
    "instance-cpu",
    "instance-memory-mib",
)
_RESTRICTED_DOMAINS = ("karpenter.sh", "kubernetes.io", "k8s.io", "karpenter.tpu")
# operator-usable domains the reference carves out of the restricted set
# (karpenter.sh_nodepools.yaml:202-208)
_CARVED_OUT_DOMAINS = (
    "node.kubernetes.io",
    "node-restriction.kubernetes.io",
    "kops.k8s.io",
)
_BUDGET_NODES_RE = re.compile(r"^((100|[0-9]{1,2})%|[0-9]+)$")


def _domain_of(key: str) -> str:
    return key.split("/", 1)[0] if "/" in key else ""


def _key_restricted(key: str) -> bool:
    if key in _WELLKNOWN_ALLOWED:
        return False
    dom = _domain_of(key)
    if dom == "karpenter.tpu":
        return not any(key == f"karpenter.tpu/{s}" for s in _TPU_DOMAIN_ALLOWED_SUFFIXES)
    # the reference carves out whole operator-usable domains
    # (karpenter.sh_nodepools.yaml:202-208): node.kubernetes.io,
    # node-restriction.kubernetes.io, and kops.k8s.io
    for carved in _CARVED_OUT_DOMAINS:
        if dom == carved or dom.endswith("." + carved):
            return False
    for restricted in _RESTRICTED_DOMAINS:
        if dom == restricted or dom.endswith("." + restricted):
            return True
    return False


def _validate_requirement(key: str, r, errors: List[str], where: str) -> None:
    if key == wk.NODEPOOL_LABEL:
        # dedicated rule (karpenter.sh_nodepools.yaml:279): a template may
        # not require the pool-identity label — hijacking it would produce
        # claims contradicting the pool that owns them
        errors.append(f'{where}: label "karpenter.sh/nodepool" is restricted')
        return
    if key == wk.HOSTNAME_LABEL:
        errors.append(f'{where}: label "kubernetes.io/hostname" is restricted')
        return
    if _key_restricted(key):
        errors.append(f'{where}: label domain of "{key}" is restricted')
    op_in = not r.complement and r.require_present
    if op_in and not r.values and r.greater_than is None and r.less_than is None:
        errors.append(
            f"{where}: requirements with operator 'In' must have a value defined"
        )
    for bound in (r.greater_than, r.less_than):
        if bound is not None and bound < 0:
            errors.append(
                f"{where}: requirements operator 'Gt' or 'Lt' must have a "
                f"single positive integer value"
            )
    if r.min_values is not None:
        # explicit 0 is rejected too (CRD minimum: 1); unset is None
        if r.min_values > 50 or r.min_values < 1:
            errors.append(f"{where}: minValues must be within 1..50")
        if not r.complement and r.values and len(r.values) < r.min_values:
            errors.append(
                f"{where}: requirements with 'minValues' must have at least "
                f"that many values specified in the 'values' field"
            )


def validate_nodepool(np_obj) -> List[str]:
    errors: List[str] = []
    tmpl = np_obj.template
    for key, r in tmpl.requirements.items():
        _validate_requirement(key, r, errors, "spec.template.spec.requirements")
    for key in tmpl.labels:
        if key == wk.HOSTNAME_LABEL:
            errors.append('labels: label "kubernetes.io/hostname" is restricted')
        elif key == wk.NODEPOOL_LABEL:
            errors.append('labels: label "karpenter.sh/nodepool" is restricted')
        elif _key_restricted(key):
            errors.append(f'labels: label domain of "{key}" is restricted')
    for b in np_obj.disruption.budgets:
        if not _BUDGET_NODES_RE.match(b.nodes):
            errors.append(
                f"budgets: nodes must be a count or a 0-100 percentage, got {b.nodes!r}"
            )
        if (b.schedule is None) != (b.duration_s is None):
            errors.append("budgets: 'schedule' must be set with 'duration'")
        if b.schedule is not None:
            from ..disruption.cron import Cron

            try:
                Cron(b.schedule)
            except ValueError as e:
                errors.append(f"budgets: {e}")
    if not tmpl.node_class_ref:
        errors.append("nodeClassRef: name may not be empty")
    return errors


def validate_nodeclaim(claim) -> List[str]:
    errors: List[str] = []
    for key, r in claim.requirements.items():
        # NodeClaim requirements legitimately carry karpenter.sh/nodepool and
        # instance-type narrowing set by the provisioner
        if key in (wk.NODEPOOL_LABEL, wk.INSTANCE_TYPE_LABEL):
            continue
        _validate_requirement(key, r, errors, "spec.requirements")
    return errors


def admission_validator(kind: str, obj) -> None:
    """Store admission hook: raises ValidationError on rule violations."""
    if kind == "nodepools":
        errors = validate_nodepool(obj)
    elif kind == "nodeclaims":
        errors = validate_nodeclaim(obj)
    else:
        return
    if errors:
        raise ValidationError(errors)


def rules_document() -> list:
    """Machine-readable export of the admission rules — the analog of the
    reference's CRD yamls with injected x-kubernetes-validations
    (charts/karpenter-crd, pkg/apis/crds/karpenter.sh_nodepools.yaml): the
    store IS this framework's API server, so the schema artifact is
    GENERATED from the enforcing code rather than maintained beside it,
    and can never drift. Rendered by `python -m karpenter_tpu.deploy
    --crds`."""
    return [
        {
            "apiVersion": "karpenter.tpu/v1",
            "kind": "ValidationRules",
            "metadata": {"name": "nodepools.karpenter.sh"},
            "spec": {
                "restrictedLabelDomains": list(_RESTRICTED_DOMAINS),
                "carvedOutDomains": list(_CARVED_OUT_DOMAINS),
                "wellKnownAllowedKeys": sorted(_WELLKNOWN_ALLOWED),
                "tpuDomainAllowedSuffixes": list(_TPU_DOMAIN_ALLOWED_SUFFIXES),
                "forbiddenTemplateLabels": [wk.HOSTNAME_LABEL, wk.NODEPOOL_LABEL],
                "requirementOperators": {
                    "In": "requires at least one value",
                    "Gt/Lt": "require a single non-negative integer value",
                    "minValues": "1..50, and an In set must carry at least "
                                 "minValues values",
                },
                "budgets": {
                    "nodes": _BUDGET_NODES_RE.pattern,
                    "schedule": "cron, must be set together with duration",
                },
                "nodeClassRef": "name may not be empty",
            },
        },
        {
            "apiVersion": "karpenter.tpu/v1",
            "kind": "ValidationRules",
            "metadata": {"name": "nodeclaims.karpenter.sh"},
            "spec": {
                # requirements flow through the same _validate_requirement
                # path as NodePools: identical domain carve-outs/allowlists
                "restrictedLabelDomains": list(_RESTRICTED_DOMAINS),
                "carvedOutDomains": list(_CARVED_OUT_DOMAINS),
                "wellKnownAllowedKeys": sorted(_WELLKNOWN_ALLOWED),
                "tpuDomainAllowedSuffixes": list(_TPU_DOMAIN_ALLOWED_SUFFIXES),
                "exemptKeys": [wk.NODEPOOL_LABEL, wk.INSTANCE_TYPE_LABEL],
            },
        },
    ]
