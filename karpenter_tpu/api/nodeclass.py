"""KwokNodeClass: the provider-specific node configuration object.

The analog of the reference's EC2NodeClass CRD (pkg/apis/crds/
karpenter.k8s.aws_ec2nodeclasses.yaml; resolved by the nodeclass status
controller, pkg/controllers/nodeclass/controller.go:62-100): where EC2NodeClass
selects AMIs/subnets/security-groups, KwokNodeClass selects the slices of the
synthetic catalog (families, generations, zones) and an image version whose
change constitutes drift — the same role AMI drift plays in the reference
(drift.go:34-74).
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .objects import ObjectMeta


@dataclass
class KwokNodeClass:
    meta: ObjectMeta
    # catalog selection (subnet/SG/AMI-selector analogs)
    instance_families: Optional[List[str]] = None  # None = all
    min_generation: int = 0
    zones: Optional[List[str]] = None  # None = all
    # image version: bumping it drifts every node built from this class
    image_version: str = "v1"
    # kubelet-ish knobs that participate in the static hash
    max_pods_override: Optional[int] = None

    # status
    ready: bool = True
    status_message: str = ""

    @property
    def name(self) -> str:
        return self.meta.name

    def static_hash(self) -> str:
        """Drift hash over the spec (the reference's EC2NodeClass hash
        annotation, cloudprovider.go:128-131)."""
        spec = {
            "instance_families": sorted(self.instance_families) if self.instance_families else None,
            "min_generation": self.min_generation,
            "zones": sorted(self.zones) if self.zones else None,
            "image_version": self.image_version,
            "max_pods_override": self.max_pods_override,
        }
        return hashlib.sha256(json.dumps(spec, sort_keys=True).encode()).hexdigest()[:16]
