"""Cluster state: the scheduler/disruption view of current capacity.

Mirrors karpenter core `pkg/controllers/state` (state.Cluster — SURVEY.md
§2.1): nodes + nodeclaims + pod bindings + daemonset overhead, feeding both
the provisioner and the disruption engine. Because this framework's API store
is in-process (no network), state is computed from the store on demand rather
than via a separate event-driven cache — same interface, simpler consistency
(the reference needs `karpenter_cluster_state_synced`; we are synced by
construction).

Nomination tracking prevents the disruption engine from deleting capacity the
provisioner just targeted (reference behavior: nominated nodes are excluded
from consolidation for a window).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..api import wellknown as wk
from ..api.objects import Node, NodeClaim, Pod
from ..controllers import store as st
from ..metrics.registry import CLUSTER_STATE_NODE_COUNT
from ..provisioning.scheduler import BoundPodRef, ExistingNode
from ..utils.resources import PODS, Resources


@dataclass
class StateNode:
    """A unified view over (Node, NodeClaim) — either may be missing while
    the other exists (in-flight claim, or unmanaged node)."""

    node: Optional[Node]
    claim: Optional[NodeClaim]

    @property
    def name(self) -> str:
        if self.node is not None:
            return self.node.meta.name
        return self.claim.node_name or self.claim.name

    @property
    def provider_id(self) -> str:
        if self.node is not None and self.node.provider_id:
            return self.node.provider_id
        return self.claim.provider_id if self.claim else ""

    @property
    def nodepool(self) -> Optional[str]:
        if self.claim is not None:
            return self.claim.nodepool
        if self.node is not None:
            return self.node.meta.labels.get(wk.NODEPOOL_LABEL)
        return None

    @property
    def initialized(self) -> bool:
        return bool(self.claim and self.claim.initialized) or (
            self.node is not None and self.node.ready and self.claim is None
        )

    def labels(self) -> Dict[str, str]:
        if self.node is not None:
            return self.node.meta.labels
        if self.claim is not None:
            lab = dict(self.claim.requirements.labels())
            lab[wk.NODEPOOL_LABEL] = self.claim.nodepool
            return lab
        return {}

    def allocatable(self) -> Resources:
        if self.node is not None and self.node.allocatable:
            return self.node.allocatable
        if self.claim is not None:
            return self.claim.allocatable
        return Resources()


class EncodeDeltas:
    """Watch-driven revision stamps feeding the incremental encode cache
    (solver/encode_cache.py).

    The store is the message bus; this tracker folds its event stream into
    three monotonic counters so a solve can prove "nothing the encoder's
    catalog tables depend on changed since that cached core was built"
    without re-hashing the catalog:

      - catalog_rev: NodePools / NodeClasses / DaemonSets — any event here
        can change pool contents, instance types, axes universes, or the
        daemonset overhead, all of which live in the cached `_EncodeCore`'s
        catalog-keyed tables;
      - pods_rev:    Pods — the delta class the cache PATCHES through;
      - nodes_rev:   Nodes / NodeClaims — nodes are encoded outside the
        cached core (`_encode_with_nodes` runs every solve), so this rev is
        informational (bench/debug), not an invalidation input.

    `snapshot()` is the raw material for the `SolverInput.state_rev` stamp:
    `(self, catalog_rev, pods_rev, nodes_rev)`. The leading element is the
    tracker OBJECT, not `id(self)` — comparisons fall back to object
    identity (no `__eq__` defined), and cache entries holding the stamp
    keep the tracker alive, so a recycled address can never alias two
    trackers' counters. The stamp is a pure OPTIMIZATION hint: equal
    (identity, catalog element) lets the donor scan skip the deep
    pools/daemonset key compare; the encoder still compares the small
    zone/capacity-type/policy key segment, and an absent or mismatched
    stamp just falls back to the full tuple compare. Because pool content
    also depends on the cloud provider's ICE/reservation masking (no store
    event fires for those), Provisioner.build_input folds the provider's
    `catalog_token()` into the catalog element and stamps nothing when the
    provider cannot produce one. Hand-rolled test inputs leave state_rev
    None — always safe.
    """

    _CATALOG_KINDS = (st.NODEPOOLS, st.NODECLASSES, st.DAEMONSETS)
    _NODE_KINDS = (st.NODES, st.NODECLAIMS)

    def __init__(self, store: st.Store):
        self._lock = threading.Lock()
        self.catalog_rev = 0
        self.pods_rev = 0
        self.nodes_rev = 0
        store.watch(None, self._on_event)

    def _on_event(self, event: str, kind: str, obj) -> None:
        with self._lock:
            if kind in self._CATALOG_KINDS:
                self.catalog_rev += 1
            elif kind == st.PODS:
                self.pods_rev += 1
            elif kind in self._NODE_KINDS:
                self.nodes_rev += 1

    def snapshot(self) -> tuple:
        with self._lock:
            return (self, self.catalog_rev, self.pods_rev, self.nodes_rev)


class Cluster:
    def __init__(self, store: st.Store, clock=time.monotonic):
        self.store = store
        self.clock = clock
        self._nominations: Dict[str, float] = {}  # node name -> expiry
        self.nomination_window_s = 20.0
        # delta channel for the incremental encode cache; shared by the
        # provisioner and the disruption engine's simulation helper so
        # their solves patch against each other's cached cores
        self.encode_deltas = EncodeDeltas(store)

    # -- assembly -----------------------------------------------------------

    def state_nodes(self) -> List[StateNode]:
        nodes = {n.meta.name: n for n in self.store.list(st.NODES)}
        out: List[StateNode] = []
        claimed_nodes = set()
        for c in self.store.list(st.NODECLAIMS):
            node = nodes.get(c.node_name) if c.node_name else None
            if node is not None:
                claimed_nodes.add(node.meta.name)
            out.append(StateNode(node=node, claim=c))
        for name, n in nodes.items():
            if name not in claimed_nodes:
                out.append(StateNode(node=n, claim=None))
        CLUSTER_STATE_NODE_COUNT.set(float(len(out)))
        return out

    def bound_pods(self) -> Dict[str, List[Pod]]:
        by_node: Dict[str, List[Pod]] = {}
        for p in self.store.list(st.PODS):
            if p.node_name:
                by_node.setdefault(p.node_name, []).append(p)
        return by_node

    def pending_pods(self) -> List[Pod]:
        return [
            p
            for p in self.store.list(st.PODS)
            if not p.bound and not p.scheduling_gated and p.phase == "Pending"
            and not p.meta.deleting
        ]

    # -- scheduler inputs ---------------------------------------------------

    def existing_nodes_for_scheduler(self) -> List[ExistingNode]:
        """Schedulable capacity: ready nodes and in-flight claims, with free =
        allocatable − bound pod requests (the daemonset share is included in
        bound pods once they bind)."""
        by_node = self.bound_pods()
        out: List[ExistingNode] = []
        for sn in self.state_nodes():
            if sn.node is not None and (sn.node.meta.deleting or sn.node.unschedulable):
                continue
            if sn.claim is not None and sn.claim.meta.deleting:
                continue
            alloc = sn.allocatable()
            if not alloc:
                continue
            pods = by_node.get(sn.name, [])
            free = Resources(alloc)
            for p in pods:
                free = free.sub(p.requests)
            free[PODS] = alloc.get_(PODS) - len(pods)
            taints = list(sn.node.taints) if sn.node is not None else list(
                (sn.claim.taints if sn.claim else [])
            )
            # the unregistered taint is lifecycle plumbing, not a scheduling
            # constraint for the simulated scheduler (pods will land once
            # registration removes it)
            taints = [t for t in taints if t.key != wk.UNREGISTERED_TAINT_KEY]
            out.append(
                ExistingNode(
                    id=sn.name,
                    labels=dict(sn.labels()),
                    taints=taints,
                    free=free,
                    pod_labels=[dict(p.meta.labels) for p in pods],
                    bound_pods=[
                        BoundPodRef(
                            uid=p.meta.uid,
                            priority=p.priority,
                            requests=p.requests,
                            # never evict: do-not-disrupt, DaemonSets (their
                            # capacity doesn't free — they reschedule right
                            # back), or pods already on the way out
                            evictable=(
                                p.meta.annotations.get(
                                    wk.DO_NOT_DISRUPT_ANNOTATION
                                ) != "true"
                                and p.owner_kind != "DaemonSet"
                                and not p.meta.deleting
                            ),
                        )
                        for p in pods
                    ],
                )
            )
        out.sort(key=lambda n: n.id)
        return out

    def nodepool_usage(self) -> Dict[str, Resources]:
        usage: Dict[str, Resources] = {}
        for sn in self.state_nodes():
            np_name = sn.nodepool
            if not np_name:
                continue
            cap = None
            if sn.claim is not None and sn.claim.capacity:
                cap = sn.claim.capacity
            elif sn.node is not None:
                cap = sn.node.capacity
            if cap:
                usage[np_name] = usage.get(np_name, Resources()).add(cap)
        return usage

    # -- nominations --------------------------------------------------------

    def nominate(self, node_name: str) -> None:
        self._nominations[node_name] = self.clock() + self.nomination_window_s

    def is_nominated(self, node_name: str) -> bool:
        exp = self._nominations.get(node_name)
        if exp is None:
            return False
        if exp <= self.clock():
            del self._nominations[node_name]
            return False
        return True
