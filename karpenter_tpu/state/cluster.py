"""Cluster state: the scheduler/disruption view of current capacity.

Mirrors karpenter core `pkg/controllers/state` (state.Cluster — SURVEY.md
§2.1): nodes + nodeclaims + pod bindings + daemonset overhead, feeding both
the provisioner and the disruption engine. Because this framework's API store
is in-process (no network), state is computed from the store on demand rather
than via a separate event-driven cache — same interface, simpler consistency
(the reference needs `karpenter_cluster_state_synced`; we are synced by
construction).

Nomination tracking prevents the disruption engine from deleting capacity the
provisioner just targeted (reference behavior: nominated nodes are excluded
from consolidation for a window).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..api import wellknown as wk
from ..api.objects import Node, NodeClaim, Pod
from ..controllers import store as st
from ..metrics.registry import CLUSTER_STATE_NODE_COUNT
from ..provisioning.scheduler import BoundPodRef, ExistingNode
from ..utils.resources import PODS, Resources


@dataclass
class StateNode:
    """A unified view over (Node, NodeClaim) — either may be missing while
    the other exists (in-flight claim, or unmanaged node)."""

    node: Optional[Node]
    claim: Optional[NodeClaim]

    @property
    def name(self) -> str:
        if self.node is not None:
            return self.node.meta.name
        return self.claim.node_name or self.claim.name

    @property
    def provider_id(self) -> str:
        if self.node is not None and self.node.provider_id:
            return self.node.provider_id
        return self.claim.provider_id if self.claim else ""

    @property
    def nodepool(self) -> Optional[str]:
        if self.claim is not None:
            return self.claim.nodepool
        if self.node is not None:
            return self.node.meta.labels.get(wk.NODEPOOL_LABEL)
        return None

    @property
    def initialized(self) -> bool:
        return bool(self.claim and self.claim.initialized) or (
            self.node is not None and self.node.ready and self.claim is None
        )

    def labels(self) -> Dict[str, str]:
        if self.node is not None:
            return self.node.meta.labels
        if self.claim is not None:
            lab = dict(self.claim.requirements.labels())
            lab[wk.NODEPOOL_LABEL] = self.claim.nodepool
            return lab
        return {}

    def allocatable(self) -> Resources:
        if self.node is not None and self.node.allocatable:
            return self.node.allocatable
        if self.claim is not None:
            return self.claim.allocatable
        return Resources()


class EncodeDeltas:
    """Watch-driven revision stamps feeding the incremental encode cache
    (solver/encode_cache.py).

    The store is the message bus; this tracker folds its event stream into
    three monotonic counters so a solve can prove "nothing the encoder's
    catalog tables depend on changed since that cached core was built"
    without re-hashing the catalog:

      - catalog_rev: NodePools / NodeClasses / DaemonSets — any event here
        can change pool contents, instance types, axes universes, or the
        daemonset overhead, all of which live in the cached `_EncodeCore`'s
        catalog-keyed tables;
      - pods_rev:    Pods — the delta class the cache PATCHES through;
      - nodes_rev:   Nodes / NodeClaims — nodes are encoded outside the
        cached core (`_encode_with_nodes` runs every solve), so this rev is
        informational (bench/debug), not an invalidation input.

    `snapshot()` is the raw material for the `SolverInput.state_rev` stamp:
    `(self, catalog_rev, pods_rev, nodes_rev)`. The leading element is the
    tracker OBJECT, not `id(self)` — comparisons fall back to object
    identity (no `__eq__` defined), and cache entries holding the stamp
    keep the tracker alive, so a recycled address can never alias two
    trackers' counters. The stamp is a pure OPTIMIZATION hint: equal
    (identity, catalog element) lets the donor scan skip the deep
    pools/daemonset key compare; the encoder still compares the small
    zone/capacity-type/policy key segment, and an absent or mismatched
    stamp just falls back to the full tuple compare. Because pool content
    also depends on the cloud provider's ICE/reservation masking (no store
    event fires for those), Provisioner.build_input folds the provider's
    `catalog_token()` into the catalog element and stamps nothing when the
    provider cannot produce one. Hand-rolled test inputs leave state_rev
    None — always safe.
    """

    _CATALOG_KINDS = (st.NODEPOOLS, st.NODECLASSES, st.DAEMONSETS)
    _NODE_KINDS = (st.NODES, st.NODECLAIMS)

    def __init__(self, store: st.Store):
        self._lock = threading.Lock()
        self.catalog_rev = 0
        self.pods_rev = 0
        self.nodes_rev = 0
        store.watch(None, self._on_event)

    def _on_event(self, event: str, kind: str, obj) -> None:
        with self._lock:
            if kind in self._CATALOG_KINDS:
                self.catalog_rev += 1
            elif kind == st.PODS:
                self.pods_rev += 1
            elif kind in self._NODE_KINDS:
                self.nodes_rev += 1

    def snapshot(self) -> tuple:
        with self._lock:
            return (self, self.catalog_rev, self.pods_rev, self.nodes_rev)


@dataclass
class JournalEvent:
    """One store event in journal order. `obj` is the LIVE stored object —
    the store mutates objects in place before update(), so an event is a
    level-triggered dirty notification ("re-read this object"), never a
    state-at-event-time payload (solver/SPEC.md "Streaming semantics")."""

    seq: int
    event: str  # ADDED | MODIFIED | DELETED
    kind: str
    key: str  # store key, "namespace/name"
    obj: object


class ClusterJournal:
    """Ordered event journal feeding the streaming delta-solve subsystem
    (solver/streaming.py).

    Every store event gets a monotonic `seq` stamp — the journal's
    `state_rev`. The stamp is always maintained (it is one counter bump per
    event, and the disruption engine's mid-stream staleness guard reads it
    unconditionally); the event BUFFER only fills while a streaming consumer
    is attached, so the journal costs nothing when `--solver-streaming` is
    off. The buffer is bounded: when it overflows, the oldest events are
    dropped and the next drain() reports the loss so the consumer re-baselines
    from a full snapshot instead of silently acting on a gapped stream.

    `applied_rev` is the seq of the last event batch a streaming consumer
    folded into its solve universe — the reference point for the disruption
    engine's Superseded defer (a speculative probe prepared at rev r must not
    act once applied_rev > r: the provisioner has already solved against a
    newer universe than the probe's).
    """

    def __init__(self, store: st.Store, maxlen: int = 4096):
        self._lock = threading.Lock()
        self._seq = 0
        self.maxlen = max(1, int(maxlen))
        self._events: deque = deque()
        self._attached = False
        # seq of the oldest event still in the buffer minus 1: drain(after)
        # with after < _floor means events were lost to overflow
        self._floor = 0
        self.overflows = 0
        self.applied_rev = 0
        # secondary consumers (solver/federation.py JournalReplicator): taps
        # see every STAMPED event regardless of attach state, because the
        # drain() buffer is single-consumer — a tap must never share it
        self._taps: List = []
        store.watch(None, self._on_event)

    def add_tap(self, fn) -> None:
        """Register a secondary event consumer called with every stamped
        JournalEvent. Taps run synchronously under the store's watch
        dispatch and hold a LIVE obj reference — a tap that needs the
        event-time object must copy it before returning."""
        with self._lock:
            self._taps.append(fn)

    def _on_event(self, event: str, kind: str, obj) -> None:
        with self._lock:
            self._seq += 1
            seq = self._seq
            taps = list(self._taps)
            if not self._attached:
                self._floor = self._seq
                ev = None
            else:
                key = f"{obj.meta.namespace}/{obj.meta.name}"
                ev = JournalEvent(self._seq, event, kind, key, obj)
                self._events.append(ev)
                if len(self._events) > self.maxlen:
                    dropped = self._events.popleft()
                    self._floor = dropped.seq
                    self.overflows += 1
        if taps:
            if ev is None:
                key = f"{obj.meta.namespace}/{obj.meta.name}"
                ev = JournalEvent(seq, event, kind, key, obj)
            for fn in taps:
                fn(ev)

    def rev(self) -> int:
        """Monotonic seq of the newest store event (the journal state_rev)."""
        with self._lock:
            return self._seq

    def depth(self) -> int:
        with self._lock:
            return len(self._events)

    def attach(self) -> int:
        """Start buffering events; returns the current seq (the consumer's
        baseline — it must snapshot the store AT OR AFTER this seq)."""
        with self._lock:
            self._attached = True
            self._events.clear()
            self._floor = self._seq
            return self._seq

    def detach(self) -> None:
        with self._lock:
            self._attached = False
            self._events.clear()
            self._floor = self._seq

    def drain(self, after_seq: int) -> Tuple[List[JournalEvent], bool]:
        """Events with seq > after_seq, in order, plus a `lost` flag: True
        when the buffer no longer covers (after_seq, now] — an overflow
        evicted events the consumer never saw, or the consumer was never
        attached — so it must re-baseline from a full snapshot."""
        with self._lock:
            if not self._attached or after_seq < self._floor:
                return [], self._seq > after_seq
            out = [e for e in self._events if e.seq > after_seq]
            # retire everything: drained events were returned, and anything
            # at or before after_seq the consumer has already folded in
            self._events.clear()
            self._floor = self._seq
            return out, False

    def mark_applied(self, seq: int) -> None:
        """Record that a streaming consumer folded events through `seq` into
        its solve universe (read by the disruption staleness guard)."""
        with self._lock:
            if seq > self.applied_rev:
                self.applied_rev = seq


def existing_node_view(sn: StateNode, pods: List[Pod]) -> Optional[ExistingNode]:
    """One StateNode + its bound pods -> the scheduler's ExistingNode, or
    None when the node is not schedulable capacity. Shared verbatim by the
    snapshot path (existing_nodes_for_scheduler) and the streaming model
    (solver/streaming.py) so the two can never drift."""
    if sn.node is not None and (sn.node.meta.deleting or sn.node.unschedulable):
        return None
    if sn.claim is not None and sn.claim.meta.deleting:
        return None
    alloc = sn.allocatable()
    if not alloc:
        return None
    free = Resources(alloc)
    for p in pods:
        free = free.sub(p.requests)
    free[PODS] = alloc.get_(PODS) - len(pods)
    taints = list(sn.node.taints) if sn.node is not None else list(
        (sn.claim.taints if sn.claim else [])
    )
    # the unregistered taint is lifecycle plumbing, not a scheduling
    # constraint for the simulated scheduler (pods will land once
    # registration removes it)
    taints = [t for t in taints if t.key != wk.UNREGISTERED_TAINT_KEY]
    return ExistingNode(
        id=sn.name,
        labels=dict(sn.labels()),
        taints=taints,
        free=free,
        pod_labels=[dict(p.meta.labels) for p in pods],
        bound_pods=[
            BoundPodRef(
                uid=p.meta.uid,
                priority=p.priority,
                requests=p.requests,
                # never evict: do-not-disrupt, DaemonSets (their
                # capacity doesn't free — they reschedule right
                # back), or pods already on the way out
                evictable=(
                    p.meta.annotations.get(
                        wk.DO_NOT_DISRUPT_ANNOTATION
                    ) != "true"
                    and p.owner_kind != "DaemonSet"
                    and not p.meta.deleting
                ),
            )
            for p in pods
        ],
    )


class Cluster:
    def __init__(self, store: st.Store, clock=time.monotonic):
        self.store = store
        self.clock = clock
        self._nominations: Dict[str, float] = {}  # node name -> expiry
        self.nomination_window_s = 20.0
        # delta channel for the incremental encode cache; shared by the
        # provisioner and the disruption engine's simulation helper so
        # their solves patch against each other's cached cores
        self.encode_deltas = EncodeDeltas(store)
        # ordered event journal for the streaming delta-solve subsystem
        # (solver/streaming.py) and the disruption engine's mid-stream
        # staleness guard; costs one counter bump per store event until a
        # streaming consumer attaches
        self.journal = ClusterJournal(store)

    # -- assembly -----------------------------------------------------------

    def state_nodes(self) -> List[StateNode]:
        nodes = {n.meta.name: n for n in self.store.list(st.NODES)}
        out: List[StateNode] = []
        claimed_nodes = set()
        for c in self.store.list(st.NODECLAIMS):
            node = nodes.get(c.node_name) if c.node_name else None
            if node is not None:
                claimed_nodes.add(node.meta.name)
            out.append(StateNode(node=node, claim=c))
        for name, n in nodes.items():
            if name not in claimed_nodes:
                out.append(StateNode(node=n, claim=None))
        CLUSTER_STATE_NODE_COUNT.set(float(len(out)))
        return out

    def bound_pods(self) -> Dict[str, List[Pod]]:
        by_node: Dict[str, List[Pod]] = {}
        for p in self.store.list(st.PODS):
            if p.node_name:
                by_node.setdefault(p.node_name, []).append(p)
        return by_node

    def pending_pods(self) -> List[Pod]:
        return [
            p
            for p in self.store.list(st.PODS)
            if not p.bound and not p.scheduling_gated and p.phase == "Pending"
            and not p.meta.deleting
        ]

    # -- scheduler inputs ---------------------------------------------------

    def existing_nodes_for_scheduler(self) -> List[ExistingNode]:
        """Schedulable capacity: ready nodes and in-flight claims, with free =
        allocatable − bound pod requests (the daemonset share is included in
        bound pods once they bind)."""
        by_node = self.bound_pods()
        out: List[ExistingNode] = []
        for sn in self.state_nodes():
            en = existing_node_view(sn, by_node.get(sn.name, []))
            if en is not None:
                out.append(en)
        out.sort(key=lambda n: n.id)
        return out

    def nodepool_usage(self) -> Dict[str, Resources]:
        usage: Dict[str, Resources] = {}
        for sn in self.state_nodes():
            np_name = sn.nodepool
            if not np_name:
                continue
            cap = None
            if sn.claim is not None and sn.claim.capacity:
                cap = sn.claim.capacity
            elif sn.node is not None:
                cap = sn.node.capacity
            if cap:
                usage[np_name] = usage.get(np_name, Resources()).add(cap)
        return usage

    # -- nominations --------------------------------------------------------

    def nominate(self, node_name: str) -> None:
        self._nominations[node_name] = self.clock() + self.nomination_window_s

    def is_nominated(self, node_name: str) -> bool:
        exp = self._nominations.get(node_name)
        if exp is None:
            return False
        if exp <= self.clock():
            del self._nominations[node_name]
            return False
        return True
