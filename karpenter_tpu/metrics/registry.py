"""Prometheus-style metrics registry.

Series names follow the reference's documented metrics
(website/content/en/preview/reference/metrics.md) so dashboards translate:
karpenter_scheduler_scheduling_duration_seconds (metrics.md:190-194),
karpenter_scheduler_queue_depth (:196-198), karpenter_voluntary_disruption_*
(:168-188), karpenter_cloudprovider_* (:298-322), batcher series (:324-332).
Text exposition format is Prometheus-compatible.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, List, Optional, Sequence, Tuple


class _Metric:
    def __init__(self, name: str, help_: str, label_names: Sequence[str] = ()):
        self.name = name
        self.help = help_
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()

    def _key(self, labels: Dict[str, str]) -> Tuple[str, ...]:
        return tuple(labels.get(k, "") for k in self.label_names)


class Counter(_Metric):
    def __init__(self, name, help_, label_names=()):
        super().__init__(name, help_, label_names)
        self._values: Dict[Tuple[str, ...], float] = {}

    def inc(self, value: float = 1.0, **labels) -> None:
        with self._lock:
            k = self._key(labels)
            self._values[k] = self._values.get(k, 0.0) + value

    def value(self, **labels) -> float:
        return self._values.get(self._key(labels), 0.0)

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} counter"]
        for k, v in sorted(self._values.items()):
            out.append(f"{self.name}{_fmt_labels(self.label_names, k)} {v}")
        return out


class Gauge(_Metric):
    def __init__(self, name, help_, label_names=()):
        super().__init__(name, help_, label_names)
        self._values: Dict[Tuple[str, ...], float] = {}

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[self._key(labels)] = float(value)

    def add(self, value: float, **labels) -> None:
        with self._lock:
            k = self._key(labels)
            self._values[k] = self._values.get(k, 0.0) + value

    def value(self, **labels) -> float:
        return self._values.get(self._key(labels), 0.0)

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} gauge"]
        for k, v in sorted(self._values.items()):
            out.append(f"{self.name}{_fmt_labels(self.label_names, k)} {v}")
        return out


_DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60)


class Histogram(_Metric):
    def __init__(self, name, help_, label_names=(), buckets: Sequence[float] = _DEFAULT_BUCKETS):
        super().__init__(name, help_, label_names)
        self.buckets = tuple(buckets)
        self._counts: Dict[Tuple[str, ...], List[int]] = {}
        self._sums: Dict[Tuple[str, ...], float] = {}
        self._totals: Dict[Tuple[str, ...], int] = {}

    def observe(self, value: float, **labels) -> None:
        with self._lock:
            k = self._key(labels)
            counts = self._counts.setdefault(k, [0] * len(self.buckets))
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
            self._sums[k] = self._sums.get(k, 0.0) + value
            self._totals[k] = self._totals.get(k, 0) + 1

    def count(self, **labels) -> int:
        return self._totals.get(self._key(labels), 0)

    def sum(self, **labels) -> float:
        return self._sums.get(self._key(labels), 0.0)

    def percentile(self, q: float, **labels) -> float:
        """Approximate quantile from bucket counts (upper-bound estimate)."""
        k = self._key(labels)
        total = self._totals.get(k, 0)
        if total == 0:
            return math.nan
        target = q * total
        cum = 0
        counts = self._counts.get(k, [])
        for i, b in enumerate(self.buckets):
            cum = counts[i]
            if cum >= target:
                return b
        return math.inf

    def expose(self) -> List[str]:
        out = [f"# HELP {self.name} {self.help}", f"# TYPE {self.name} histogram"]
        for k in sorted(self._totals):
            for i, b in enumerate(self.buckets):
                lbl = _fmt_labels(self.label_names + ("le",), k + (_fmt_float(b),))
                out.append(f"{self.name}_bucket{lbl} {self._counts[k][i]}")
            lbl_inf = _fmt_labels(self.label_names + ("le",), k + ("+Inf",))
            out.append(f"{self.name}_bucket{lbl_inf} {self._totals[k]}")
            out.append(f"{self.name}_sum{_fmt_labels(self.label_names, k)} {self._sums[k]}")
            out.append(f"{self.name}_count{_fmt_labels(self.label_names, k)} {self._totals[k]}")
        return out


def _fmt_float(b: float) -> str:
    return f"{b:g}"


def _fmt_labels(names: Sequence[str], values: Sequence[str]) -> str:
    pairs = [f'{n}="{v}"' for n, v in zip(names, values) if v != "" or n == "le"]
    return "{" + ",".join(pairs) + "}" if pairs else ""


class Registry:
    def __init__(self):
        self.metrics: List[_Metric] = []

    def register(self, m):
        self.metrics.append(m)
        return m

    def expose(self) -> str:
        lines: List[str] = []
        for m in self.metrics:
            lines.extend(m.expose())
        return "\n".join(lines) + "\n"


REGISTRY = Registry()

# -- the reference's documented series (metrics.md) --------------------------

PROVISIONER_SCHEDULING_DURATION = REGISTRY.register(
    Histogram(
        "karpenter_scheduler_scheduling_duration_seconds",
        "Duration of scheduling simulations (metrics.md:190-194)",
    )
)
SCHEDULER_QUEUE_DEPTH = REGISTRY.register(
    Gauge("karpenter_scheduler_queue_depth", "Pending pods awaiting scheduling (metrics.md:196-198)")
)
NODECLAIMS_CREATED = REGISTRY.register(
    Counter("karpenter_nodeclaims_created_total", "NodeClaims created", ("nodepool",))
)
NODECLAIMS_TERMINATED = REGISTRY.register(
    Counter("karpenter_nodeclaims_terminated_total", "NodeClaims terminated", ("nodepool", "reason"))
)
DISRUPTION_EVAL_DURATION = REGISTRY.register(
    Histogram(
        "karpenter_voluntary_disruption_decision_evaluation_duration_seconds",
        "Disruption decision evaluation latency (metrics.md:182-184)",
        ("method",),
    )
)
DISRUPTION_DECISIONS = REGISTRY.register(
    Counter(
        "karpenter_voluntary_disruption_decisions_total",
        "Disruption decisions executed (metrics.md:168-188)",
        ("decision", "reason"),
    )
)
SOLVER_SOLVES = REGISTRY.register(
    Counter(
        "karpenter_tpu_solver_solves_total",
        "Solves by EXECUTING backend (device kernel / native C++ core / "
        "python oracle) — each concrete executor counts itself exactly "
        "once per logical solve; delegation layers count nothing "
        "(fallback-chain visibility; this framework's addition)",
        ("backend",),
    )
)
LEADER = REGISTRY.register(
    Gauge(
        "karpenter_leader",
        "1 while the labeled elector identity holds the leader lease, else "
        "0 (labeled so co-hosted electors — the in-process HA test "
        "configuration — never overwrite each other's series)",
        ("identity",),
    )
)
OFFERING_AVAILABLE = REGISTRY.register(
    Gauge(
        "karpenter_cloudprovider_instance_type_offering_available",
        "Per-offering availability (controllers/metrics/metrics.go:30-58)",
        ("instance_type", "zone", "capacity_type"),
    )
)
OFFERING_PRICE = REGISTRY.register(
    Gauge(
        "karpenter_cloudprovider_instance_type_offering_price_estimate",
        "Per-offering price estimate (controllers/metrics/metrics.go:30-58)",
        ("instance_type", "zone", "capacity_type"),
    )
)
CLOUDPROVIDER_DURATION = REGISTRY.register(
    Histogram(
        "karpenter_cloudprovider_duration_seconds",
        "CloudProvider method latency (metrics.md:298-322)",
        ("method",),
    )
)
CLOUDPROVIDER_ERRORS = REGISTRY.register(
    Counter(
        "karpenter_cloudprovider_errors_total",
        "CloudProvider errors (metrics.md:298-322)",
        ("method", "error"),
    )
)
BATCHER_BATCH_SIZE = REGISTRY.register(
    Histogram(
        "karpenter_cloudprovider_batcher_batch_size",
        "Request batch sizes (metrics.md:324-332)",
        ("batcher",),
        buckets=(1, 2, 5, 10, 25, 50, 100, 250, 500, 1000),
    )
)
BATCHER_BATCH_TIME = REGISTRY.register(
    Histogram(
        "karpenter_cloudprovider_batcher_batch_time_seconds",
        "Batch window durations (metrics.md:324-332)",
        ("batcher",),
    )
)
CLUSTER_STATE_NODE_COUNT = REGISTRY.register(
    Gauge("karpenter_cluster_state_node_count", "Nodes tracked in cluster state (metrics.md:286-296)")
)
PODS_UNSCHEDULABLE = REGISTRY.register(
    Gauge("karpenter_pods_state", "Pod scheduling states", ("state",))
)
ICE_CACHE_SIZE = REGISTRY.register(
    Gauge("karpenter_unavailable_offerings_count", "ICE-cached unavailable offerings")
)

# -- resilience series (solver/resilient.py, controllers/manager.py,
#    lifecycle/repair.py — this framework's addition) -------------------------

SOLVER_FALLBACK = REGISTRY.register(
    Counter(
        "karpenter_tpu_solver_fallback_total",
        "Solves routed to the fallback chain, by reason (timeout / "
        "device_error / encode_bug / unknown / invariant_gate / "
        "breaker_open / fallback_error / solver_exception)",
        ("reason",),
    )
)
# -- scheduling-class series (solver/scheduling_class.py). No _tpu segment:
#    the subsystem is backend-neutral (same counts on oracle/host/device) ----

SOLVER_PREEMPTIONS = REGISTRY.register(
    Counter(
        "karpenter_solver_preemptions_total",
        "Evictions planned by the preemption pass (victims of strictly-"
        "higher-priority pending pods; executed by provisioning/preemption.py)",
    )
)
SOLVER_GANGS_PLACED = REGISTRY.register(
    Counter(
        "karpenter_solver_gangs_placed_total",
        "Gangs that committed atomically (>= min-ranks members placed)",
    )
)
SOLVER_GANGS_UNSCHEDULABLE = REGISTRY.register(
    Counter(
        "karpenter_solver_gangs_unschedulable_total",
        "Gangs rolled back whole (fewer than min-ranks members could place)",
    )
)
SOLVER_PRIORITY_INVERSIONS = REGISTRY.register(
    Counter(
        "karpenter_solver_priority_inversions_total",
        "Unplaced pods that lost a committed slot to a strictly-lower-"
        "priority pod — structurally impossible under priority-major order; "
        "parity tests assert this stays 0",
    )
)

SOLVER_BREAKER_STATE = REGISTRY.register(
    Gauge(
        "karpenter_tpu_solver_breaker_state",
        "Device-path circuit breaker state: 0=closed, 1=half-open, 2=open",
    )
)
SOLVER_UPLOAD_BYTES = REGISTRY.register(
    Gauge(
        "karpenter_tpu_solver_upload_bytes_per_solve",
        "Host→device bytes uploaded by the last device solve (argument-"
        "arena delta upload; 0 = exact encode-cache hit, every kernel arg "
        "reused device-resident — solver/arena.py)",
    )
)
SOLVER_UPLOAD_ARRAYS = REGISTRY.register(
    Gauge(
        "karpenter_tpu_solver_upload_arrays_per_solve",
        "ffd.ARG_SPEC entries found stale (uploaded) by the last device "
        "solve; the full set is ~36",
    )
)
SOLVER_ARENA_HIT_RATE = REGISTRY.register(
    Gauge(
        "karpenter_tpu_solver_arena_hit_rate",
        "Fraction of arena adoptions that reused EVERY resident buffer "
        "(zero-upload dispatches) since process start",
    )
)
# checkpointed-scan resume series (ISSUE 5 names these without the _tpu
# segment — keep them as specified so the bench trajectory keys match)
SOLVER_RESUME_HIT_RATE = REGISTRY.register(
    Gauge(
        "karpenter_solver_resume_hit_rate",
        "Fraction of device dispatches that resumed the FFD scan from a "
        "device-resident checkpoint instead of replaying every run "
        "(solver/tpu/ffd.py ffd_resume) since process start",
    )
)
SOLVER_RUNS_SKIPPED = REGISTRY.register(
    Counter(
        "karpenter_solver_runs_skipped_total",
        "Scan runs skipped by checkpoint resume (prefix runs whose "
        "decisions were replayed from the checkpoint carry instead of "
        "re-executed)",
    )
)
# on-device decode + relax ladder series (ISSUE 6 — same naming rule as
# the resume series: no _tpu segment, bench trajectory keys match)
SOLVER_WIDE_REFETCH = REGISTRY.register(
    Counter(
        "karpenter_solver_wide_refetch_total",
        "Device solves whose packed claim-delta overflowed uint16 (value "
        ">65535 or entry count over capacity) and fell back to fetching "
        "the full dense take tables — the double-fetch carve-out of the "
        "on-device decode path (solver/backend.py _pack_dispatch)",
    )
)
SOLVER_DECODE_BYTES = REGISTRY.register(
    Gauge(
        "karpenter_solver_decode_bytes_per_solve",
        "Device→host result bytes fetched by the last device solve "
        "(packed claim-delta when --solver-device-decode is on; dense "
        "take tables otherwise or after a wide re-fetch)",
    )
)
SOLVER_RELAX_DISPATCHES = REGISTRY.register(
    Gauge(
        "karpenter_solver_relax_dispatches_per_solve",
        "Kernel dispatches the last preference-relaxation solve needed: "
        "1 on the device-resident ladder path, ~rungs on the host-driven "
        "redispatch loop (solver/backend.py _relax_solve)",
    )
)
# mesh-sharded solve series (ISSUE 7 — same naming rule as the resume /
# decode series: no _tpu segment, bench trajectory keys match)
SOLVER_MESH_DEVICES = REGISTRY.register(
    Gauge(
        "karpenter_solver_mesh_devices",
        "Devices in the provisioning-solve mesh the solver last dispatched "
        "across (1 = single-device scan; solver/backend.py _shard_mesh)",
    )
)
SOLVER_SHARD_FIXUP_RUNS = REGISTRY.register(
    Counter(
        "karpenter_solver_shard_fixup_runs_total",
        "Run-block scan steps replayed by the sharded solve's carry-"
        "exchange fix-up (blocks whose block-local placement could differ "
        "under the true prefix carry re-run via ffd_resume — SPEC.md "
        "\"Sharding semantics\")",
    )
)
SOLVER_SHARDED_FALLBACK = REGISTRY.register(
    Counter(
        "karpenter_solver_sharded_fallback_total",
        "Sharded-solve requests that fell back to the single-device scan, "
        "by decline reason: v_axis/q_axis (constraint axes a mesh path "
        "cannot express — none remain since the sparse constraint engine "
        "lifted the V/Q restriction), tiny_fleet (run axis narrower than "
        "the mesh or block-misaligned), no_mesh (no usable multi-device "
        "mesh behind a sharded request)",
        label_names=("reason",),
    )
)
CONTROLLER_ERRORS = REGISTRY.register(
    Counter(
        "karpenter_controller_errors_total",
        "Controller reconcile exceptions caught by the manager tick loop",
        ("controller",),
    )
)
REPAIR_BREAKER_OPEN = REGISTRY.register(
    Gauge(
        "karpenter_repair_breaker_open",
        "1 while the node-repair >20% unhealthy circuit breaker is tripped",
    )
)
CONTROLLER_TICK_SECONDS = REGISTRY.register(
    Histogram(
        "karpenter_controller_tick_seconds",
        "Per-controller reconcile duration per manager tick (crashing "
        "reconciles observe too, so a slow failure is as visible as a slow "
        "success)",
        ("controller",),
    )
)

# -- pipelined solve service (solver/pipeline.py) -----------------------------

SOLVE_PIPELINE_DEPTH = REGISTRY.register(
    Gauge(
        "karpenter_tpu_solve_pipeline_depth",
        "Solves currently in flight on the pipelined solve service "
        "(dispatched to the device, not yet decoded)",
    )
)
SOLVE_PIPELINE_OCCUPANCY = REGISTRY.register(
    Gauge(
        "karpenter_tpu_solve_pipeline_occupancy",
        "Fraction of wall time since service start with at least one solve "
        "in flight (1.0 = the device never waited on the host)",
    )
)
SOLVE_COALESCED = REGISTRY.register(
    Counter(
        "karpenter_tpu_solve_coalesced_requests_total",
        "Queued solve requests superseded before dispatch by a newer "
        "cluster-state revision of the same class (the stale snapshot never "
        "ran)",
        ("kind",),
    )
)
# -- solver fleet (solver/fleet.py; ISSUE 8 — same naming rule as the
#    resume / decode / shard series: no _tpu segment) -------------------------

FLEET_HEALTHY = REGISTRY.register(
    Gauge(
        "karpenter_solver_fleet_healthy",
        "Healthy (unfenced) solve owners: the unlabeled series carries the "
        "fleet-wide count, the owner-labeled series carries each owner's "
        "0/1 health bit (host-labeled under federation — empty host label "
        "keeps single-host series identity unchanged)",
        ("owner", "host"),
    )
)
FLEET_FAILOVER = REGISTRY.register(
    Counter(
        "karpenter_solver_failover_total",
        "Owner fencing events: the canary watchdog (or a breaker trip) "
        "declared an owner unhealthy and re-routed its work",
        ("owner", "host"),
    )
)
FLEET_REQUEUED = REGISTRY.register(
    Counter(
        "karpenter_solver_requeued_solves_total",
        "In-flight or queued solves re-routed off a fenced owner onto a "
        "healthy owner or degraded to the oracle (none dropped, none run "
        "twice — first-wins ticket delivery)",
        ("target", "host"),
    )
)
FLEET_CANARY_LATENCY = REGISTRY.register(
    Histogram(
        "karpenter_solver_canary_latency_seconds",
        "Liveness-probe canary solve latency per owner (a miss — deadline "
        "expiry — records a breaker failure instead of observing here)",
        ("owner", "host"),
    )
)

# -- federation (solver/federation.py; ISSUE 18 — same naming rule as the
#    fleet series: no _tpu segment, routing is backend-neutral) ---------------

FEDERATION_HOSTS_HEALTHY = REGISTRY.register(
    Gauge(
        "karpenter_federation_hosts_healthy",
        "Unfenced federation hosts: the unlabeled series carries the "
        "federation-wide count, the host-labeled series each host's 0/1 "
        "health bit (mirrors karpenter_solver_fleet_healthy one layer up)",
        ("host",),
    )
)
FEDERATION_TENANT_MOVES = REGISTRY.register(
    Counter(
        "karpenter_federation_tenant_moves_total",
        "Tenant re-homings observed at route time (consistent-hash ring "
        "membership changed between two routes of the same tenant) — the "
        "ring's bounded-disruption guarantee makes this ~K/N per host "
        "change, and the drift test pins that bound",
        ("tenant",),
    )
)
FEDERATION_REPLICATION_LAG = REGISTRY.register(
    Gauge(
        "karpenter_federation_journal_replication_lag",
        "Journal events replicated to a peer but not yet acknowledged "
        "(drained) by it: unlabeled = worst peer, peer-labeled = per peer. "
        "Bounds the re-baseline gap a surviving host must close on "
        "cross-host failover",
        ("peer",),
    )
)
FEDERATION_FAILOVERS = REGISTRY.register(
    Counter(
        "karpenter_federation_cross_host_failovers_total",
        "Host fencing events at the federation router: the fenced host left "
        "the ring and its outstanding solves were requeued, in submission "
        "order, onto the surviving hosts",
        ("host",),
    )
)
SOLVER_DEADLINE_LEAKED_THREADS = REGISTRY.register(
    Gauge(
        "karpenter_solver_deadline_leaked_threads",
        "resilient-solve watchdog threads whose post-deadline device call "
        "never returned (still alive after the bounded join) — a rising "
        "value means a backend is wedging, not just slow",
    )
)

# -- tracing / flight recorder (obs/ — ISSUE 10; same naming rule as the
#    fleet series: no _tpu segment, spans are backend-neutral) ----------------

SOLVER_STAGE_SECONDS = REGISTRY.register(
    Histogram(
        "karpenter_solver_stage_seconds",
        "Per-stage solve latency derived from trace spans (obs/trace.py): "
        "one observation per closed span at trace finish, labeled by span "
        "name (pipeline.queue / pipeline.dispatch / backend.encode / "
        "backend.upload / backend.dispatch / backend.fetch / backend.decode "
        "/ pipeline.decode / ...) — bench.py's stage breakdown reads the "
        "same spans, so bench and production measure the same thing",
        ("stage",),
    )
)
FLIGHT_RECORDER_DUMPS = REGISTRY.register(
    Counter(
        "karpenter_solver_flight_dumps_total",
        "Flight-recorder crash dumps written, by trigger (fleet_fence / "
        "breaker_open / invariant_gate) — obs/recorder.py; throttled "
        "repeats do not count",
        ("reason",),
    )
)

# -- tenancy (solver/tenancy.py; ISSUE 11 — same naming rule as the fleet /
#    trace series: no _tpu segment, the mux is backend-neutral) ---------------

TENANT_QUEUE_DEPTH = REGISTRY.register(
    Gauge(
        "karpenter_solver_tenant_queue_depth",
        "Solve requests held at the tenant mux (admitted, not yet forwarded "
        "to the owner pool), per tenant — the WFQ backlog",
        ("tenant",),
    )
)
TENANT_ADMISSION_REJECTS = REGISTRY.register(
    Counter(
        "karpenter_solver_tenant_admission_rejects_total",
        "Submissions refused by per-tenant queue-depth admission control "
        "(typed TenantAdmissionReject returned to the caller), per tenant",
        ("tenant",),
    )
)
TENANT_BREAKER_STATE = REGISTRY.register(
    Gauge(
        "karpenter_solver_tenant_breaker_state",
        "Per-tenant circuit breaker state: 0=closed, 1=half-open, 2=open — "
        "an open tenant breaker routes only THAT tenant to its oracle rung, "
        "never fencing a shared owner",
        ("tenant",),
    )
)
TENANT_SOLVE_SECONDS = REGISTRY.register(
    Histogram(
        "karpenter_solver_tenant_solve_seconds",
        "End-to-end solve latency through the tenant mux (submit to ticket "
        "resolution, queueing included), per tenant",
        ("tenant",),
    )
)
TENANT_DEGRADED = REGISTRY.register(
    Counter(
        "karpenter_solver_tenant_degraded_total",
        "Solves served by a tenant's OWN oracle-fallback ladder because its "
        "breaker was open or its device-path attempt failed, per tenant",
        ("tenant",),
    )
)
SOLVER_COHORT_SIZE = REGISTRY.register(
    Histogram(
        "karpenter_solver_cohort_size",
        "Members per fused cross-tenant cohort dispatch (tenancy.py WFQ "
        "cohort picking): size 1 never lands here — a lone winner rides "
        "the legacy single-head path",
        buckets=(2, 3, 4, 6, 8, 12, 16),
    )
)
SOLVER_FUSED_DISPATCHES = REGISTRY.register(
    Counter(
        "karpenter_solver_fused_dispatches_total",
        "Cross-tenant cohort dispatches forwarded as ONE downstream unit "
        "(>= 2 members; one kernel launch serves every fuse-compatible "
        "member)",
    )
)
SOLVER_COHORT_POISON_REPLAYS = REGISTRY.register(
    Counter(
        "karpenter_solver_cohort_poison_replays_total",
        "Cohort members whose fused device path failed and replayed solo "
        "on their OWN tenant's oracle lane (co-members kept their fused "
        "results), per tenant",
        ("tenant",),
    )
)

PROBE_BATCH_SIZE = REGISTRY.register(
    Histogram(
        "karpenter_tpu_disruption_probe_batch_size",
        "Candidate-prefix rows per batched speculative-probe dispatch "
        "(disruption consolidation; one row = one full re-solve of the "
        "universe minus that prefix)",
        buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024),
    )
)

# -- decision provenance (obs/explain.py; ISSUE 12 — same naming rule as the
#    trace series: no _tpu segment, records are backend-neutral) --------------

SOLVER_EXPLAIN_RECORDS = REGISTRY.register(
    Counter(
        "karpenter_solver_explain_records_total",
        "Explain records captured, by table source: device = decoded from "
        "the EXPLAIN wire section (tpu/ffd.explain_pack), host = the numpy "
        "deriver (obs/explain.host_table — oracle/native legs and every "
        "device carve-out)",
        ("source",),
    )
)
SOLVER_EXPLAIN_WIDE = REGISTRY.register(
    Counter(
        "karpenter_solver_explain_wide_total",
        "Device explain fetches whose wire buffer flagged overflow (node "
        "index above uint16) — the host deriver recomputed the table, "
        "mirroring the claim delta's wide re-fetch carve-out",
    )
)
SOLVER_EXPLAIN_BYTES = REGISTRY.register(
    Gauge(
        "karpenter_solver_explain_bytes_per_solve",
        "Device→host bytes the last EXPLAIN wire fetch moved (0 when the "
        "explain knob is off — the off path adds no tunnel traffic, which "
        "bench's --explain-suite asserts via the transfer ledger)",
    )
)

# -- SLO engine (obs/slo.py; ISSUE 12) ----------------------------------------

SLO_BURN_RATE = REGISTRY.register(
    Gauge(
        "karpenter_slo_burn_rate",
        "Multi-window burn rate per SLO stage: breach fraction over the "
        "window divided by the error budget (1 - target). window=fast is "
        "5m, window=slow is 1h; page when fast>=14.4 and slow>=6 "
        "(obs/slo.py, surfaced in /healthz)",
        ("stage", "window"),
    )
)
SLO_BREACHES = REGISTRY.register(
    Counter(
        "karpenter_slo_breaches_total",
        "Span observations exceeding their stage's SLO latency threshold "
        "(obs/slo.py objectives; fed from trace finish like "
        "karpenter_solver_stage_seconds)",
        ("stage",),
    )
)

# -- per-tenant metering (obs/slo.py; ISSUE 12 — billing-grade usage ledger
#    on top of the ISSUE 11 mux: tenant \"default\" when no mux attributed
#    the solve) ---------------------------------------------------------------

TENANT_METER_SOLVES = REGISTRY.register(
    Counter(
        "karpenter_tenant_meter_solves_total",
        "Completed solves metered per tenant (one per finished trace; "
        "tenant from the trace's tenancy attribution)",
        ("tenant",),
    )
)
TENANT_METER_DEVICE_MS = REGISTRY.register(
    Counter(
        "karpenter_tenant_meter_device_ms_total",
        "Device dispatch milliseconds metered per tenant (sum of "
        "backend.dispatch span durations at trace finish)",
        ("tenant",),
    )
)
TENANT_METER_H2D_BYTES = REGISTRY.register(
    Counter(
        "karpenter_tenant_meter_h2d_bytes_total",
        "Host→device bytes metered per tenant (transfer-ledger uploads "
        "attributed via the calling thread's trace tenancy)",
        ("tenant",),
    )
)
TENANT_METER_D2H_BYTES = REGISTRY.register(
    Counter(
        "karpenter_tenant_meter_d2h_bytes_total",
        "Device→host bytes metered per tenant (transfer-ledger fetches "
        "attributed via the calling thread's trace tenancy)",
        ("tenant",),
    )
)

# -- streaming delta-solve (solver/streaming.py; ISSUE 13) --------------------

STREAMING_BATCHES_APPLIED = REGISTRY.register(
    Counter(
        "karpenter_streaming_batches_applied_total",
        "Journal event batches the streaming model folded into its resident "
        "solve universe (one per non-empty drain; solver/streaming.py)",
    )
)
STREAMING_EVENTS_APPLIED = REGISTRY.register(
    Counter(
        "karpenter_streaming_events_applied_total",
        "Individual journal events folded across all applied batches",
    )
)
STREAMING_REBASELINE = REGISTRY.register(
    Counter(
        "karpenter_streaming_rebaseline_total",
        "Forced full re-baselines of the streaming model, by cause: journal "
        "overflow/loss, inexpressible batch (catalog mutation), epoch parity "
        "drift, fleet fence",
        ("reason",),
    )
)
STREAMING_JOURNAL_DEPTH = REGISTRY.register(
    Gauge(
        "karpenter_streaming_journal_depth",
        "Buffered events awaiting the next drain in the ClusterJournal "
        "(state/cluster.py; 0 while no streaming consumer is attached)",
    )
)
STREAMING_STATE_AGE = REGISTRY.register(
    Gauge(
        "karpenter_streaming_resident_state_age_seconds",
        "Age of the streaming model's device-resident baseline: seconds "
        "since the last full re-encode (re-baseline or epoch check) — how "
        "long decisions have been extending purely from deltas",
    )
)

# -- runtime health plane (obs/telemetry.py + obs/anomaly.py; ISSUE 14) -------

SOLVER_COMPILES = REGISTRY.register(
    Counter(
        "karpenter_solver_compiles_total",
        "Kernel (re)compiles observed at the jit/AOT entry points, by kernel "
        "and kind: kind=prewarm covers AOT lowers and warm-up-phase "
        "dispatches; kind=hot_path is any post-prewarm compile on the "
        "dispatch path — a defect the recompile detector WARNs on "
        "(obs/telemetry.py)",
        ("kernel", "kind"),
    )
)
SOLVER_COMPILE_SECONDS = REGISTRY.register(
    Histogram(
        "karpenter_solver_compile_seconds",
        "Wall seconds spent in a compiling kernel entry (trace + XLA compile "
        "+ first dispatch for kind=hot_path/prewarm calls; lower().compile() "
        "time for AOT prewarm points)",
        ("kernel", "kind"),
    )
)
SOLVER_PREWARM_COVERAGE = REGISTRY.register(
    Gauge(
        "karpenter_solver_prewarm_coverage",
        "AOT prewarm coverage: claim-bucket lattice points compiled divided "
        "by points requested (1.0 = full lattice; < 1.0 surfaces as a "
        "/healthz WARN — a broken compile cache shows at startup, not as "
        "mystery hot-path compiles)",
    )
)
SOLVER_PREWARM_FAILURES = REGISTRY.register(
    Counter(
        "karpenter_solver_prewarm_failures_total",
        "AOT prewarm lattice points that failed to lower/compile "
        "(backend.prewarm_aot; logged once per bucket, never raised)",
    )
)
SOLVER_ARENA_BYTES = REGISTRY.register(
    Gauge(
        "karpenter_solver_arena_bytes",
        "Device-resident arena bytes by residency class (args / ckpt / "
        "ladder / shard / run_host) and tenant namespace (tenant=default "
        "outside the mux) — the accounting the arena byte budget evicts "
        "against (solver/arena.py)",
        ("class", "tenant"),
    )
)
SOLVER_ARENA_EVICTIONS = REGISTRY.register(
    Counter(
        "karpenter_solver_arena_evictions_total",
        "Arena buckets evicted (LRU under the byte budget, plus max_buckets "
        "FIFO turnover); an evicted bucket costs exactly one cold packed "
        "re-upload, never a wrong answer",
    )
)
SOLVER_HBM_BYTES = REGISTRY.register(
    Gauge(
        "karpenter_solver_hbm_bytes",
        "Device memory watermarks from jax memory_stats() when the runtime "
        "reports them (kind=bytes_in_use / peak_bytes_in_use / bytes_limit); "
        "absent on runtimes without allocator stats",
        ("kind",),
    )
)
SOLVER_PERF_ANOMALIES = REGISTRY.register(
    Counter(
        "karpenter_solver_perf_anomalies_total",
        "Rolling-baseline anomaly trips per trace stage: sustained latency "
        "beyond the configured multiplier of the EWMA/quantile baseline "
        "(obs/anomaly.py; flips /healthz to WARN and dumps the flight "
        "recorder with reason perf_anomaly)",
        ("stage",),
    )
)
SOLVER_PERF_ANOMALY_STATE = REGISTRY.register(
    Gauge(
        "karpenter_solver_perf_anomaly_state",
        "1 while the stage's rolling-baseline detector is tripped, 0 after "
        "it recovers (obs/anomaly.py)",
        ("stage",),
    )
)

# --- durable solver resident state (solver/vault.py) -------------------------

SOLVER_VAULT_SNAPSHOT_SECONDS = REGISTRY.register(
    Histogram(
        "karpenter_solver_vault_snapshot_seconds",
        "Wall time of one vault snapshot (capture + pickle + fsync + "
        "atomic rename), measured on the vault's background writer — the "
        "solve path never blocks on it (SolverStateVault.snapshot_now)",
    )
)
SOLVER_VAULT_BYTES = REGISTRY.register(
    Gauge(
        "karpenter_solver_vault_bytes",
        "Size of the newest vault file on disk (header + checksummed "
        "payload); tracks how much resident state a restore re-seeds",
    )
)
SOLVER_VAULT_AGE = REGISTRY.register(
    Gauge(
        "karpenter_solver_vault_age_seconds",
        "Age of the newest successful vault snapshot (refreshed on write "
        "and on every /healthz scrape) — restart-to-first-solve is bounded "
        "by the journal tail accumulated over this window, so a growing "
        "age is a shrinking durability guarantee",
    )
)
SOLVER_VAULT_RESTORE_SECONDS = REGISTRY.register(
    Histogram(
        "karpenter_solver_vault_restore_seconds",
        "Wall time of one successful vault restore (candidate scan + "
        "checksum verify + donor install + streaming/arena composition)",
    )
)
SOLVER_VAULT_RESTORES = REGISTRY.register(
    Counter(
        "karpenter_solver_vault_restores_total",
        "Successful vault restores (boot-time hydration plus fence-time "
        "re-seeds in solver/fleet.py)",
    )
)
SOLVER_VAULT_RESTORE_FAILURES = REGISTRY.register(
    Counter(
        "karpenter_solver_vault_restore_failures_total",
        "Restore attempts where EVERY candidate file was rejected "
        "(truncated / checksum mismatch / wrong journal epoch / seq or "
        "store-rv cross-check) — the operator degraded to the cold "
        "re-encode path and dumped the flight recorder "
        "(reason=vault_restore_failed); an empty vault dir is a fresh "
        "boot, not a failure, and does not count",
    )
)

# -- convex (global-optimization) solver backend (solver/convex.py) ---------
SOLVER_CONVEX_SOLVES = REGISTRY.register(
    Counter(
        "karpenter_solver_convex_solves_total",
        "ADMM solves that produced an accepted result, by path "
        "(provision = full solve through the Solver seam, consolidate = "
        "consolidate_global whole-cluster proposal); declines that "
        "delegated verbatim to FFD count nothing",
        ("path",),
    )
)
SOLVER_CONVEX_FALLBACKS = REGISTRY.register(
    Counter(
        "karpenter_solver_convex_fallbacks_total",
        "Convex solves that fell back LOUDLY to the FFD inner solver "
        "after dispatch, by reason (nonconverged / invariant / min_values "
        "/ device / consolidate_nonconverged) — each also leaves a flight "
        "dump (reason=convex_fallback); a rising rate means the tolerance "
        "or iteration budget no longer fits the fleet shape",
        ("reason",),
    )
)
SOLVER_CONVEX_ITERATIONS = REGISTRY.register(
    Gauge(
        "karpenter_solver_convex_iterations",
        "ADMM iterations the most recent converged solve needed (scan "
        "convergence latch) — trending toward --convex-max-iters predicts "
        "imminent nonconverged fallbacks",
    )
)
