"""Cross-process leader-lease transport: flock'd JSON file.

The reference's standbys are separate PODS contending a kube Lease
(charts/karpenter/values.yaml `replicas: 2`, Makefile:56
DISABLE_LEADER_ELECTION); this framework's in-process store cannot span OS
processes, so the lease gets its own minimal transport: one JSON file whose
every read-modify-write happens under an exclusive POSIX flock on a sidecar
lock file. The backend implements exactly the store surface LeaderElector
touches (try_get / create / update_if raising Conflict) — resource_version
increments under the file lock, so two processes CASing the lease serialize
the same way two threads do on the in-process store, and kill -9 of the
holder releases nothing (the standby waits out lease_duration_s, exactly
like kube leases).

Timebase: renew_time in the file is the HOLDER's wall clock (time.time();
new_kwok_operator wires that when lease_path is set) — but no other process
ever compares against it. Expiry follows client-go semantics: each elector
records (resource_version, holder, renew_time) with ITS OWN clock when it
observes the record change, and seizes only after the record sits unchanged
for a full lease_duration_s on that local clock (leaderelection.py). Renewal
still writes renew_time so every CAS changes the record; cross-host clock
skew can neither expire a live lease (dual leaders) nor immortalize a dead
one.

Storage requirement: the lease path must live on a filesystem whose
advisory byte-range/flock locking is coherent ACROSS HOSTS — NFSv4+ (lock
leases in-protocol), or a local disk when both replicas share a node. NFSv3
(separate lockd), SMB/CIFS mounted with `nolock`/`nobrl`, and most FUSE
overlays grant flock locally without cross-host coherence, which would let
two CAS sections interleave. The deploy renderer's storageClassName
validation (deploy/render.py) carries the same note next to the RWX check.
"""

from __future__ import annotations

import fcntl
import json
import os
import tempfile
from contextlib import contextmanager
from typing import Optional

from ..api.objects import ObjectMeta
from . import store as st
from .leaderelection import LEADER_LEASE_NAME, Lease


class FileLeaseBackend:
    def __init__(self, path: str):
        self.path = path
        self.lock_path = path + ".lock"
        d = os.path.dirname(os.path.abspath(path)) or "."
        os.makedirs(d, exist_ok=True)

    @contextmanager
    def _locked(self):
        with open(self.lock_path, "a+") as lf:
            fcntl.flock(lf.fileno(), fcntl.LOCK_EX)
            try:
                yield
            finally:
                fcntl.flock(lf.fileno(), fcntl.LOCK_UN)

    def _read(self) -> Optional[Lease]:
        try:
            with open(self.path) as f:
                d = json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            # a torn file cannot happen (atomic rename); missing = no lease
            return None
        lease = Lease(
            meta=ObjectMeta(
                name=d.get("name", LEADER_LEASE_NAME),
                resource_version=int(d.get("rv", 0)),
                creation_timestamp=d.get("created", 0.0),
            ),
            holder=d.get("holder", ""),
            renew_time=float(d.get("renew_time", 0.0)),
            lease_duration_s=float(d.get("lease_duration_s", 15.0)),
        )
        return lease

    def _write(self, lease: Lease) -> None:
        d = os.path.dirname(os.path.abspath(self.path)) or "."
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".lease-")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(
                    {
                        "name": lease.meta.name,
                        "rv": lease.meta.resource_version,
                        "created": lease.meta.creation_timestamp,
                        "holder": lease.holder,
                        "renew_time": lease.renew_time,
                        "lease_duration_s": lease.lease_duration_s,
                    },
                    f,
                )
            os.replace(tmp, self.path)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)

    # -- the LeaderElector store surface ------------------------------------

    def try_get(self, kind: str, name: str):
        with self._locked():
            return self._read()

    def create(self, kind: str, obj: Lease):
        with self._locked():
            if self._read() is not None:
                raise st.Conflict(f"{kind} {obj.meta.name} already exists")
            obj.meta.resource_version = 1
            if obj.meta.creation_timestamp is None:
                obj.meta.creation_timestamp = obj.renew_time
            self._write(obj)
            return obj

    def update_if(self, kind: str, obj: Lease, expected_rv: int):
        with self._locked():
            cur = self._read()
            if cur is None:
                raise st.NotFound(f"{kind} {obj.meta.name}")
            if cur.meta.resource_version != expected_rv:
                raise st.Conflict(
                    f"{kind} {obj.meta.name}: rv {cur.meta.resource_version} != {expected_rv}"
                )
            obj.meta.resource_version = expected_rv + 1
            if obj.meta.creation_timestamp is None:
                obj.meta.creation_timestamp = cur.meta.creation_timestamp
            self._write(obj)
            return obj
