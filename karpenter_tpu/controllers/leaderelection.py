"""Leader election: lease-based single-active-controller HA.

The reference runs one active controller instance with standbys behind
kube's lease-based leader election (`DISABLE_LEADER_ELECTION`,
/root/reference/Makefile:56; settings.md:21). This framework's analog is a
Lease object contended through the shared store with the same semantics:

  - the holder renews every `renew_s`; a candidate acquires only when the
    lease is expired (holder crashed / wedged past `lease_s`);
  - acquisition goes through the store's optimistic concurrency
    (resource_version conflict = someone else won the race);
  - the Manager gates reconciliation on `elector.is_leader()` — standbys
    tick their elector but run no controllers until they take over.

A two-process deployment shares the lease through the snapshot/store layer;
in-process HA (the testable configuration here) contends two managers on
one store — the handoff test kills the leader and watches the standby take
over and continue the control loop.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Optional

from ..api.objects import CLOCK, ObjectMeta
from ..metrics.registry import LEADER
from . import store as st

LEASES = "leases"
LEADER_LEASE_NAME = "karpenter-tpu-leader"


@dataclass
class Lease:
    meta: ObjectMeta
    holder: str = ""
    # in-process leases run on the control-plane clock; snapshot restore
    # rebases this (CLOCK marker) so a restored lease's remaining duration
    # is preserved instead of skewing by the downtime delta. (File-backed
    # leases run on wall time and never pass through snapshots.)
    renew_time: float = field(default=0.0, metadata=CLOCK)
    lease_duration_s: float = 15.0


class LeaderElector:
    """Contends for the leader lease; call tick() regularly (the manager
    does). Defaults mirror kube leader election (15s lease / 10s renew /
    2s retry).

    `identity` MUST be unique per process (kube's hostname_uuid convention;
    the operator defaults to pid+uuid). Identity-match reclaims the lease
    without waiting for expiry — correct for a restarted holder, split-brain
    if two live processes ever share an identity."""

    def __init__(
        self,
        store: st.Store,
        identity: str,
        lease_s: float = 15.0,
        renew_s: float = 10.0,
        clock=time.monotonic,
    ):
        self.store = store
        self.identity = identity
        self.lease_s = lease_s
        self.renew_s = renew_s
        self.clock = clock
        self._leading = False
        # True when the CURRENT leadership was seized from ANOTHER holder
        # (expired or resigned lease) — a real failover. False on fresh
        # creation and identity-match reclaim. The manager's on_elected hook
        # (snapshot re-hydration) keys on this so an initial acquisition
        # never clear-restores over freshly injected objects.
        self.takeover = False
        # fencing token for shared-state writers (snapshot): the lease
        # resource version observed at our last successful acquire/renew.
        # Strictly increases across acquisitions, so a deposed leader's
        # stale token loses against the new leader's writes.
        self.fence_token = 0
        # Local observation of the remote record (client-go semantics): a
        # candidate judges expiry from ITS OWN clock at the moment it last
        # saw the record CHANGE — (rv, holder, renew_time). The holder's
        # renew_time may only SHORTEN the wait (restored leases carry their
        # remaining duration), floored at a renew interval of observed
        # silence — see tick(). Cross-host clock skew therefore cannot
        # manufacture an "expired" lease while the holder is alive and
        # renewing (dual-leader), nor keep a dead holder's lease alive.
        self._obs_key: Optional[tuple] = None
        self._obs_at: float = 0.0

    def is_leader(self) -> bool:
        return self._leading

    def _cas(self, lease: Lease, holder: str, renew_time: float) -> bool:
        """Compare-and-swap a FRESH lease object against the observed
        resource_version — two concurrent electors cannot both win; the
        loser sees Conflict (store.update_if, real optimistic concurrency)."""
        fresh = Lease(
            meta=ObjectMeta(name=LEADER_LEASE_NAME),
            holder=holder,
            renew_time=renew_time,
            lease_duration_s=self.lease_s,
        )
        try:
            self.store.update_if(LEASES, fresh, lease.meta.resource_version)
            self.fence_token = fresh.meta.resource_version
            return True
        except (st.Conflict, st.NotFound):
            return False

    def tick(self) -> bool:
        """Acquire/renew/observe; returns True when leadership CHANGED."""
        now = self.clock()
        lease: Optional[Lease] = self.store.try_get(LEASES, LEADER_LEASE_NAME)
        was = self._leading
        if lease is not None:
            key = (lease.meta.resource_version, lease.holder, lease.renew_time)
            if key != self._obs_key:
                self._obs_key = key
                self._obs_at = now
        if lease is None:
            try:
                created = self.store.create(
                    LEASES,
                    Lease(
                        meta=ObjectMeta(name=LEADER_LEASE_NAME),
                        holder=self.identity,
                        renew_time=now,
                        lease_duration_s=self.lease_s,
                    ),
                )
                self._leading = True
                self.takeover = False  # fresh lease: nobody to take from
                if created is not None:
                    self.fence_token = created.meta.resource_version
            except st.Conflict:
                self._leading = False  # lost the creation race
        elif lease.holder == self.identity:
            # Holder-identity match renews even when _leading is False — a
            # restarted leader with the same identity reclaims its own
            # unexpired lease immediately (kube renews on identity match; the
            # reclaim goes through CAS so two same-identity processes racing
            # still serialize on the resource version).
            if not self._leading or now - lease.renew_time >= self.renew_s / 2:
                # a failed renewal CAS means someone took the lease from us
                self._leading = self._cas(lease, self.identity, now)
            else:
                self._leading = True
            if self._leading and was != self._leading:
                self.takeover = False  # our own lease — reclaim, not failover
        else:
            # Expiry deadline on OUR clock: a full lease duration of observed
            # silence — or sooner, when the record's renew_time is meaningful
            # on this clock (restored snapshot rebases it; same-clock peers
            # share it) and implies less remaining. The renew_time shortcut
            # is floored at a full renew interval of observed silence, so a
            # live holder whose clock runs BEHIND ours always renews the
            # record (resetting the observation) before we can seize —
            # wall-clock skew still cannot manufacture an expired lease.
            deadline = min(
                self._obs_at + lease.lease_duration_s,
                max(lease.renew_time + lease.lease_duration_s,
                    self._obs_at + self.renew_s),
            )
            if lease.holder == "" or now > deadline:
                # Resigned (empty holder: immediately acquirable, kube treats
                # an unheld record as free) or expired. Seize from the
                # previous holder; CAS loser stays standby.
                self._leading = self._cas(lease, self.identity, now)
                if self._leading:
                    self.takeover = True
            else:
                self._leading = False
        LEADER.set(1.0 if self._leading else 0.0, identity=self.identity)
        return self._leading != was

    def resign(self) -> None:
        """Release the lease voluntarily (clean shutdown hands off fast)."""
        lease: Optional[Lease] = self.store.try_get(LEASES, LEADER_LEASE_NAME)
        if lease is not None and lease.holder == self.identity:
            # empty holder + expired: candidates take over at once, and this
            # process's identity no longer matches (it will not auto-reclaim)
            self._cas(lease, "", -self.lease_s)
        self._leading = False
        LEADER.set(0.0, identity=self.identity)
