"""Durability: periodic store + cloud snapshot with boot-time restore.

The reference's control plane is stateless (all durable state lives in the
kube API); its ONE explicit checkpoint is kwok's instance backup to
ConfigMaps every 5s with restore at boot (kwok/ec2/ec2.go:112-232). In this
framework the in-process store IS the API server, so durability covers both
halves: every store kind (the "API objects") plus the kwok cloud's instance
map (the "cloud side"), written atomically to one snapshot file on a 5s
cadence and restored before controllers run.

A process restart therefore rebuilds the exact cluster: instances without
NodeClaims are reaped by the GC controller after its grace period (no leaked
capacity), and NodeClaims without instances re-launch — the same
reconcile-from-state convergence the reference gets from re-listing the API.
"""

from __future__ import annotations

import os
import pickle
import tempfile
import time
from typing import Optional, Tuple

from . import store as st

SNAPSHOT_KINDS = (
    st.PODS,
    st.NODES,
    st.NODEPOOLS,
    st.NODECLAIMS,
    st.NODECLASSES,
    st.PDBS,
    st.DAEMONSETS,
    st.PERSISTENTVOLUMES,
    st.PERSISTENTVOLUMECLAIMS,
)


def save_snapshot(
    store: st.Store, cloud, path: str, now: Optional[float] = None,
    fence_token: Optional[int] = None,
) -> bool:
    """Atomic snapshot (tmp + rename): store kinds + cloud instances.

    Serialization happens WHILE both locks are held — the collected lists
    reference the live objects, and other threads mutate fields in place
    (deletion timestamps, PVC bindings), so pickling after release could
    tear the snapshot or crash mid-iteration. The dump goes to memory under
    the locks; only the file write happens outside. Lock order is cloud
    before store, matching KwokCloud.create_fleet (which holds its lock
    while fabricating Node objects through the store). `now` (the control-
    plane clock) is recorded so restore can rebase monotonic timestamps.

    Cost note: the dump serializes the whole store under the lock — at 5s
    cadence this is the kwok ConfigMap-backup trade-off, and the controller
    skips entirely when the rv high-water mark hasn't moved."""
    with cloud._lock, store._lock:
        objects = {kind: list(store._objects.get(kind, {}).values()) for kind in SNAPSHOT_KINDS}
        rv = store.current_rv()  # non-consuming high-water mark
        instances = dict(cloud._instances)
        seq = next(cloud._seq)  # observe; re-prime on restore
        payload = pickle.dumps(
            {
                "objects": objects,
                "instances": instances,
                "rv": rv,
                "seq": seq,
                "now": now if now is not None else time.monotonic(),
            }
        )
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".snap-")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(payload)
        if fence_token is None:
            os.replace(tmp, path)
            return True
        # Fenced write (HA shared state): a deposed leader's in-flight save
        # must not clobber the new leader's snapshots. The fence token is
        # the writer's lease resource version — strictly higher for every
        # later acquisition — compared and advanced under a flock, so
        # compare + rename are one atomic step (r5 review finding).
        import fcntl

        with open(path + ".fence", "a+") as ff:
            fcntl.flock(ff.fileno(), fcntl.LOCK_EX)
            try:
                ff.seek(0)
                raw = ff.read().strip()
                cur = int(raw) if raw else -1
                if cur > fence_token:
                    return False  # we were deposed; drop the stale snapshot
                os.replace(tmp, path)
                ff.seek(0)
                ff.truncate()
                ff.write(str(fence_token))
                ff.flush()
            finally:
                fcntl.flock(ff.fileno(), fcntl.LOCK_UN)
        return True
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def restore_snapshot(
    store: st.Store, cloud, path: str, now: Optional[float] = None,
    clear: bool = False,
) -> bool:
    """Hydrate an EMPTY store + cloud from a snapshot file; True on restore.
    `clear=True` replaces the snapshot kinds (and the instance map)
    wholesale instead of merging by key — the HA-takeover mode, where the
    restoring standby may hold a stale boot-time restore whose deletions
    must not linger.

    Persisted timestamps are CLOCK_MONOTONIC values from the dead process —
    meaningless on a rebooted machine. Every known timestamp field is rebased
    by (now - snapshot_now) so AGES are preserved: GC grace, expiry, and
    disruption lifetime math keep working after restore."""
    if not os.path.exists(path):
        return False
    with open(path, "rb") as f:
        payload = pickle.load(f)
    snap_now = payload.get("now")
    # payloads without a clock reference (older format) must NOT be rebased:
    # defaulting the epoch to 0 would shift every timestamp by the restoring
    # host's entire uptime and freeze GC/expiry/lifetime math
    delta = ((now if now is not None else time.monotonic()) - snap_now) if snap_now is not None else 0.0

    def rebase(obj) -> None:
        m = getattr(obj, "meta", None)
        if m is not None:
            if m.creation_timestamp is not None:
                m.creation_timestamp += delta
            if m.deletion_timestamp:
                m.deletion_timestamp += delta
        for f in ("last_transition", "launched_at", "registered_at"):
            v = getattr(obj, f, None)
            if isinstance(v, (int, float)) and v:
                setattr(obj, f, v + delta)

    with store._lock:
        for kind, objs in payload["objects"].items():
            if clear:
                store._objects[kind] = {}
            for obj in objs:
                rebase(obj)
                store._objects[kind][store._key(obj)] = obj
        store.bump_to(payload.get("rv", 0))
    with cloud._lock:
        for inst in payload["instances"].values():
            inst.launch_time += delta
        if clear:
            cloud._instances = {}
        cloud._instances.update(payload["instances"])
        import itertools

        cloud._seq = itertools.count(payload.get("seq", 1))
    return True


class SnapshotController:
    """Writes the snapshot every `interval_s` of controller-loop time — the
    5s ConfigMap-backup cadence of the reference's kwok provider."""

    name = "snapshot"

    def __init__(self, store: st.Store, cloud, path: str, interval_s: float = 5.0,
                 clock=time.monotonic, fence=None):
        self.store = store
        self.cloud = cloud
        self.path = path
        self.interval_s = interval_s
        self.clock = clock
        self.fence = fence  # callable -> current lease fence token (HA)
        self._last: Optional[float] = None
        self._last_rv: int = -1

    def reconcile(self) -> bool:
        now = self.clock()
        if self._last is not None and now - self._last < self.interval_s:
            return False
        # skip when nothing changed: the rv high-water mark is a
        # non-consuming peek, so an idle cluster pays nothing
        rv = self.store.current_rv()
        if rv == self._last_rv:
            self._last = now
            return False
        save_snapshot(
            self.store, self.cloud, self.path, now=now,
            fence_token=self.fence() if self.fence is not None else None,
        )
        self._last = now
        self._last_rv = rv
        return False  # snapshots are not cluster progress
