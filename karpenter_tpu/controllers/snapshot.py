"""Durability: periodic store + cloud snapshot with boot-time restore.

The reference's control plane is stateless (all durable state lives in the
kube API); its ONE explicit checkpoint is kwok's instance backup to
ConfigMaps every 5s with restore at boot (kwok/ec2/ec2.go:112-232). In this
framework the in-process store IS the API server, so durability covers both
halves: every store kind (the "API objects") plus the kwok cloud's instance
map (the "cloud side"), written atomically to one snapshot file on a 5s
cadence and restored before controllers run.

A process restart therefore rebuilds the exact cluster: instances without
NodeClaims are reaped by the GC controller after its grace period (no leaked
capacity), and NodeClaims without instances re-launch — the same
reconcile-from-state convergence the reference gets from re-listing the API.
"""

from __future__ import annotations

import dataclasses
import hashlib
import logging
import os
import pickle
import tempfile
import time
from typing import Optional, Tuple

from . import store as st

log = logging.getLogger("karpenter_tpu")

SNAPSHOT_KINDS = (
    st.PODS,
    st.NODES,
    st.NODEPOOLS,
    st.NODECLAIMS,
    st.NODECLASSES,
    st.PDBS,
    st.DAEMONSETS,
    st.PERSISTENTVOLUMES,
    st.PERSISTENTVOLUMECLAIMS,
    # in-process leader lease: restored so a same-identity restart reclaims
    # instantly while a NEW process waits out the (rebased) remaining
    # duration — crash-restore cannot fast-track leadership
    "leases",
)

# payload format version. v2 rebases timestamps discovered via the CLOCK
# field-metadata marker (api/objects.py) instead of a hardcoded name list —
# new timestamp fields declared with the marker rebase automatically.
SNAPSHOT_VERSION = 2

# On-disk framing: magic + blake2b-16(payload) + payload. A torn or
# bit-rotted snapshot is DETECTED at restore and skipped (boot proceeds
# empty) instead of raising an UnpicklingError out of boot. Legacy files
# (bare pickle, first byte \x80) restore unframed — the magic cannot
# collide with a pickle protocol-2+ opcode stream.
SNAP_MAGIC = b"KSNAPC1\n"
_SNAP_DIGEST_SIZE = 16
_SNAP_HDR = len(SNAP_MAGIC) + _SNAP_DIGEST_SIZE

_CLOCK_FIELDS_CACHE: dict = {}


def _clock_fields(obj) -> Tuple[str, ...]:
    """Names of obj's control-plane-timestamp fields (CLOCK metadata),
    cached per type."""
    tp = type(obj)
    hit = _CLOCK_FIELDS_CACHE.get(tp)
    if hit is None:
        try:
            flds = dataclasses.fields(obj)
        except TypeError:
            flds = ()
        hit = tuple(f.name for f in flds if f.metadata.get("clock"))
        _CLOCK_FIELDS_CACHE[tp] = hit
    return hit


def save_snapshot(
    store: st.Store, cloud, path: str, now: Optional[float] = None,
    fence_token: Optional[int] = None,
    blob_cache: Optional[dict] = None,
) -> bool:
    """Atomic snapshot (tmp + rename): store kinds + cloud instances.

    Serialization happens WHILE both locks are held — the collected lists
    reference the live objects, and other threads mutate fields in place
    (deletion timestamps, PVC bindings), so pickling after release could
    tear the snapshot or crash mid-iteration. The dump goes to memory under
    the locks; only the file write happens outside. Lock order is cloud
    before store, matching KwokCloud.create_fleet (which holds its lock
    while fabricating Node objects through the store). `now` (the control-
    plane clock) is recorded so restore can rebase monotonic timestamps.

    Stall bound (VERDICT r4 weak #3 — measured 270 ms full-pickle at 10k
    nodes): with `blob_cache` (the SnapshotController passes a persistent
    dict), store objects serialize INCREMENTALLY — each object's pickle is
    cached by its resource_version, so an unchanged object costs a dict hit
    and the under-lock work scales with the CHANGE RATE, not cluster size.
    rv is a sound dirty marker at this granularity: every store write path
    bumps it via update()/create(), and an in-place mutation not yet
    update()d is exactly the state a snapshot should not capture anyway."""
    seen = set()

    def _obj_blobs(kind, objs):
        if blob_cache is None:
            return [pickle.dumps(o) for o in objs]
        out = []
        for o in objs:
            key = (kind, o.meta.namespace, o.meta.name)
            seen.add(key)
            rv_o = o.meta.resource_version
            hit = blob_cache.get(key)
            if hit is not None and hit[0] == rv_o:
                out.append(hit[1])
            else:
                b = pickle.dumps(o)
                blob_cache[key] = (rv_o, b)
                out.append(b)
        return out

    def _inst_blobs(insts):
        # instances have no resource_version; cache their pickles against a
        # cheap fingerprint of every mutable field (state transitions,
        # binding, tagging) — building the tuple is ~100x cheaper than
        # re-pickling an unchanged instance
        if blob_cache is None:
            return [pickle.dumps(i) for i in insts]
        out = []
        for i in insts:
            fp = (i.state, i.node_name, i.reservation_id,
                  i.launch_time, tuple(sorted(i.tags.items())))
            key = ("__instance__", i.id)
            seen.add(key)
            hit = blob_cache.get(key)
            if hit is not None and hit[0] == fp:
                out.append(hit[1])
            else:
                b = pickle.dumps(i)
                blob_cache[key] = (fp, b)
                out.append(b)
        return out

    with cloud._lock, store._lock:
        objects = {
            kind: _obj_blobs(kind, store._objects.get(kind, {}).values())
            for kind in SNAPSHOT_KINDS
        }
        rv = store.current_rv()  # non-consuming high-water mark
        instances = _inst_blobs(cloud._instances.values())
        seq = next(cloud._seq)  # observe; re-prime on restore
        if blob_cache is not None:
            # deleted objects' blobs must not accumulate forever
            for key in [k for k in blob_cache if k not in seen]:
                del blob_cache[key]
        payload = pickle.dumps(
            {
                "version": SNAPSHOT_VERSION,
                "objects": objects,
                "instances": instances,
                "rv": rv,
                "seq": seq,
                "now": now if now is not None else time.monotonic(),
            }
        )
    d = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".snap-")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(SNAP_MAGIC)
            f.write(hashlib.blake2b(
                payload, digest_size=_SNAP_DIGEST_SIZE).digest())
            f.write(payload)
            f.flush()
            # fsync BEFORE the rename: without it a crash can leave the
            # rename durable while the data is not — a torn/empty file
            # surviving as the newest snapshot
            os.fsync(f.fileno())
        if fence_token is None:
            os.replace(tmp, path)
            return True
        # Fenced write (HA shared state): a deposed leader's in-flight save
        # must not clobber the new leader's snapshots. The fence token is
        # the writer's lease resource version — strictly higher for every
        # later acquisition — compared and advanced under a flock, so
        # compare + rename are one atomic step (r5 review finding).
        import fcntl

        with open(path + ".fence", "a+") as ff:
            fcntl.flock(ff.fileno(), fcntl.LOCK_EX)
            try:
                ff.seek(0)
                raw = ff.read().strip()
                cur = int(raw) if raw else -1
                if cur > fence_token:
                    return False  # we were deposed; drop the stale snapshot
                os.replace(tmp, path)
                ff.seek(0)
                ff.truncate()
                ff.write(str(fence_token))
                ff.flush()
            finally:
                fcntl.flock(ff.fileno(), fcntl.LOCK_UN)
        return True
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def restore_snapshot(
    store: st.Store, cloud, path: str, now: Optional[float] = None,
    clear: bool = False,
) -> bool:
    """Hydrate an EMPTY store + cloud from a snapshot file; True on restore.
    `clear=True` replaces the snapshot kinds (and the instance map)
    wholesale instead of merging by key — the HA-takeover mode, where the
    restoring standby may hold a stale boot-time restore whose deletions
    must not linger.

    Persisted timestamps are CLOCK_MONOTONIC values from the dead process —
    meaningless on a rebooted machine. Every known timestamp field is rebased
    by (now - snapshot_now) so AGES are preserved: GC grace, expiry, and
    disruption lifetime math keep working after restore."""
    if not os.path.exists(path):
        return False
    try:
        with open(path, "rb") as f:
            raw = f.read()
        if raw.startswith(SNAP_MAGIC):
            if len(raw) < _SNAP_HDR:
                raise ValueError("truncated snapshot header")
            digest = raw[len(SNAP_MAGIC):_SNAP_HDR]
            blob = raw[_SNAP_HDR:]
            if hashlib.blake2b(
                    blob, digest_size=_SNAP_DIGEST_SIZE).digest() != digest:
                raise ValueError("snapshot checksum mismatch")
            payload = pickle.loads(blob)
        else:
            payload = pickle.loads(raw)  # legacy unframed snapshot
        if not isinstance(payload, dict):
            raise ValueError("snapshot payload is not a dict")
    except Exception as e:  # noqa: BLE001 — a bad snapshot must not
        # crash boot: the process starts empty and reconverges, which is
        # strictly better than refusing to start at all
        log.warning(
            "snapshot restore skipped %s (%s: %s) — booting empty",
            path, type(e).__name__, e,
        )
        return False
    snap_now = payload.get("now")
    # payloads without a clock reference (older format) must NOT be rebased:
    # defaulting the epoch to 0 would shift every timestamp by the restoring
    # host's entire uptime and freeze GC/expiry/lifetime math
    delta = ((now if now is not None else time.monotonic()) - snap_now) if snap_now is not None else 0.0

    def rebase(obj) -> None:
        # pickle reconstructs instances of the CURRENT classes, so the CLOCK
        # introspection applies uniformly to any payload version — fields
        # absent from an old payload simply don't exist on the object
        m = getattr(obj, "meta", None)
        for target in (m, obj):
            if target is None:
                continue
            for name in _clock_fields(target):
                v = getattr(target, name, None)
                # 0.0 is a real instant (sim clocks start at 0) — only None
                # means "never set" (r5 review: `and v` skipped t=0 stamps)
                if isinstance(v, (int, float)):
                    setattr(target, name, v + delta)
                elif isinstance(v, dict):
                    # dict-valued clock stamps (Node.condition_since):
                    # every value shifts (r5 review finding)
                    for k, t in v.items():
                        if isinstance(t, (int, float)):
                            v[k] = t + delta

    with store._lock:
        for kind, objs in payload["objects"].items():
            if clear:
                store._objects[kind] = {}
            for obj in objs:
                if isinstance(obj, bytes):  # v2 incremental format
                    obj = pickle.loads(obj)
                rebase(obj)
                store._objects[kind][store._key(obj)] = obj
        store.bump_to(payload.get("rv", 0))
    with cloud._lock:
        raw = payload["instances"]
        insts = (
            list(raw.values())
            if isinstance(raw, dict)  # pre-v2 payloads stored objects
            else [pickle.loads(b) if isinstance(b, bytes) else b for b in raw]
        )
        for inst in insts:
            inst.launch_time += delta
        if clear:
            cloud._instances = {}
        cloud._instances.update({i.id: i for i in insts})
        import itertools

        cloud._seq = itertools.count(payload.get("seq", 1))
    return True


class SnapshotController:
    """Writes the snapshot every `interval_s` of controller-loop time — the
    5s ConfigMap-backup cadence of the reference's kwok provider."""

    name = "snapshot"

    def __init__(self, store: st.Store, cloud, path: str, interval_s: float = 5.0,
                 clock=time.monotonic, fence=None):
        self.store = store
        self.cloud = cloud
        self.path = path
        self.interval_s = interval_s
        self.clock = clock
        self.fence = fence  # callable -> current lease fence token (HA)
        self._last: Optional[float] = None
        self._last_rv: int = -1
        # per-object pickle cache keyed by resource_version: steady-state
        # snapshot cost scales with the change rate, not cluster size
        self._blobs: dict = {}

    def reconcile(self) -> bool:
        now = self.clock()
        if self._last is not None and now - self._last < self.interval_s:
            return False
        # skip when nothing changed: the rv high-water mark is a
        # non-consuming peek, so an idle cluster pays nothing
        rv = self.store.current_rv()
        if rv == self._last_rv:
            self._last = now
            return False
        save_snapshot(
            self.store, self.cloud, self.path, now=now,
            fence_token=self.fence() if self.fence is not None else None,
            blob_cache=self._blobs,
        )
        self._last = now
        self._last_rv = rv
        return False  # snapshots are not cluster progress
