"""Binder: the kube-scheduler stand-in for the hermetic loop.

The reference relies on the real kube-scheduler to bind pods once capacity
registers (kwok replaces kubelet; nothing replaces kube-scheduler because a
real cluster runs one). In this fully hermetic framework the binder closes
that gap — and rather than duplicating admission logic, it reuses the
scheduler itself in existing-nodes-only mode (no nodepools), so binding
honors the exact same requirements/taints/resources/topology/affinity
semantics the solver planned with.
"""

from __future__ import annotations

from ..api import wellknown as wk
from ..controllers import store as st
from ..provisioning.scheduler import SolverInput, solve
from ..state.cluster import Cluster


class Binder:
    name = "binder"

    def __init__(self, store: st.Store, cluster: Cluster):
        self.store = store
        self.cluster = cluster

    def reconcile(self) -> bool:
        pending = self.cluster.pending_pods()
        if not pending:
            return False
        nodes = [
            n
            for n in self.cluster.existing_nodes_for_scheduler()
            # bind only to truly ready nodes (existing_nodes_for_scheduler
            # also yields in-flight claims for the provisioner's benefit)
            if (lambda node: node is not None and node.ready)(self.store.try_get(st.NODES, n.id))
        ]
        if not nodes:
            return False
        result = solve(
            SolverInput(pods=pending, nodes=nodes, nodepools=[], zones=self._zones(nodes))
        )
        did = False
        for uid, placement in result.placements.items():
            if placement[0] != "node":
                continue
            pod = next((p for p in pending if p.meta.uid == uid), None)
            if pod is None:
                continue
            pod.node_name = placement[1]
            pod.phase = "Running"
            self.store.update(st.PODS, pod)
            did = True
        return did

    @staticmethod
    def _zones(nodes) -> tuple:
        return tuple(sorted({n.labels.get(wk.ZONE_LABEL) for n in nodes if n.labels.get(wk.ZONE_LABEL)}))
