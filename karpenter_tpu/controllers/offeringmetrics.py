"""Per-offering gauges (controllers/metrics/metrics.go:30-58): availability
and price-estimate series per (instance type, zone, capacity type), refilled
periodically so dashboards see the live ICE/pricing state."""

from __future__ import annotations

import time

from ..metrics.registry import OFFERING_AVAILABLE, OFFERING_PRICE


class OfferingMetricsController:
    name = "metrics.offerings"

    def __init__(self, cloud_provider, interval_s: float = 60.0, clock=time.monotonic):
        self.cloud_provider = cloud_provider
        self.interval_s = interval_s
        self.clock = clock
        self._last = None

    def reconcile(self) -> bool:
        now = self.clock()
        if self._last is not None and now - self._last < self.interval_s:
            return False
        self._last = now
        for it in self.cloud_provider.get_instance_types(""):
            for o in it.offerings:
                labels = dict(
                    instance_type=it.name, zone=o.zone, capacity_type=o.capacity_type
                )
                OFFERING_AVAILABLE.set(1.0 if o.available else 0.0, **labels)
                OFFERING_PRICE.set(o.price, **labels)
        return False  # metrics are not cluster progress
