"""Reserved -> on-demand capacity-type flips.

Mirror of pkg/controllers/nodeclaim/capacityreservation (controller.go:45-107,
SURVEY.md §2.4): when a node's backing capacity reservation expires, the
instance keeps running but is now billed on-demand — the claim and node flip
their karpenter.sh/capacity-type from `reserved` to `on-demand` (and pricing
updates) so consolidation sees the true cost.
"""

from __future__ import annotations

import time

from ..api import wellknown as wk
from ..controllers import store as st
from ..kwok.cloud import KwokCloud
from ..providers.capacityreservation import CapacityReservationProvider


class CapacityReservationFlipController:
    name = "nodeclaim.capacityreservation"

    def __init__(
        self,
        store: st.Store,
        cloud: KwokCloud,
        reservations: CapacityReservationProvider,
        clock=time.monotonic,
    ):
        self.store = store
        self.cloud = cloud
        self.reservations = reservations
        self.clock = clock

    def reconcile(self) -> bool:
        did = False
        active = {r.id for r in self.reservations.list()}
        for claim in self.store.list(st.NODECLAIMS):
            if claim.capacity_type != wk.CAPACITY_TYPE_RESERVED or claim.meta.deleting:
                continue
            iid = claim.provider_id.rsplit("/", 1)[-1] if claim.provider_id else ""
            insts = self.cloud.describe_instances([iid]) if iid else []
            if not insts:
                continue
            inst = insts[0]
            if inst.reservation_id and inst.reservation_id in active:
                continue
            # reservation gone: flip to on-demand at the od price
            claim.capacity_type = wk.CAPACITY_TYPE_ON_DEMAND
            it = self.cloud.types.get(claim.instance_type)
            if it is not None:
                for o in it.offerings:
                    if o.zone == claim.zone and o.capacity_type == wk.CAPACITY_TYPE_ON_DEMAND:
                        claim.price = o.price
                        break
            self.store.update(st.NODECLAIMS, claim)
            if claim.node_name:
                node = self.store.try_get(st.NODES, claim.node_name)
                if node is not None:
                    node.meta.labels[wk.CAPACITY_TYPE_LABEL] = wk.CAPACITY_TYPE_ON_DEMAND
                    self.store.update(st.NODES, node)
            did = True
        return did
