"""Garbage collection: cloud instances with no NodeClaim.

Mirrors pkg/controllers/nodeclaim/garbagecollection (controller.go:55-91):
instances older than a grace window (30s, :82) whose NodeClaim vanished are
terminated, catching leaked capacity from crashes between launch and
NodeClaim persistence.
"""

from __future__ import annotations

import time

from ..controllers import store as st
from ..kwok.cloud import KwokCloud


class GarbageCollectionController:
    name = "nodeclaim.garbagecollection"

    def __init__(self, store: st.Store, cloud: KwokCloud, grace_s: float = 30.0, clock=time.monotonic):
        self.store = store
        self.cloud = cloud
        self.grace_s = grace_s
        self.clock = clock

    def reconcile(self) -> bool:
        claim_ids = set()
        for c in self.store.list(st.NODECLAIMS):
            if c.provider_id:
                claim_ids.add(c.provider_id.rsplit("/", 1)[-1])
        orphans = []
        for inst in self.cloud.describe_instances():
            if inst.id in claim_ids:
                continue
            if self.clock() - inst.launch_time < self.grace_s:
                continue
            orphans.append(inst.id)
        if orphans:
            self.cloud.terminate_instances(orphans)
            return True
        return False
