"""Garbage collection: cloud instances with no NodeClaim.

Mirrors pkg/controllers/nodeclaim/garbagecollection (controller.go:55-91):
instances older than a grace window (30s, :82) whose NodeClaim vanished are
terminated, catching leaked capacity from crashes between launch and
NodeClaim persistence.
"""

from __future__ import annotations

import time

from ..controllers import store as st
from ..kwok.cloud import KwokCloud


class GarbageCollectionController:
    name = "nodeclaim.garbagecollection"

    def __init__(self, store: st.Store, cloud: KwokCloud, grace_s: float = 30.0, clock=time.monotonic):
        self.store = store
        self.cloud = cloud
        self.grace_s = grace_s
        self.clock = clock

    def reconcile(self) -> bool:
        # Snapshot claims BEFORE DescribeInstances: for the claim-deletion
        # direction, staleness then only means the live set GROWS after the
        # claim list (an instance created concurrently is still visible),
        # which can only make us keep a claim — never kill a healthy one.
        # The opposite order had a window where an instance created between
        # describe and the claim scan got its claim deleted (ADVICE r4).
        claims = list(self.store.list(st.NODECLAIMS))
        instances = self.cloud.describe_instances()
        live = {i.id for i in instances}
        now = self.clock()
        claim_ids = set()
        did = False
        for c in claims:
            if not c.provider_id:
                continue
            iid = c.provider_id.rsplit("/", 1)[-1]
            claim_ids.add(iid)
            # the OTHER reconcile direction: a launched claim whose instance
            # vanished (terminated out from under us — spot reclaim, manual
            # kill) must be deleted, or it lingers as phantom in-flight
            # capacity the provisioner packs pending pods onto forever. The
            # reference's lifecycle gets this from CloudProvider.Get
            # returning NodeClaimNotFoundError; termination handles the
            # finalizer drain (the node object is already gone). Guarded by
            # the same creation grace the reference puts on GC
            # (garbagecollection/controller.go:57-60): a claim younger than
            # grace_s may have an instance still materializing on the cloud
            # side — never reap it on a single missing describe.
            if (
                iid not in live
                and not c.meta.deleting
                and now - c.meta.creation_timestamp >= self.grace_s
            ):
                try:
                    self.store.delete(st.NODECLAIMS, c.name)
                except st.NotFound:
                    pass
                did = True
        orphans = []
        for inst in instances:
            if inst.id in claim_ids:
                continue
            if now - inst.launch_time < self.grace_s:
                continue
            orphans.append(inst.id)
        if orphans:
            self.cloud.terminate_instances(orphans)
            return True
        return did
