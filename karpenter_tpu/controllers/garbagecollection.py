"""Garbage collection: cloud instances with no NodeClaim.

Mirrors pkg/controllers/nodeclaim/garbagecollection (controller.go:55-91):
instances older than a grace window (30s, :82) whose NodeClaim vanished are
terminated, catching leaked capacity from crashes between launch and
NodeClaim persistence.
"""

from __future__ import annotations

import time

from ..controllers import store as st
from ..kwok.cloud import KwokCloud


class GarbageCollectionController:
    name = "nodeclaim.garbagecollection"

    def __init__(self, store: st.Store, cloud: KwokCloud, grace_s: float = 30.0, clock=time.monotonic):
        self.store = store
        self.cloud = cloud
        self.grace_s = grace_s
        self.clock = clock

    def reconcile(self) -> bool:
        # ONE DescribeInstances per tick: both directions derive from the
        # same snapshot (consistent view; half the non-mutating rate-limit
        # pressure of two calls)
        instances = self.cloud.describe_instances()
        live = {i.id for i in instances}
        claim_ids = set()
        did = False
        for c in self.store.list(st.NODECLAIMS):
            if not c.provider_id:
                continue
            iid = c.provider_id.rsplit("/", 1)[-1]
            claim_ids.add(iid)
            # the OTHER reconcile direction: a launched claim whose instance
            # vanished (terminated out from under us — spot reclaim, manual
            # kill) must be deleted, or it lingers as phantom in-flight
            # capacity the provisioner packs pending pods onto forever. The
            # reference's lifecycle gets this from CloudProvider.Get
            # returning NodeClaimNotFoundError; termination handles the
            # finalizer drain (the node object is already gone).
            if iid not in live and not c.meta.deleting:
                try:
                    self.store.delete(st.NODECLAIMS, c.name)
                except st.NotFound:
                    pass
                did = True
        orphans = []
        for inst in instances:
            if inst.id in claim_ids:
                continue
            if self.clock() - inst.launch_time < self.grace_s:
                continue
            orphans.append(inst.id)
        if orphans:
            self.cloud.terminate_instances(orphans)
            return True
        return did
