"""Post-registration instance tagging (tagging/controller.go:54-131).

Launch-time tags only carry what the launch path knows; once the NodeClaim
registers, the instance gains the Name + claim identity tags the reference
applies (Name, karpenter.sh/nodeclaim) so cloud-side inventories line up
with cluster objects. Applied once per claim (annotation-marked, like the
reference's tagged-annotation)."""

from __future__ import annotations

from ..api import wellknown as wk
from ..kwok.ratelimit import ThrottleError
from . import store as st

TAGGED_ANNOTATION = "karpenter.tpu/tagged"


class TaggingController:
    name = "nodeclaim.tagging"

    def __init__(self, store: st.Store, cloud):
        self.store = store
        self.cloud = cloud

    def reconcile(self) -> bool:
        did = False
        for claim in self.store.list(st.NODECLAIMS):
            if not claim.registered or not claim.provider_id:
                continue
            if claim.meta.annotations.get(TAGGED_ANNOTATION) == "true":
                continue
            instance_id = claim.provider_id.rsplit("/", 1)[-1]
            try:
                self.cloud.create_tags(
                    instance_id,
                    {
                        "Name": claim.node_name or claim.name,
                        "karpenter.sh/nodeclaim": claim.name,
                        wk.NODEPOOL_LABEL: claim.nodepool,
                    },
                )
            except ThrottleError:
                continue  # throttled: retry next loop (instance-gone is a
                # silent no-op in the cloud; anything else is a programming
                # error that must surface, not be retried forever)
            claim.meta.annotations[TAGGED_ANNOTATION] = "true"
            self.store.update(st.NODECLAIMS, claim)
            did = True
        return did
