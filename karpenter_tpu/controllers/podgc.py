"""Pod GC: re-pose pods bound to vanished nodes.

The reference leans on kube-controller-manager's podgc: when a Node object
disappears abruptly (kwok's node-killer purges Nodes whose instance
vanished, ec2.go:219-262), podgc deletes the orphaned pods and their
workload controllers recreate them as Pending — which is what re-triggers
provisioning. This framework's store IS the API server and pods stand in
for their workloads, so the analog re-poses the pod itself: node_name
cleared, phase back to Pending. Without this, a pod bound to a killed
node is stuck forever (graceful drain re-poses only pods on nodes that go
through termination).
"""

from __future__ import annotations

from . import store as st


class PodGCController:
    name = "podgc"

    def __init__(self, store: st.Store):
        self.store = store

    def reconcile(self) -> bool:
        node_names = {n.meta.name for n in self.store.list(st.NODES)}
        did = False
        for pod in self.store.list(st.PODS):
            if pod.meta.deleting or not pod.node_name:
                continue
            if pod.node_name in node_names:
                continue
            st.repose_pod(self.store, pod)
            did = True
        return did
