"""PV zonal topology controller (website/.../concepts/scheduling.md:430+).

Two reconciliations:

1. **Resolve**: a pending pod referencing PVCs bound to zonal PVs gets its
   `volume_zones` restriction set (intersection across its bound volumes) —
   the scheduler and the solver encoders read it through
   `Pod.scheduling_requirements()`. Pods are REPLACED on update (store
   convention: scheduling fields never mutate in place), which also keeps
   the solver's identity-keyed caches sound.

2. **Late binding** (WaitForFirstConsumer): when a pod with an UNBOUND claim
   lands on a node, a zonal PV is provisioned in the node's zone and the
   claim binds to it — so a later reschedule of the pod stays zone-pinned,
   exactly the trap the reference documents for zonal storage.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

from ..api import wellknown as wk
from ..api.objects import ObjectMeta, PersistentVolume
from . import store as st


class VolumeTopologyController:
    name = "volume-topology"

    def __init__(self, store: st.Store):
        self.store = store
        self._pv_seq = 0

    def reconcile(self) -> bool:
        did = False
        # claims are namespaced like pods (a pod's volume_claims name PVCs in
        # ITS namespace); PVs are cluster-scoped
        pvcs = {
            (c.meta.namespace, c.meta.name): c
            for c in self.store.list(st.PERSISTENTVOLUMECLAIMS)
        }
        pvs = {v.meta.name: v for v in self.store.list(st.PERSISTENTVOLUMES)}
        for pod in self.store.list(st.PODS):
            if not pod.volume_claims:
                continue
            if pod.node_name is not None and self._late_bind(pod, pvcs):
                did = True
                pvcs = {
                    (c.meta.namespace, c.meta.name): c
                    for c in self.store.list(st.PERSISTENTVOLUMECLAIMS)
                }
                pvs = {v.meta.name: v for v in self.store.list(st.PERSISTENTVOLUMES)}
            # resolve for bound pods too: a reschedule must stay zone-pinned
            if self._resolve(pod, pvcs, pvs):
                did = True
        return did

    def _zones_for(self, pod, pvcs, pvs) -> Optional[Tuple[str, ...]]:
        """Intersection of the pod's bound zonal PVs' zones; None when no
        bound zonal volume restricts it."""
        restriction: Optional[set] = None
        for claim_name in pod.volume_claims:
            pvc = pvcs.get((pod.meta.namespace, claim_name))
            if pvc is None or pvc.volume_name is None:
                continue  # unbound: WaitForFirstConsumer, no restriction yet
            pv = pvs.get(pvc.volume_name)
            if pv is None or not pv.zones:
                continue  # non-zonal volume
            zs = set(pv.zones)
            restriction = zs if restriction is None else (restriction & zs)
        if restriction is None:
            return None
        return tuple(sorted(restriction))

    def _resolve(self, pod, pvcs, pvs) -> bool:
        zones = self._zones_for(pod, pvcs, pvs)
        if zones == pod.volume_zones:
            return False
        updated = dataclasses.replace(pod, volume_zones=zones)
        self.store.update(st.PODS, updated)
        return True

    def _late_bind(self, pod, pvcs) -> bool:
        node = self.store.try_get(st.NODES, pod.node_name)
        if node is None:
            return False
        zone = node.meta.labels.get(wk.ZONE_LABEL)
        if zone is None:
            return False
        did = False
        for claim_name in pod.volume_claims:
            pvc = pvcs.get((pod.meta.namespace, claim_name))
            if pvc is None or pvc.volume_name is not None:
                continue
            # seq restarts at 0 after a snapshot restore while restored PVs
            # keep their names — skip past collisions instead of Conflict-ing
            while True:
                self._pv_seq += 1
                name = f"pv-{claim_name}-{self._pv_seq:04d}"
                if self.store.try_get(st.PERSISTENTVOLUMES, name) is None:
                    break
            pv = PersistentVolume(
                meta=ObjectMeta(name=name),
                zones=[zone],
                storage_class=pvc.storage_class,
            )
            self.store.create(st.PERSISTENTVOLUMES, pv)
            pvc.volume_name = pv.meta.name
            self.store.update(st.PERSISTENTVOLUMECLAIMS, pvc)
            did = True
        return did
