"""Interruption handling: queue events -> drain ahead of reclaim.

Behavioral mirror of pkg/controllers/interruption (SURVEY.md §2.4, §3.4):
an in-memory queue stands in for SQS (10-message receive batches, visibility
semantics — pkg/providers/sqs/sqs.go:57-77); the controller parses four
message kinds + noop (messages/{spotinterruption, rebalancerecommendation,
scheduledchange, statechange, noop}), resolves the NodeClaim by instance id
(the reference's status.instanceID field index, operator.go:284-305), marks
the interrupted offering unavailable for spot interruptions (ICE cache,
controller.go:219-225), and cordon-and-drains by deleting the NodeClaim
(-> termination flow §3.3; replacement via provisioning §3.1).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..api import wellknown as wk
from ..controllers import store as st
from ..metrics.registry import NODECLAIMS_TERMINATED
from ..providers.unavailable import UnavailableOfferings

# message kinds (messages/* in the reference)
SPOT_INTERRUPTION = "spot_interruption"  # 2-minute reclaim warning
REBALANCE_RECOMMENDATION = "rebalance_recommendation"
SCHEDULED_CHANGE = "scheduled_change"  # host maintenance
STATE_CHANGE = "state_change"  # stopping/terminating outside karpenter
NOOP = "noop"

KINDS = (SPOT_INTERRUPTION, REBALANCE_RECOMMENDATION, SCHEDULED_CHANGE, STATE_CHANGE, NOOP)


@dataclass
class Message:
    kind: str
    instance_id: str = ""
    state: str = ""  # for state_change: stopping | terminating | ...
    received_at: float = field(default_factory=time.monotonic)


class InterruptionQueue:
    """In-memory SQS stand-in: send / receive(max 10) / delete."""

    MAX_RECEIVE = 10  # sqs.go:57-77 batch size

    def __init__(self):
        self._q: deque = deque()
        self._inflight: Dict[int, Message] = {}
        self._lock = threading.Lock()
        self._seq = 0

    def send(self, msg: Message) -> None:
        with self._lock:
            self._q.append(msg)

    def receive(self) -> List[tuple]:
        """Returns [(handle, Message)] up to MAX_RECEIVE."""
        out = []
        with self._lock:
            while self._q and len(out) < self.MAX_RECEIVE:
                msg = self._q.popleft()
                self._seq += 1
                self._inflight[self._seq] = msg
                out.append((self._seq, msg))
        return out

    def delete(self, handle: int) -> None:
        with self._lock:
            self._inflight.pop(handle, None)

    def requeue_inflight(self) -> None:
        """Visibility timeout expiry: undeleted messages return to the queue."""
        with self._lock:
            for h in sorted(self._inflight):
                self._q.appendleft(self._inflight.pop(h))

    def __len__(self) -> int:
        with self._lock:
            return len(self._q)


class InterruptionController:
    name = "interruption"

    # which kinds trigger cordon-and-drain (controller.go:96-137: all but noop;
    # state_change only for stopping/terminating states)
    _ACTIONABLE_STATES = {"stopping", "stopped", "shutting-down", "terminated"}

    def __init__(
        self,
        store: st.Store,
        queue: InterruptionQueue,
        unavailable: Optional[UnavailableOfferings] = None,
    ):
        self.store = store
        self.queue = queue
        self.unavailable = unavailable or UnavailableOfferings()
        # instance-id -> claim-name index: the reference's status.instanceID
        # field indexer (operator.go:284-305) — interruption is the hot path
        # where a per-message linear scan over claims would be O(msgs×claims).
        # Watch-driven (informer-style) so it is exact under mid-batch
        # additions: a claim whose provider_id lands between batch start and
        # message handling is indexed by its MODIFIED event before the
        # message's lookup runs (watch delivery is synchronous with the
        # mutation's drain). Deletions race benignly: the existence re-check
        # in _claim_by_instance drops stale hits.
        self._index: Dict[str, str] = {}
        # ids proven absent by a direct store scan (unknown instances,
        # repeat messages for deleted claims): O(1) misses on the hot path
        # instead of a per-message O(claims) scan. Exactness: any claim
        # event that (re)binds a provider id discards its negative entry,
        # and the scan that populates it reads the store directly.
        self._negative: set = set()
        self._index_lock = threading.Lock()
        store.watch(st.NODECLAIMS, self._on_claim_event)

    def _on_claim_event(self, event: str, kind: str, obj) -> None:
        if not getattr(obj, "provider_id", None):
            return
        iid = obj.provider_id.rsplit("/", 1)[-1]
        with self._index_lock:
            if event == "DELETED":
                # only retire the mapping this claim actually owns — the id
                # may have been re-bound to a newer live claim, whose entry
                # (and interruptions) must survive the old claim's deletion
                if self._index.get(iid) == obj.name:
                    self._index.pop(iid, None)
                    self._negative.add(iid)
                    if len(self._negative) > 100_000:
                        self._negative.clear()  # bounded; rebuilds lazily
            else:
                self._index[iid] = obj.name
                self._negative.discard(iid)

    def reconcile(self) -> bool:
        batch = self.queue.receive()
        if not batch:
            return False
        for handle, msg in batch:
            try:
                self._handle(msg)
            finally:
                self.queue.delete(handle)
        return True

    # -- per-message --------------------------------------------------------

    def _handle(self, msg: Message) -> None:
        if msg.kind == NOOP:
            return
        if msg.kind == STATE_CHANGE and msg.state not in self._ACTIONABLE_STATES:
            return
        claim = self._claim_by_instance(msg.instance_id)
        if claim is None:
            return
        if msg.kind == SPOT_INTERRUPTION and claim.capacity_type == wk.CAPACITY_TYPE_SPOT:
            # the spot pool just proved unavailable: mask the offering so the
            # replacement solve avoids it (controller.go:219-225)
            self.unavailable.mark_unavailable(
                wk.CAPACITY_TYPE_SPOT, claim.instance_type, claim.zone
            )
        # cordon-and-drain == delete the NodeClaim; termination handles the rest
        if not claim.meta.deleting:
            try:
                self.store.delete(st.NODECLAIMS, claim.name)
            except st.NotFound:
                pass
            NODECLAIMS_TERMINATED.inc(nodepool=claim.nodepool, reason="interrupted")

    def _claim_by_instance(self, instance_id: str):
        if not instance_id:
            return None
        with self._index_lock:
            name = self._index.get(instance_id)
            known_absent = name is None and instance_id in self._negative
        if known_absent:
            return None
        if name is None:
            # Exactness fallback: watch delivery can lag a mutation when the
            # dispatch queue is draining behind a slow watcher, so a FIRST
            # miss is re-checked against the store directly — a dropped
            # message here would never be retried (reconcile deletes it).
            # A confirmed absence is remembered (negative set), so repeat
            # unknown-id messages stay O(1) and the scan amortizes to once
            # per distinct id per binding epoch.
            for c in self.store.list(st.NODECLAIMS):
                if c.provider_id and c.provider_id.rsplit("/", 1)[-1] == instance_id:
                    return c
            with self._index_lock:
                if instance_id not in self._index:
                    self._negative.add(instance_id)
                    if len(self._negative) > 100_000:
                        self._negative.clear()  # same bound as the event path
            return None
        c = self.store.try_get(st.NODECLAIMS, name)
        if (
            c is None
            or not c.provider_id
            or c.provider_id.rsplit("/", 1)[-1] != instance_id
        ):
            return None  # deleted or re-assigned since the index refresh
        return c
