"""In-process object store with watch semantics — the message bus.

The reference's layers communicate exclusively through watch/reconcile on the
kube API server (SURVEY.md §1: "Kubernetes API server is the message bus";
no custom RPC). This store is the hermetic stand-in: typed collections,
optimistic resource versions, finalizer-gated deletion, and watch events
feeding controller work queues.

Deletion semantics mirror kube: delete() sets deletion_timestamp; the object
remains until every finalizer is removed, then is purged (the reference's
termination flow relies on this — designs/termination.md, SURVEY.md §3.3).
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import defaultdict
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

from .. import faults

WatchFn = Callable[[str, str, Any], None]  # (event, kind, obj); event in ADDED|MODIFIED|DELETED


class Conflict(Exception):
    """Optimistic-concurrency conflict (stale resource_version)."""


class NotFound(Exception):
    pass


class Store:
    def __init__(self, clock=time.monotonic):
        self.clock = clock  # stamps creation_timestamp on create()
        self._lock = threading.RLock()
        self._objects: Dict[str, Dict[str, Any]] = defaultdict(dict)  # kind -> key -> obj
        self._watchers: List[Tuple[Optional[str], WatchFn]] = []
        self._rv_counter = 0  # last issued resource version
        # watcher events queue under _lock (rv order) and deliver outside it
        from collections import deque

        self._pending = deque()
        self._dispatch_lock = threading.Lock()
        # admission validators per kind (the CRD-schema/CEL analog — the
        # store IS this framework's API server): fn(kind, obj) raises on
        # invalid objects before they are persisted
        self._validators: Dict[str, Callable[[str, Any], None]] = {}

    def set_validator(self, kind: str, fn: Callable[[str, Any], None]) -> None:
        self._validators[kind] = fn

    def _admit(self, kind: str, obj: Any) -> None:
        fn = self._validators.get(kind)
        if fn is not None:
            fn(kind, obj)

    @staticmethod
    def _key(obj: Any) -> str:
        m = obj.meta
        return f"{m.namespace}/{m.name}"

    def bump_to(self, rv: int) -> None:
        """Advance the resource-version counter past a restored snapshot's
        high-water mark so post-restore updates stay monotonic."""
        with self._lock:
            self._rv_counter = max(self._rv_counter, rv)

    def _next_rv(self) -> int:
        self._rv_counter += 1
        return self._rv_counter

    def current_rv(self) -> int:
        """Last issued resource version — a non-consuming peek (snapshot
        change detection)."""
        with self._lock:
            return self._rv_counter

    # -- crud ---------------------------------------------------------------

    def create(self, kind: str, obj: Any) -> Any:
        self._admit(kind, obj)
        with self._lock:
            key = self._key(obj)
            if key in self._objects[kind]:
                raise Conflict(f"{kind} {key} already exists")
            obj.meta.resource_version = self._next_rv()
            if obj.meta.creation_timestamp is None:
                # the API-server stamp: every persisted object gets its age
                # from the store's clock (callers may pre-stamp, e.g. the
                # cloudprovider's instance-derived claims)
                obj.meta.creation_timestamp = self.clock()
            if getattr(obj, "last_transition", False) is None:
                obj.last_transition = self.clock()
            self._objects[kind][key] = obj
            self._enqueue("ADDED", kind, obj)
        self._drain()
        return obj

    def _admit_update(self, kind: str, obj: Any) -> None:
        # Admission on update: deleting objects are exempt (finalizer removal
        # must always proceed), and objects whose STORED state already fails
        # validation are grandfathered (e.g. restored from a pre-rule
        # snapshot) so they never become un-updatable. Caveat: in-process
        # callers often mutate the live stored object before calling
        # update(), so a rejected update cannot un-publish the mutation —
        # admission is airtight for create(), advisory for update().
        if not obj.meta.deleting:
            try:
                self._admit(kind, obj)
            except Exception:
                cur0 = self.try_get(kind, obj.meta.name, obj.meta.namespace)
                grandfathered = False
                if cur0 is not None:
                    try:
                        self._admit(kind, cur0)
                    except Exception:
                        grandfathered = True
                if not grandfathered:
                    raise

    def update(self, kind: str, obj: Any) -> Any:
        faults.check("store.update")
        self._admit_update(kind, obj)
        with self._lock:
            key = self._key(obj)
            cur = self._objects[kind].get(key)
            if cur is None:
                raise NotFound(f"{kind} {key}")
            obj.meta.resource_version = self._next_rv()
            self._objects[kind][key] = obj
            # finalizer-gated purge: a deleting object with no finalizers goes away
            if obj.meta.deleting and not obj.meta.finalizers:
                del self._objects[kind][key]
                self._enqueue("DELETED", kind, obj)
            else:
                self._enqueue("MODIFIED", kind, obj)
        self._drain()
        return obj

    def update_if(self, kind: str, obj: Any, expected_rv: int) -> Any:
        """Compare-and-swap update: succeeds only if the stored object's
        resource_version still equals expected_rv (real optimistic
        concurrency for contended objects like the leader lease — callers
        must write a FRESH object, not mutate the stored one). Same admission
        as update(): CAS is not a validation bypass."""
        self._admit_update(kind, obj)
        with self._lock:
            key = self._key(obj)
            cur = self._objects[kind].get(key)
            if cur is None:
                raise NotFound(f"{kind} {key}")
            if cur.meta.resource_version != expected_rv:
                raise Conflict(
                    f"{kind} {key}: rv {cur.meta.resource_version} != {expected_rv}"
                )
            obj.meta.resource_version = self._next_rv()
            self._objects[kind][key] = obj
            self._enqueue("MODIFIED", kind, obj)
        self._drain()
        return obj

    def delete(self, kind: str, name: str, namespace: str = "default") -> None:
        """Kube-style: mark deleting; purge only when finalizers are gone."""
        with self._lock:
            key = f"{namespace}/{name}"
            cur = self._objects[kind].get(key)
            if cur is None:
                raise NotFound(f"{kind} {key}")
            if cur.meta.finalizers:
                if cur.meta.deleting:
                    return
                cur.meta.deletion_timestamp = time.monotonic()
                cur.meta.resource_version = self._next_rv()
                self._enqueue("MODIFIED", kind, cur)
            else:
                del self._objects[kind][key]
                cur.meta.deletion_timestamp = cur.meta.deletion_timestamp or time.monotonic()
                self._enqueue("DELETED", kind, cur)
        self._drain()

    def get(self, kind: str, name: str, namespace: str = "default") -> Any:
        with self._lock:
            obj = self._objects[kind].get(f"{namespace}/{name}")
            if obj is None:
                raise NotFound(f"{kind} {namespace}/{name}")
            return obj

    def try_get(self, kind: str, name: str, namespace: str = "default") -> Optional[Any]:
        with self._lock:
            return self._objects[kind].get(f"{namespace}/{name}")

    def list(self, kind: str) -> List[Any]:
        with self._lock:
            return list(self._objects[kind].values())

    # -- watch --------------------------------------------------------------

    def watch(self, kind: Optional[str], fn: WatchFn) -> None:
        """Register a watcher; kind=None watches everything. Existing objects
        are replayed as ADDED (informer-style initial list)."""
        with self._lock:
            self._watchers.append((kind, fn))
            kinds = [kind] if kind else list(self._objects)
            for k in kinds:
                for obj in self._objects[k].values():
                    fn("ADDED", k, obj)

    def _enqueue(self, event: str, kind: str, obj: Any) -> None:
        """Called UNDER the store lock so queue order matches rv order."""
        self._pending.append((event, kind, obj))

    def _drain(self) -> None:
        """Deliver queued events OUTSIDE the store lock, in rv order, from a
        single drainer at a time: a slow watcher never stalls other threads'
        mutations (they enqueue and return; the active drainer delivers
        their events in order when the watcher yields).

        The outer loop closes the lost-wakeup window: a thread that enqueued
        while the drainer was between its empty-check and its lock release
        re-checks after the release instead of assuming delivery."""
        while self._pending:
            if not self._dispatch_lock.acquire(blocking=False):
                return  # an active drainer will re-check after releasing
            try:
                while True:
                    try:
                        event, kind, obj = self._pending.popleft()
                    except IndexError:
                        break
                    for k, fn in list(self._watchers):
                        if k is None or k == kind:
                            fn(event, kind, obj)
            finally:
                self._dispatch_lock.release()


def repose_pod(store: "Store", pod) -> None:
    """Unbind a pod back to Pending (the ReplicaSet-recreates-it analog).
    THE one re-pose idiom — eviction, forced drain, disruption pre-spin,
    and pod GC all route here so the operation can grow steps (nomination
    clearing, events) without the call sites diverging."""
    pod.node_name = None
    pod.phase = "Pending"
    store.update(PODS, pod)


# Canonical kind names
PODS = "pods"
NODES = "nodes"
NODEPOOLS = "nodepools"
NODECLAIMS = "nodeclaims"
NODECLASSES = "nodeclasses"
PDBS = "poddisruptionbudgets"
DAEMONSETS = "daemonsets"
PERSISTENTVOLUMES = "persistentvolumes"
PERSISTENTVOLUMECLAIMS = "persistentvolumeclaims"
