"""In-process object store with watch semantics — the message bus.

The reference's layers communicate exclusively through watch/reconcile on the
kube API server (SURVEY.md §1: "Kubernetes API server is the message bus";
no custom RPC). This store is the hermetic stand-in: typed collections,
optimistic resource versions, finalizer-gated deletion, and watch events
feeding controller work queues.

Deletion semantics mirror kube: delete() sets deletion_timestamp; the object
remains until every finalizer is removed, then is purged (the reference's
termination flow relies on this — designs/termination.md, SURVEY.md §3.3).
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import defaultdict
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

WatchFn = Callable[[str, str, Any], None]  # (event, kind, obj); event in ADDED|MODIFIED|DELETED


class Conflict(Exception):
    """Optimistic-concurrency conflict (stale resource_version)."""


class NotFound(Exception):
    pass


class Store:
    def __init__(self):
        self._lock = threading.RLock()
        self._objects: Dict[str, Dict[str, Any]] = defaultdict(dict)  # kind -> key -> obj
        self._watchers: List[Tuple[Optional[str], WatchFn]] = []
        self._rv = itertools.count(1)

    @staticmethod
    def _key(obj: Any) -> str:
        m = obj.meta
        return f"{m.namespace}/{m.name}"

    # -- crud ---------------------------------------------------------------

    def create(self, kind: str, obj: Any) -> Any:
        with self._lock:
            key = self._key(obj)
            if key in self._objects[kind]:
                raise Conflict(f"{kind} {key} already exists")
            obj.meta.resource_version = next(self._rv)
            self._objects[kind][key] = obj
            self._notify("ADDED", kind, obj)
            return obj

    def update(self, kind: str, obj: Any) -> Any:
        with self._lock:
            key = self._key(obj)
            cur = self._objects[kind].get(key)
            if cur is None:
                raise NotFound(f"{kind} {key}")
            obj.meta.resource_version = next(self._rv)
            self._objects[kind][key] = obj
            # finalizer-gated purge: a deleting object with no finalizers goes away
            if obj.meta.deleting and not obj.meta.finalizers:
                del self._objects[kind][key]
                self._notify("DELETED", kind, obj)
            else:
                self._notify("MODIFIED", kind, obj)
            return obj

    def delete(self, kind: str, name: str, namespace: str = "default") -> None:
        """Kube-style: mark deleting; purge only when finalizers are gone."""
        with self._lock:
            key = f"{namespace}/{name}"
            cur = self._objects[kind].get(key)
            if cur is None:
                raise NotFound(f"{kind} {key}")
            if cur.meta.finalizers:
                if not cur.meta.deleting:
                    cur.meta.deletion_timestamp = time.monotonic()
                    cur.meta.resource_version = next(self._rv)
                    self._notify("MODIFIED", kind, cur)
                return
            del self._objects[kind][key]
            cur.meta.deletion_timestamp = cur.meta.deletion_timestamp or time.monotonic()
            self._notify("DELETED", kind, cur)

    def get(self, kind: str, name: str, namespace: str = "default") -> Any:
        with self._lock:
            obj = self._objects[kind].get(f"{namespace}/{name}")
            if obj is None:
                raise NotFound(f"{kind} {namespace}/{name}")
            return obj

    def try_get(self, kind: str, name: str, namespace: str = "default") -> Optional[Any]:
        with self._lock:
            return self._objects[kind].get(f"{namespace}/{name}")

    def list(self, kind: str) -> List[Any]:
        with self._lock:
            return list(self._objects[kind].values())

    # -- watch --------------------------------------------------------------

    def watch(self, kind: Optional[str], fn: WatchFn) -> None:
        """Register a watcher; kind=None watches everything. Existing objects
        are replayed as ADDED (informer-style initial list)."""
        with self._lock:
            self._watchers.append((kind, fn))
            kinds = [kind] if kind else list(self._objects)
            for k in kinds:
                for obj in self._objects[k].values():
                    fn("ADDED", k, obj)

    def _notify(self, event: str, kind: str, obj: Any) -> None:
        for k, fn in list(self._watchers):
            if k is None or k == kind:
                fn(event, kind, obj)


# Canonical kind names
PODS = "pods"
NODES = "nodes"
NODEPOOLS = "nodepools"
NODECLAIMS = "nodeclaims"
NODECLASSES = "nodeclasses"
PDBS = "poddisruptionbudgets"
DAEMONSETS = "daemonsets"
