"""NodeClass status + drift controllers.

- NodeClassController: the status reconciler (readiness) of
  pkg/controllers/nodeclass/controller.go:62-100 — resolves the class's
  catalog selection, validates it matches something, sets readiness.
- DriftController: hash-based drift detection (cloudprovider.go IsDrifted +
  drift.go:34-74 behaviorally): a claim drifts when its recorded
  nodepool-hash or nodeclass-hash no longer matches the live objects, or its
  instance no longer satisfies the class selection (AMI-drift analog via
  image_version). The disruption engine's Drift method then replaces it.
"""

from __future__ import annotations

from typing import Optional

from ..api import wellknown as wk
from ..api.nodeclass import KwokNodeClass
from ..api.objects import NodePool
from ..catalog.catalog import generate
from ..controllers import store as st


def nodepool_static_hash(np_obj: NodePool) -> str:
    import hashlib
    import json

    t = np_obj.template
    spec = {
        "labels": sorted(t.labels.items()),
        "annotations": sorted(t.annotations.items()),
        "taints": sorted((x.key, x.value, x.effect) for x in t.taints),
        "startup_taints": sorted((x.key, x.value, x.effect) for x in t.startup_taints),
        "requirements": sorted(
            (k, r.complement, sorted(r.values), r.greater_than, r.less_than)
            for k, r in t.requirements.items()
        ),
        "node_class_ref": t.node_class_ref,
        "expire_after_s": t.expire_after_s,
    }
    return hashlib.sha256(json.dumps(spec, sort_keys=True).encode()).hexdigest()[:16]


class NodeClassController:
    name = "nodeclass.status"

    def __init__(self, store: st.Store, catalog=None):
        self.store = store
        self.catalog = catalog if catalog is not None else generate()

    def reconcile(self) -> bool:
        did = False
        for nc in self.store.list(st.NODECLASSES):
            ready, msg = self._resolve(nc)
            if nc.ready != ready or nc.status_message != msg:
                nc.ready = ready
                nc.status_message = msg
                self.store.update(st.NODECLASSES, nc)
                did = True
        return did

    def _resolve(self, nc: KwokNodeClass):
        matched = 0
        for it in self.catalog:
            fam = it.name.split(".")[0]
            if nc.instance_families is not None and fam not in nc.instance_families:
                continue
            gen_req = it.requirements.get("karpenter.tpu/instance-generation")
            if gen_req is not None:
                gen = int(gen_req.values_list()[0]) if gen_req.values_list() else 0
                if gen < nc.min_generation:
                    continue
            matched += 1
        if matched == 0:
            return False, "no instance types match the class selection"
        return True, f"{matched} instance types resolved"


class DriftController:
    name = "nodeclaim.drift"

    def __init__(self, store: st.Store):
        self.store = store

    def reconcile(self) -> bool:
        nodepools = {p.name: p for p in self.store.list(st.NODEPOOLS)}
        classes = {c.name: c for c in self.store.list(st.NODECLASSES)}
        did = False
        for claim in self.store.list(st.NODECLAIMS):
            if not claim.initialized or claim.meta.deleting:
                continue
            reason = self._drift_reason(claim, nodepools, classes)
            if reason != claim.drifted:
                claim.drifted = reason
                self.store.update(st.NODECLAIMS, claim)
                did = True
        return did

    def _drift_reason(self, claim, nodepools, classes) -> Optional[str]:
        np_obj = nodepools.get(claim.nodepool)
        if np_obj is None:
            return None  # ownerless claims are GC'd elsewhere, not drifted
        recorded_np = claim.meta.annotations.get(wk.NODEPOOL_HASH_ANNOTATION)
        if recorded_np is not None and recorded_np != nodepool_static_hash(np_obj):
            return "NodePoolDrifted"
        nc = classes.get(claim.node_class_ref)
        if nc is not None:
            recorded_nc = claim.meta.annotations.get(wk.NODECLASS_HASH_ANNOTATION)
            if recorded_nc is not None and recorded_nc != nc.static_hash():
                return "NodeClassDrifted"
        return None
