"""Deterministic tick-based controller runtime.

The reference runs controller-runtime reconcilers on workqueues with
per-controller concurrency (SURVEY.md §2.10). This framework's runtime is a
deterministic tick engine: each registered controller exposes `reconcile() ->
bool` (did work); `tick()` runs every controller once; `settle()` ticks until
a fixed point (no controller did work) — giving tests the exact semantics the
reference gets from `ExpectProvisioned`-style eventually-blocks without
sleeps or races. A threaded `run()` drives the same controllers continuously
for live operation.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Protocol


class Controller(Protocol):
    name: str

    def reconcile(self) -> bool:  # returns True if it changed anything
        ...


class Manager:
    def __init__(self, elector=None, on_elected: Callable[[], None] = None):
        self.controllers: List[Controller] = []
        self._stop = threading.Event()
        # lease-based leader election (controllers/leaderelection.py):
        # standbys tick the elector but run nothing until they take over —
        # the reference's singleton-controller HA model (settings.md:21)
        self.elector = elector
        # fires on every standby->leader transition BEFORE controllers run
        # (the operator wires snapshot re-hydration here so a takeover
        # resumes the dead leader's claims instead of duplicating them)
        self.on_elected = on_elected

    def register(self, *controllers: Controller) -> None:
        self.controllers.extend(controllers)

    def tick(self) -> bool:
        did = False
        if self.elector is not None:
            changed = self.elector.tick()
            if not self.elector.is_leader():
                return False
            if (
                changed
                and self.on_elected is not None
                and getattr(self.elector, "takeover", True)
            ):
                # takeover=False (fresh lease / own-lease reclaim) skips the
                # hook: an initial acquisition must not clear-restore over
                # objects injected between construction and the first tick
                try:
                    self.on_elected()
                except Exception as e:  # noqa: BLE001 — lead anyway
                    import logging

                    logging.getLogger("karpenter_tpu").exception(
                        "on_elected hook: %s", e
                    )
        for c in self.controllers:
            try:
                did = bool(c.reconcile()) or did
            except Exception as e:  # a controller crash must not kill the loop
                import logging

                logging.getLogger("karpenter_tpu").exception("controller %s: %s", c.name, e)
        return did

    def settle(self, max_ticks: int = 200) -> int:
        """Tick until fixed point; returns tick count. Raises if not settled
        (a controller livelock is a bug worth failing loudly on)."""
        for i in range(max_ticks):
            if not self.tick():
                return i + 1
        raise RuntimeError(f"manager did not settle in {max_ticks} ticks")

    def run(self, interval_s: float = 1.0) -> threading.Thread:
        def loop():
            while not self._stop.is_set():
                self.tick()
                self._stop.wait(interval_s)

        t = threading.Thread(target=loop, daemon=True, name="karpenter-tpu-manager")
        self._loop_thread = t
        t.start()
        return t

    def stop(self) -> None:
        self._stop.set()
        # join the loop BEFORE resigning: an in-flight tick could otherwise
        # observe the resigned (expired) lease and CAS-re-acquire it on the
        # way out, leaving the dead process holding a fresh lease
        t = getattr(self, "_loop_thread", None)
        if t is not None and t is not threading.current_thread():
            t.join(timeout=30)
        if self.elector is not None:
            # clean shutdown hands off immediately: resign empties the lease
            # holder so a standby acquires on its next tick instead of
            # waiting out the full lease duration (kube's ReleaseOnCancel)
            self.elector.resign()
