"""Deterministic tick-based controller runtime.

The reference runs controller-runtime reconcilers on workqueues with
per-controller concurrency (SURVEY.md §2.10). This framework's runtime is a
deterministic tick engine: each registered controller exposes `reconcile() ->
bool` (did work); `tick()` runs every controller once; `settle()` ticks until
a fixed point (no controller did work) — giving tests the exact semantics the
reference gets from `ExpectProvisioned`-style eventually-blocks without
sleeps or races. A threaded `run()` drives the same controllers continuously
for live operation.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, List, Protocol

from ..metrics.registry import CONTROLLER_ERRORS, CONTROLLER_TICK_SECONDS

log = logging.getLogger("karpenter_tpu")

#: backoff ceiling: a crash-looping controller is still probed at least once
#: every BACKOFF_CAP ticks so recovery is observed without a restart
BACKOFF_CAP = 32


class Controller(Protocol):
    name: str

    def reconcile(self) -> bool:  # returns True if it changed anything
        ...


class Manager:
    def __init__(self, elector=None, on_elected: Callable[[], None] = None):
        self.controllers: List[Controller] = []
        self._stop = threading.Event()
        # crash-loop containment: per-controller consecutive-failure counts
        # and exponential tick backoff — a persistently crashing controller
        # is skipped for min(2**(failures-1), BACKOFF_CAP) ticks instead of
        # being retried at full rate with the same input forever
        self._failures: Dict[str, int] = {}
        self._skip: Dict[str, int] = {}
        # lease-based leader election (controllers/leaderelection.py):
        # standbys tick the elector but run nothing until they take over —
        # the reference's singleton-controller HA model (settings.md:21)
        self.elector = elector
        # fires on every standby->leader transition BEFORE controllers run
        # (the operator wires snapshot re-hydration here so a takeover
        # resumes the dead leader's claims instead of duplicating them)
        self.on_elected = on_elected

    def register(self, *controllers: Controller) -> None:
        self.controllers.extend(controllers)

    def tick(self) -> bool:
        did = False
        if self.elector is not None:
            changed = self.elector.tick()
            if not self.elector.is_leader():
                return False
            if (
                changed
                and self.on_elected is not None
                and getattr(self.elector, "takeover", True)
            ):
                # takeover=False (fresh lease / own-lease reclaim) skips the
                # hook: an initial acquisition must not clear-restore over
                # objects injected between construction and the first tick
                try:
                    self.on_elected()
                except Exception as e:  # noqa: BLE001 — lead anyway
                    import logging

                    logging.getLogger("karpenter_tpu").exception(
                        "on_elected hook: %s", e
                    )
        for c in self.controllers:
            if self._skip.get(c.name, 0) > 0:
                self._skip[c.name] -= 1
                continue
            t0 = time.perf_counter()
            try:
                did = bool(c.reconcile()) or did
            except Exception as e:  # a controller crash must not kill the loop
                CONTROLLER_TICK_SECONDS.observe(
                    time.perf_counter() - t0, controller=c.name
                )
                f = self._failures.get(c.name, 0) + 1
                self._failures[c.name] = f
                self._skip[c.name] = min(2 ** (f - 1), BACKOFF_CAP)
                CONTROLLER_ERRORS.inc(controller=c.name)
                log.exception(
                    "controller %s: %s (consecutive failures: %d, backing "
                    "off %d ticks)", c.name, e, f, self._skip[c.name],
                )
            else:
                CONTROLLER_TICK_SECONDS.observe(
                    time.perf_counter() - t0, controller=c.name
                )
                if self._failures.get(c.name):
                    log.info("controller %s recovered after %d failures",
                             c.name, self._failures[c.name])
                self._failures[c.name] = 0
        return did

    def health(self) -> Dict[str, Dict[str, int]]:
        """Per-controller crash-loop snapshot: consecutive failures and
        remaining backoff ticks (0/0 = healthy)."""
        return {
            c.name: {
                "consecutive_failures": self._failures.get(c.name, 0),
                "backoff_ticks_remaining": self._skip.get(c.name, 0),
            }
            for c in self.controllers
        }

    def settle(self, max_ticks: int = 200) -> int:
        """Tick until fixed point; returns tick count. Raises if not settled
        (a controller livelock is a bug worth failing loudly on)."""
        for i in range(max_ticks):
            if not self.tick():
                return i + 1
        raise RuntimeError(f"manager did not settle in {max_ticks} ticks")

    def run(self, interval_s: float = 1.0) -> threading.Thread:
        def loop():
            while not self._stop.is_set():
                self.tick()
                self._stop.wait(interval_s)

        t = threading.Thread(target=loop, daemon=True, name="karpenter-tpu-manager")
        self._loop_thread = t
        t.start()
        return t

    def stop(self) -> None:
        self._stop.set()
        # join the loop BEFORE resigning: an in-flight tick could otherwise
        # observe the resigned (expired) lease and CAS-re-acquire it on the
        # way out, leaving the dead process holding a fresh lease
        t = getattr(self, "_loop_thread", None)
        if t is not None and t is not threading.current_thread():
            t.join(timeout=30)
        if self.elector is not None:
            # clean shutdown hands off immediately: resign empties the lease
            # holder so a standby acquires on its next tick instead of
            # waiting out the full lease duration (kube's ReleaseOnCancel)
            self.elector.resign()
