"""Reference scheduler: exact, sequential implementation of solver/SPEC.md.

This is the ground-truth `Solver` — the behavioral mirror of karpenter core's
`provisioning/scheduling.Scheduler.Solve` (designs/bin-packing.md:17-43;
website/.../concepts/scheduling.md; SURVEY.md §2.1). The TPU tensor solver in
`karpenter_tpu/solver/tpu/` must produce bit-identical decisions; the
differential tests enforce it.

Everything here is integer-exact and deterministic per SPEC.md.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

from ..api import wellknown as wk
from ..api.objects import Pod, Taint, Toleration, TopologySpreadConstraint, tolerates_all
from ..cloudprovider.types import InstanceType
from ..scheduling.requirements import IN, Requirement, Requirements
from ..utils.resources import PODS, Resources


# ---------------------------------------------------------------------------
# Inputs / outputs
# ---------------------------------------------------------------------------


@dataclass
class BoundPodRef:
    """Preemption-relevant view of one bound pod: enough to plan an eviction
    (who, how important, how much capacity it returns) without carrying the
    Pod object into the solver."""

    uid: str
    priority: int
    requests: Resources
    # False for pods the preemption planner must never evict: do-not-disrupt
    # annotated, DaemonSet-owned, or already terminating.
    evictable: bool = True


@dataclass
class ExistingNode:
    """A schedulable existing node or in-flight NodeClaim."""

    id: str
    labels: Dict[str, str]
    taints: List[Taint]
    free: Resources  # allocatable minus bound pods/daemonsets
    pod_labels: List[Dict[str, str]] = field(default_factory=list)  # bound pods (for topo/affinity)
    schedulable: bool = True
    # bound-pod refs for the preemption planner (solver/scheduling_class.py);
    # empty is always safe — the node simply offers no reclaimable capacity
    bound_pods: List[BoundPodRef] = field(default_factory=list)


@dataclass
class NodePoolSpec:
    name: str
    weight: int
    requirements: Requirements  # template labels+requirements (+nodepool label)
    taints: List[Taint]
    instance_types: List[InstanceType]
    limits: Resources = field(default_factory=Resources)
    usage: Resources = field(default_factory=Resources)  # current aggregate
    # per-pool backend override (wellknown.SOLVER_BACKEND_LABEL); None =
    # operator default. Consulted only by the ConvexSolver selection gate —
    # the FFD kernel and the oracle never read it.
    solver_backend: Optional[str] = None


@dataclass
class SolverInput:
    pods: List[Pod]
    nodes: List[ExistingNode]
    nodepools: List[NodePoolSpec]
    daemonset_pods: List[Pod] = field(default_factory=list)
    zones: Tuple[str, ...] = ()  # zone universe (for topology domains)
    capacity_types: Tuple[str, ...] = (wk.CAPACITY_TYPE_ON_DEMAND, wk.CAPACITY_TYPE_SPOT)
    # --preference-policy (settings.md:38): Respect treats preferences as
    # required and relaxes them by ascending weight on failure; Ignore drops
    # every preference up front.
    preference_policy: str = "Respect"
    # pods are ALREADY in canonical FFD order — skip the sort. Set only by
    # the device relaxation loop (solver/relax.py), which must keep the
    # ORIGINAL pods' processing order while pods' materialized signatures
    # change between redispatches.
    presorted: bool = False
    # Encode-cache delta stamp (state/cluster.py:EncodeDeltas.snapshot()):
    # (tracker identity, catalog rev, pods rev, nodes rev). Optional hint —
    # a matching tracker + catalog rev lets the incremental encoder skip the
    # deep catalog-key compare when hunting a patch donor (solver/
    # encode_cache.py); None is always safe (full compare).
    state_rev: Optional[tuple] = None
    # Tenancy attribution (solver/tenancy.py): which tenant's cluster this
    # snapshot belongs to. Never consulted by the solving math — it selects
    # the per-tenant encode-cache namespace and arena residency namespace,
    # and rides into span attrs / flight dumps / JSON logs. None = the
    # single-tenant default namespace (byte-identical to pre-tenancy).
    tenant_id: Optional[str] = None


@dataclass
class ClaimResult:
    nodepool: str
    requirements: Requirements
    instance_type_names: List[str]
    pod_uids: List[str]
    requests: Resources
    taints: List[Taint]
    hostname: str


@dataclass
class Eviction:
    """One planned preemption: evict `pod_uid` (bound on `node_id`) so the
    strictly-higher-priority pending pod `for_pod` can land there on a later
    reconcile. The solver plans; provisioning/preemption.py executes."""

    node_id: str
    pod_uid: str
    victim_priority: int
    for_pod: str


@dataclass
class SolverResult:
    placements: Dict[str, Tuple[str, object]]  # pod uid -> ("node", id) | ("claim", idx)
    claims: List[ClaimResult]
    errors: Dict[str, str]
    # scheduling-class outputs (solver/scheduling_class.py); default-empty so
    # every pre-existing constructor call and consumer stays valid
    evictions: List[Eviction] = field(default_factory=list)
    gangs_unschedulable: List[str] = field(default_factory=list)


# ---------------------------------------------------------------------------
# FFD order (SPEC.md "Pod order")
# ---------------------------------------------------------------------------


def ffd_key(pod: Pod):
    # cached on the pod: sort keys are an O(pods·log pods) Python cost per
    # solve; pods are immutable once admitted (objects are replaced on
    # update), so the key survives across solves like the encoder signature
    k = pod.__dict__.get("_ffd_key")
    if k is None:
        k = (-pod.requests.get_("cpu"), -pod.requests.get_("memory"), pod.meta.uid)
        pod.__dict__["_ffd_key"] = k
    return k


def ffd_sort(pods: Sequence[Pod]) -> List[Pod]:
    """Canonical FFD order (SPEC.md "Pod order"): descending (cpu, memory);
    within an equal-size block, same-signature pods group contiguously by
    first appearance (uid order within a signature). Size ties are arbitrary
    for FFD correctness — grouping them maximizes run length so the tensor
    path scans O(distinct specs) steps instead of O(pods) when differently-
    constrained pods interleave by uid.

    Scheduling classes (SPEC.md "Priority, preemption & gang semantics")
    prepend two keys — priority descending, then gang id lexicographic
    (non-gang pods carry "" and sort first within a priority) — but ONLY
    when the batch actually carries more than one distinct priority or any
    gang. A flat fleet takes the exact pre-class code path, so the class
    machinery is provably inert there (the lexsort keys would be constant
    anyway; skipping them keeps even the float of the key-build identical).

    Vectorized (numpy lexsort + stable regroup): the per-solve sort is an
    O(pods) host cost on the end-to-end Solve() seam, so no Python-level
    comparison runs; semantics are identical to the sequential spec above
    (tests/test_solver_parity.py covers the interleaved-tie cases)."""
    return ffd_sort_with_sigs(pods)[0]


def _class_keys(pods: Sequence[Pod]):
    """(neg_prio[int64], gang_rank[int64]) when class-aware ordering must
    engage, else None. Gang ranks are the lexicographic ranks of the gang-id
    strings with "" (no gang) ranked 0, so ascending rank == ascending lex
    order and non-gang pods precede gangs within a priority level."""
    import numpy as np

    from ..solver import scheduling_class as sc  # lazy: avoid import cycle

    n = len(pods)
    use_prio = sc.PRIORITY_ENABLED
    use_gang = sc.GANG_ENABLED
    if not use_prio and not use_gang:
        return None
    prios = np.fromiter((p.priority for p in pods), np.int64, n)
    gids = [(p.gang() or ("", 0, 0))[0] if use_gang else "" for p in pods]
    if (not use_prio or (prios == prios[0]).all()) and not any(gids):
        return None
    neg_prio = -prios if use_prio else np.zeros(n, np.int64)
    _, gang_rank = np.unique(np.array(gids, dtype=object), return_inverse=True)
    return neg_prio, gang_rank.astype(np.int64)


def ffd_sort_with_sigs(pods: Sequence[Pod], presorted: bool = False):
    """ffd_sort plus the interned signature id and uid per sorted pod — the
    encoder consumes these directly so the batch pays one key-gathering pass.

    Returns (sorted_pods, sigs_sorted[int64], uids_sorted[str], interned) —
    see encode.sig_nums for the `interned` contract. `presorted` trusts the
    caller's order (the relaxation loop re-encodes materialized pods in the
    ORIGINAL pods' canonical order — their mutated signatures would regroup
    differently within equal-size blocks and diverge from the oracle)."""
    import numpy as np

    from ..solver.encode import sig_nums  # lazy: avoid import cycle

    n = len(pods)
    if presorted or n <= 1:
        sigs, interned = sig_nums(pods)
        uids = np.array([p.meta.uid for p in pods], dtype=object)
        return list(pods), sigs, uids, interned
    keys = [ffd_key(p) for p in pods]
    neg_cpu = np.fromiter((k[0] for k in keys), np.int64, n)
    neg_mem = np.fromiter((k[1] for k in keys), np.int64, n)
    uids = np.array([k[2] for k in keys], dtype=object)
    sigs, interned = sig_nums(pods)
    cls = _class_keys(pods)
    if cls is None:
        # primary sort: the full ffd_key (-cpu, -mem, uid)
        order0 = np.lexsort((uids, neg_mem, neg_cpu))
        cpu_s, mem_s, sig_s = neg_cpu[order0], neg_mem[order0], sigs[order0]
        # equal-(cpu,mem) block ids over the sorted sequence
        blk = np.zeros(n, np.int64)
        blk[1:] = np.cumsum((np.diff(cpu_s) != 0) | (np.diff(mem_s) != 0))
    else:
        # class-aware order: (priority desc, gang_id, existing FFD key) —
        # same lexsort, two more significant keys; signature regrouping must
        # not cross a priority or gang boundary, so those keys join the
        # equal-block condition too
        neg_prio, gang_rank = cls
        order0 = np.lexsort((uids, neg_mem, neg_cpu, gang_rank, neg_prio))
        cpu_s, mem_s, sig_s = neg_cpu[order0], neg_mem[order0], sigs[order0]
        prio_s, gang_s = neg_prio[order0], gang_rank[order0]
        blk = np.zeros(n, np.int64)
        blk[1:] = np.cumsum(
            (np.diff(cpu_s) != 0) | (np.diff(mem_s) != 0)
            | (np.diff(prio_s) != 0) | (np.diff(gang_s) != 0)
        )
    # regroup within each block by signature first-appearance: stable argsort
    # on the first sorted-position of each (block, signature) pair — constant
    # within a pair, and always inside the pair's block, so blocks never mix
    pair = blk * (np.int64(sig_s.max()) + 1) + sig_s
    _, first_idx, inv = np.unique(pair, return_index=True, return_inverse=True)
    final = order0[np.argsort(first_idx[inv], kind="stable")]
    # map over a plain-int list: ~3× faster than indexing with numpy ints
    sorted_pods = list(map(pods.__getitem__, final.tolist()))
    return sorted_pods, sigs[final], uids[final], interned


# ---------------------------------------------------------------------------
# Topology / affinity state (SPEC.md "Topology spread", "Inter-pod affinity")
# ---------------------------------------------------------------------------


def _sel_sig(selector: Mapping[str, str]) -> tuple:
    return tuple(sorted(selector.items()))


def node_hostname(n: "ExistingNode") -> str:
    return n.labels.get(wk.HOSTNAME_LABEL, n.id)


def _matches(selector: Mapping[str, str], labels: Mapping[str, str]) -> bool:
    return all(labels.get(k) == v for k, v in selector.items())


class TopologyState:
    def __init__(self, inp: SolverInput):
        self._zones = tuple(inp.zones)
        self._capacity_types = tuple(inp.capacity_types)
        # hostname domain of an existing node = its hostname label, defaulting
        # to its id (real nodes always carry kubernetes.io/hostname; kwok
        # fabricates it equal to the node name) — SPEC.md "Topology spread"
        self._hostnames: List[str] = [node_hostname(n) for n in inp.nodes]
        # spread counts: (key, sel_sig, max_skew) -> {domain: count}
        self._spread: Dict[tuple, Dict[str, int]] = {}
        # matching-pod counts per (sel_sig, topo_key) -> {domain: count}
        self._match: Dict[tuple, Dict[str, int]] = {}
        # anti-affinity terms owned by placed pods: (sel_sig, key) -> set(domain)
        self._anti: Dict[tuple, set] = {}
        self._existing = inp.nodes
        # pods placed THIS solve: (labels, key->domain) — lazily-materialized
        # groups must see them (they are invisible in node.pod_labels when the
        # pod landed on a virtual claim).
        self._placed: List[Tuple[Dict[str, str], Dict[str, str]]] = []

    # -- universes ----------------------------------------------------------

    def universe(self, key: str) -> List[str]:
        if key == wk.ZONE_LABEL:
            return list(self._zones)
        if key == wk.CAPACITY_TYPE_LABEL:
            return list(self._capacity_types)
        if key == wk.HOSTNAME_LABEL:
            return list(self._hostnames)
        return []

    def add_hostname(self, h: str) -> None:
        self._hostnames.append(h)

    # -- spread groups ------------------------------------------------------

    def _group(self, tsc: TopologySpreadConstraint) -> Dict[str, int]:
        sig = (tsc.topology_key, _sel_sig(tsc.label_selector), tsc.max_skew)
        g = self._spread.get(sig)
        if g is None:
            g = {d: 0 for d in self.universe(tsc.topology_key)}
            for n in self._existing:
                if tsc.topology_key == wk.HOSTNAME_LABEL:
                    d = node_hostname(n)
                else:
                    d = n.labels.get(tsc.topology_key)
                if d is None:
                    continue
                g.setdefault(d, 0)
                for pl in n.pod_labels:
                    if _matches(tsc.label_selector, pl):
                        g[d] += 1
            for labels, domains in self._placed:
                d = domains.get(tsc.topology_key)
                if d is not None and _matches(tsc.label_selector, labels):
                    g[d] = g.get(d, 0) + 1
            self._spread[sig] = g
        return g

    def spread_allowed(
        self,
        tsc: TopologySpreadConstraint,
        pod_domains: Optional[set],
        extra_domains: Sequence[str] = (),
    ) -> set:
        """Domains where the pod may land: count[d]+1-min <= maxSkew."""
        g = self._group(tsc)
        for d in self.universe(tsc.topology_key):
            g.setdefault(d, 0)
        for d in extra_domains:  # e.g. a not-yet-registered claim hostname
            g.setdefault(d, 0)
        eligible = set(g)
        if pod_domains is not None:
            eligible &= pod_domains
        if not eligible:
            return set()
        if tsc.topology_key == wk.HOSTNAME_LABEL:
            floor = 0  # a fresh empty hostname is always creatable (SPEC.md)
        else:
            floor = min(g[d] for d in eligible)
        return {d for d in eligible if g[d] + 1 - floor <= tsc.max_skew}

    # -- affinity -----------------------------------------------------------

    def _match_group(self, selector: Mapping[str, str], key: str) -> Dict[str, int]:
        sig = (_sel_sig(selector), key)
        g = self._match.get(sig)
        if g is None:
            g = {}
            for n in self._existing:
                if key == wk.HOSTNAME_LABEL:
                    d = node_hostname(n)
                else:
                    d = n.labels.get(key)
                if d is None:
                    continue
                for pl in n.pod_labels:
                    if _matches(selector, pl):
                        g[d] = g.get(d, 0) + 1
            for labels, domains in self._placed:
                d = domains.get(key)
                if d is not None and _matches(selector, labels):
                    g[d] = g.get(d, 0) + 1
            self._match[sig] = g
        return g

    def affinity_domains(self, selector: Mapping[str, str], key: str) -> Dict[str, int]:
        return dict(self._match_group(selector, key))

    def anti_blocked(self, selector: Mapping[str, str], key: str) -> set:
        """Domains holding a pod matching `selector` (can't place anti pod)."""
        return {d for d, c in self._match_group(selector, key).items() if c > 0}

    def symmetric_anti_blocked(self, pod_labels: Mapping[str, str]) -> Dict[str, set]:
        """key -> blocked domains from already-placed pods' anti terms whose
        selector matches this pod."""
        out: Dict[str, set] = {}
        for (sel_sig, key), domains in self._anti.items():
            if _matches(dict(sel_sig), pod_labels):
                out.setdefault(key, set()).update(domains)
        return out

    # -- commit -------------------------------------------------------------

    def record(self, pod: Pod, domains: Mapping[str, str]) -> None:
        """Update all state after the pod lands with the given key->domain."""
        self._placed.append((dict(pod.meta.labels), dict(domains)))
        # every materialized spread group whose selector matches sees the pod
        # (not just the pod's own TSC signatures)
        for (key, sel_sig, _skew), g in self._spread.items():
            if _matches(dict(sel_sig), pod.meta.labels):
                d = domains.get(key)
                if d is not None:
                    g[d] = g.get(d, 0) + 1
        # matching-pod index: update every materialized group this pod matches
        for (sel_sig, key), g in self._match.items():
            if _matches(dict(sel_sig), pod.meta.labels):
                d = domains.get(key)
                if d is not None:
                    g[d] = g.get(d, 0) + 1
        # register owned anti-affinity terms
        for term in pod.affinity_terms:
            if term.weight is not None or not term.anti:
                continue
            d = domains.get(term.topology_key)
            if d is not None:
                sig = (_sel_sig(term.label_selector), term.topology_key)
                self._anti.setdefault(sig, set()).add(d)


# ---------------------------------------------------------------------------
# Virtual node (SPEC.md "Virtual-node instance-type survival")
# ---------------------------------------------------------------------------


class VirtualNode:
    def __init__(self, index: int, pool: NodePoolSpec, daemon_overhead: Resources):
        self.index = index
        self.pool = pool
        self.hostname = f"claim-{index}"
        self.requirements = Requirements(pool.requirements)
        self.options: List[InstanceType] = list(pool.instance_types)
        self.requests = Resources(daemon_overhead)
        self.requests[PODS] = self.requests.get_(PODS)  # ensure key
        self.pod_uids: List[str] = []
        self.taints = list(pool.taints)
        # claim-local affinity state: pods on one claim share EVERY topology
        # domain (same node ⇒ same zone), even while the claim's zone is
        # still multi-valued — so (anti-)affinity must see co-located pods
        # directly, not only through recorded zone counts (SPEC.md).
        self.pod_label_list: List[Dict[str, str]] = []
        self.anti_sigs: set = set()  # {(sel_sig, key)} owned by pods here
        # options start as the RAW pool catalog (unfiltered): the first
        # commit stores survivors of a full compatibility pass, after which
        # probes may re-check only CHANGED requirement keys (options only
        # ever shrink, and unchanged keys keep their verdicts)
        self._consistent = False

    def _surviving(
        self, reqs: Requirements, requests: Resources, changed_keys=None
    ) -> List[InstanceType]:
        incremental = changed_keys is not None and self._consistent
        # offering availability depends only on the zone/ct requirements:
        # unchanged -> every current option already passed it
        check_off = not incremental or any(
            k in (wk.ZONE_LABEL, wk.CAPACITY_TYPE_LABEL) for k in changed_keys
        )
        pairs = (
            [(k, reqs.get(k)) for k in changed_keys] if incremental else ()
        )
        out = []
        for it in self.options:
            if incremental:
                ok = True
                for k, r in pairs:
                    o = it.requirements.get(k)
                    if o is not None and not r.intersects(o):
                        ok = False
                        break
                if not ok:
                    continue
            elif not reqs.compatible(it.requirements):
                continue
            if not requests.fits(it.allocatable_view()):
                continue
            if check_off and not _has_offering(it, reqs):
                continue
            out.append(it)
        return out

    def try_add(self, pod: Pod, pod_reqs: Requirements) -> Optional[Tuple[Requirements, List[InstanceType], Resources]]:
        """Feasibility check; returns prospective state without committing."""
        if not tolerates_all(pod.tolerations, self.taints):
            return None
        combined = Requirements(self.requirements)
        combined.add(*pod_reqs.values())
        # unsatisfiable keys (empty sets, contradictory Gt/Lt) => fail fast
        for r in combined.values():
            if not r.satisfiable():
                return None
        requests = self.requests.add(pod.requests)
        requests[PODS] = requests.get_(PODS) + 1
        changed = [
            k for k, v in combined.items() if self.requirements.get(k) is not v
        ]
        survivors = self._surviving(combined, requests, changed_keys=changed)
        if not survivors:
            return None
        if not min_values_ok(combined, survivors):
            return None  # narrowed below the NodePool's flexibility floor
        return combined, survivors, requests

    # NOTE: there is deliberately no commit() helper — the one commit site
    # (_try_claim) interleaves topology bookkeeping with the state swap and
    # manages the _consistent flag itself; a second commit path would skip
    # that bookkeeping silently.

    def narrow(self, key: str, allowed: set) -> bool:
        """Intersect a label requirement with `allowed`; refilter options."""
        cur = self.requirements.get(key)
        req = Requirement.create(key, IN, sorted(allowed))
        nxt = cur.intersect(req) if cur is not None else req
        if not nxt.complement and not nxt.values:
            return False
        trial = Requirements(self.requirements)
        trial[key] = nxt
        survivors = self._surviving(trial, self.requests, changed_keys=[key])
        if not survivors:
            return False
        if not min_values_ok(trial, survivors):
            return False
        self.requirements, self.options = trial, survivors
        self._consistent = True
        return True

    def domain_values(self, key: str, universe: Sequence[str]) -> List[str]:
        """Current admissible domains for a topology key."""
        if key == wk.HOSTNAME_LABEL:
            return [self.hostname]
        r = self.requirements.get(key)
        if r is None:
            return list(universe)
        return [v for v in universe if r.has(v)]


def _has_offering(it: InstanceType, reqs: Requirements) -> bool:
    """Any available offering admitted by `reqs`. Exact unrolling of
    `reqs.compatible(o.requirements())`: an offering constrains exactly
    {zone IN [z], ct IN [c]}, compatible() walks reqs' keys and checks
    intersects against those two, and intersects(r, IN[v]) == r.has(v)
    (single-value intersection keeps r's own bounds). The unrolled form
    skips ~5 Requirements/Requirement constructions per offering — the
    oracle's former #1 hot spot (63 of 91 s on a 800-pod topology solve)."""
    zr = reqs.get(wk.ZONE_LABEL)
    cr = reqs.get(wk.CAPACITY_TYPE_LABEL)
    for o in it.offerings:
        if (
            o.available
            and (zr is None or zr.has(o.zone))
            and (cr is None or cr.has(o.capacity_type))
        ):
            return True
    return False


def distinct_values_at_least(
    key: str, eff: "Requirement", floor: int, survivors: Sequence[InstanceType]
) -> bool:
    """True iff the surviving instance types expose >= `floor` distinct
    values for `key` admitted by the effective requirement `eff` — the ONE
    counting rule behind minValues, shared by the oracle's per-step check
    and the tensor backends' final-state post-check."""
    vals: set = set()
    for it in survivors:
        ir = it.requirements.get(key)
        if ir is not None and not ir.complement:
            vals.update(v for v in ir.values if eff.has(v))
        if len(vals) >= floor:
            return True
    return len(vals) >= floor


def min_values_ok(reqs: Requirements, survivors: Sequence[InstanceType]) -> bool:
    """NodePool minValues flexibility floors (nodepools.md:268-330): every
    requirement carrying a floor must retain >= minValues distinct values
    among the surviving instance types. Checked at every narrowing step in
    the oracle; the tensor backends check the FINAL surviving sets instead —
    equivalent, because options only ever shrink (a final state meeting the
    floor implies every intermediate superset did too)."""
    for k, r in reqs.items():
        if not r.min_values:
            continue
        if not distinct_values_at_least(k, r, r.min_values, survivors):
            return False
    return True


# ---------------------------------------------------------------------------
# Scheduler
# ---------------------------------------------------------------------------


class Scheduler:
    """Sequential FFD scheduler per SPEC.md."""

    def __init__(self, inp: SolverInput):
        self.inp = inp
        self.topo = TopologyState(inp)
        self.claims: List[VirtualNode] = []
        self.node_free = {n.id: Resources(n.free) for n in inp.nodes}
        self.node_pods = {n.id: 0 for n in inp.nodes}
        self.pool_usage = {p.name: Resources(p.usage) for p in inp.nodepools}
        self.pools = sorted(inp.nodepools, key=lambda p: (-p.weight, p.name))
        self._daemon_cache: Dict[str, Resources] = {}
        # node labels are immutable during a solve: build Requirements once
        self._node_reqs = {n.id: Requirements.from_labels(n.labels) for n in inp.nodes}

    # -- daemonset overhead -------------------------------------------------

    def _daemon_overhead(self, pool: NodePoolSpec) -> Resources:
        cached = self._daemon_cache.get(pool.name)
        if cached is not None:
            return cached
        total = Resources()
        count = 0
        for dp in self.inp.daemonset_pods:
            if not tolerates_all(dp.tolerations, pool.taints):
                continue
            if not dp.scheduling_requirements().compatible(pool.requirements):
                continue
            total = total.add(dp.requests)
            count += 1
        total[PODS] = total.get_(PODS) + count
        self._daemon_cache[pool.name] = total
        return total

    # -- main loop ----------------------------------------------------------

    def solve(self) -> SolverResult:
        placements: Dict[str, Tuple[str, object]] = {}
        errors: Dict[str, str] = {}
        pods = ffd_sort([p for p in self.inp.pods if not p.scheduling_gated and not p.bound])
        for pod in pods:
            err = self._schedule_with_relaxation(pod, placements)
            if err:
                errors[pod.meta.uid] = err
        claims = [
            ClaimResult(
                nodepool=c.pool.name,
                requirements=c.requirements,
                instance_type_names=[it.name for it in c.options],
                pod_uids=c.pod_uids,
                requests=c.requests,
                taints=c.taints,
                hostname=c.hostname,
            )
            for c in self.claims
        ]
        return SolverResult(placements=placements, claims=claims, errors=errors)

    def _schedule_with_relaxation(self, pod: Pod, placements) -> Optional[str]:
        """Preferences treated as required, then relaxed ONE at a time by
        ascending weight until the pod places (scheduling.md:212-219).
        Preference kinds: preferred node affinity (its weight),
        ScheduleAnyway topology spread (weight 0 — relaxed first), and
        weighted (preferred) pod-affinity terms (their weight). Ties break by
        kind then input order (framework-chosen; the docs leave it open).
        --preference-policy=Ignore drops them all up front (settings.md:38)."""
        items: List[Tuple[int, int, str, int]] = []  # (weight, kind, tag, idx)
        if self.inp.preference_policy != "Ignore":
            for i, (w, _r) in enumerate(pod.preferred_node_affinity):
                items.append((w, 0, "na", i))
            for i, t in enumerate(pod.topology_spread):
                if t.when_unsatisfiable == "ScheduleAnyway":
                    items.append((0, 1, "tsc", i))
            for i, t in enumerate(pod.affinity_terms):
                if t.weight is not None:
                    items.append((t.weight, 2, "aff", i))
            items.sort(key=lambda it: (it[0], it[1], it[3]))
        dropped = 0
        while True:
            active = items[dropped:]
            active_prefs = [
                pod.preferred_node_affinity[i] for (_w, _k, tag, i) in active
                if tag == "na"
            ]
            eff = self._effective_pod(pod, active)
            err = self._try_schedule(pod, eff, active_prefs, placements)
            if err is None:
                return None
            if dropped >= len(items):
                return err
            dropped += 1  # relax lowest-weight preference and retry

    def _effective_pod(self, pod: Pod, active) -> Pod:
        """Pod view where the still-active soft constraints appear REQUIRED:
        active ScheduleAnyway spreads become DoNotSchedule, active weighted
        affinity terms lose their weight; dropped ones vanish. Admission
        checks read this view; bookkeeping (placement record, owned-anti
        registration) stays on the original pod so satisfied preferences
        never constrain later pods."""
        import dataclasses as _dc

        if all(t.when_unsatisfiable == "DoNotSchedule" for t in pod.topology_spread) and all(
            t.weight is None for t in pod.affinity_terms
        ):
            return pod
        act_tsc = {i for (_w, _k, tag, i) in active if tag == "tsc"}
        act_aff = {i for (_w, _k, tag, i) in active if tag == "aff"}
        tscs = []
        for i, t in enumerate(pod.topology_spread):
            if t.when_unsatisfiable == "DoNotSchedule":
                tscs.append(t)
            elif i in act_tsc:
                tscs.append(_dc.replace(t, when_unsatisfiable="DoNotSchedule"))
        affs = []
        for i, t in enumerate(pod.affinity_terms):
            if t.weight is None:
                affs.append(t)
            elif i in act_aff:
                affs.append(_dc.replace(t, weight=None))
        return _dc.replace(pod, topology_spread=tscs, affinity_terms=affs)

    def _pod_requirement_alternatives(self, pod: Pod, active_prefs) -> List[Requirements]:
        """nodeSelector ∧ (one OR'd required node-affinity term) ∧ active
        preferences — kube semantics: a node matches if ANY term matches, so
        each term yields an alternative tried per target in input order."""
        base = Requirements.from_labels(pod.node_selector)
        if pod.volume_zones is not None:
            # bound zonal PVs pin the pod to their zones (scheduling.md:430+);
            # an empty tuple (conflicting volumes) is unsatisfiable
            base.add(Requirement.create(wk.ZONE_LABEL, IN, list(pod.volume_zones)))
        for _w, pref in active_prefs:
            base = base.union(pref)
        if not pod.node_affinity:
            return [base]
        return [base.union(term) for term in pod.node_affinity]

    def _try_schedule(self, pod: Pod, eff: Pod, active_prefs, placements) -> Optional[str]:
        alternatives = self._pod_requirement_alternatives(pod, active_prefs)

        # 1. existing nodes, in order
        for n in self.inp.nodes:
            if any(self._try_existing(pod, eff, reqs, n) for reqs in alternatives):
                placements[pod.meta.uid] = ("node", n.id)
                return None

        # 2. open claims, in order
        for c in self.claims:
            if any(self._try_claim(pod, eff, reqs, c) for reqs in alternatives):
                placements[pod.meta.uid] = ("claim", c.index)
                return None

        # 3. new claim per nodepool
        last_err = "no nodepool admits the pod"
        for pool in self.pools:
            if self._limits_exceeded(pool):
                last_err = f"nodepool {pool.name} limits exceeded"
                continue
            c = VirtualNode(len(self.claims), pool, self._daemon_overhead(pool))
            if any(self._try_claim(pod, eff, reqs, c, new=True) for reqs in alternatives):
                self.claims.append(c)
                self.topo.add_hostname(c.hostname)
                placements[pod.meta.uid] = ("claim", c.index)
                self._charge_pool(pool, c)
                return None
            last_err = f"no instance type in nodepool {pool.name} satisfies the pod"
        return last_err

    # -- existing-node path -------------------------------------------------

    def _try_existing(self, pod: Pod, eff: Pod, pod_reqs: Requirements, n: ExistingNode) -> bool:
        if not n.schedulable:
            return False
        if not tolerates_all(pod.tolerations, n.taints):
            return False
        if not pod_reqs.strictly_compatible(self._node_reqs[n.id]):
            return False
        requests = pod.requests
        free = self.node_free[n.id]
        if not requests.fits(free):
            return False
        if free.get_(PODS) < 1:
            return False
        domains = {k: n.labels[k] for k in wk.TOPOLOGY_KEYS if k in n.labels}
        domains.setdefault(wk.HOSTNAME_LABEL, n.id)
        if not self._topo_admits_fixed(eff, pod_reqs, domains):
            return False
        # commit (the placement log in TopologyState.record covers topology
        # bookkeeping; n.pod_labels stays as-input to avoid double counting)
        nf = free.sub(requests)
        nf[PODS] = free.get_(PODS) - 1
        self.node_free[n.id] = nf
        self.topo.record(pod, domains)
        return True

    # -- claim path ---------------------------------------------------------

    def _try_claim(self, pod: Pod, eff: Pod, pod_reqs: Requirements, c: VirtualNode, new: bool = False) -> bool:
        state = c.try_add(pod, pod_reqs)
        if state is None:
            return False
        combined, survivors, requests = state
        # Topology/affinity: compute per-key narrowing before committing.
        # survivors ARE the full-filter result for `combined`, so the claim
        # is consistent during the topo phase (narrow() may run
        # incrementally); rollback must restore the PRIOR consistency flag
        # too, or a later probe would incrementally re-check the raw
        # unfiltered catalog (r5 review finding).
        saved_reqs, saved_opts, saved_cons = c.requirements, c.options, c._consistent
        c.requirements, c.options, c._consistent = combined, survivors, True
        ok, domains = self._topo_admits_claim(eff, pod_reqs, c)
        if not ok:
            c.requirements, c.options, c._consistent = (
                saved_reqs, saved_opts, saved_cons
            )
            return False
        c.requests = requests
        c.pod_uids.append(pod.meta.uid)
        c.pod_label_list.append(dict(pod.meta.labels))
        for term in pod.affinity_terms:
            if term.weight is None and term.anti and term.topology_key != wk.HOSTNAME_LABEL:
                c.anti_sigs.add((_sel_sig(term.label_selector), term.topology_key))
        self.topo.record(pod, domains)
        return True

    # -- topology/affinity admission ---------------------------------------

    def _pod_own_domains(self, pod_reqs: Requirements, key: str) -> Optional[set]:
        r = pod_reqs.get(key)
        if r is None or r.complement:
            return None
        return set(r.values_list())

    def _topo_admits_fixed(self, pod: Pod, pod_reqs: Requirements, domains: Mapping[str, str]) -> bool:
        for tsc in pod.topology_spread:
            if tsc.when_unsatisfiable != "DoNotSchedule":
                continue
            d = domains.get(tsc.topology_key)
            if d is None:
                return False
            allowed = self.topo.spread_allowed(tsc, self._pod_own_domains(pod_reqs, tsc.topology_key))
            if d not in allowed:
                return False
        return self._affinity_admits(pod, {k: {v} for k, v in domains.items()}, fixed=True)[0]

    def _anti_blocked_domains(self, pod: Pod, key: str) -> set:
        """Domains of `key` excluded by anti-affinity for this pod: owned
        required anti terms (domains holding matching pods) plus symmetric
        blocks from placed owners whose selector matches this pod."""
        blocked = set(self.topo.symmetric_anti_blocked(pod.meta.labels).get(key, set()))
        for term in pod.affinity_terms:
            if term.weight is not None or not term.anti or term.topology_key != key:
                continue
            blocked |= self.topo.anti_blocked(term.label_selector, key)
        return blocked

    def _affinity_present_restriction(
        self, pod: Pod, key: str, claim: Optional[VirtualNode] = None
    ) -> Optional[set]:
        """Joint positive-affinity restriction on `key`: the intersection of
        the present sets of the pod's required positive terms. Terms with no
        matching pod anywhere (bootstrap) or satisfied claim-locally impose
        no restriction. None = unrestricted."""
        restriction: Optional[set] = None
        for term in pod.affinity_terms:
            if term.weight is not None or term.anti or term.topology_key != key:
                continue
            if claim is not None and any(
                _matches(term.label_selector, pl) for pl in claim.pod_label_list
            ):
                continue  # co-located match satisfies the term
            present = {
                d
                for d, cnt in self.topo.affinity_domains(term.label_selector, key).items()
                if cnt > 0
            }
            if not present:
                continue  # bootstrap (or doomed later) — no restriction here
            restriction = present if restriction is None else (restriction & present)
        return restriction

    def _topo_admits_claim(self, pod: Pod, pod_reqs: Requirements, c: VirtualNode) -> Tuple[bool, Dict[str, str]]:
        """Admission + narrowing for a virtual node. Returns committed domains."""
        committed: Dict[str, str] = {wk.HOSTNAME_LABEL: c.hostname}
        # spread constraints — the allowed set is JOINT: skew rule minus the
        # pod's anti-affinity exclusions, so the committed domain is workable
        # under every constraint at once (the reference tracks topology
        # domains jointly across spread and affinity groups)
        for tsc in pod.topology_spread:
            if tsc.when_unsatisfiable != "DoNotSchedule":
                continue
            key = tsc.topology_key
            universe = self.topo.universe(key)
            node_domains = c.domain_values(key, universe)
            allowed = self.topo.spread_allowed(
                tsc,
                self._pod_own_domains(pod_reqs, key),
                extra_domains=(c.hostname,) if key == wk.HOSTNAME_LABEL else (),
            )
            allowed = allowed - self._anti_blocked_domains(pod, key)
            aff_restriction = self._affinity_present_restriction(pod, key, c)
            if aff_restriction is not None:
                allowed = allowed & aff_restriction
            inter = [d for d in node_domains if d in allowed]
            if not inter:
                return False, {}
            if key == wk.HOSTNAME_LABEL:
                committed[key] = c.hostname
                continue
            g = self.topo._group(tsc)
            d_star = min(inter, key=lambda d: (g.get(d, 0), d))
            if len(node_domains) > 1 or node_domains[0] != d_star:
                if not c.narrow(key, {d_star}):
                    return False, {}
            committed[key] = d_star
        ok, aff_committed = self._affinity_admits(
            pod,
            {
                k: set(c.domain_values(k, self.topo.universe(k)))
                for k in (wk.ZONE_LABEL, wk.CAPACITY_TYPE_LABEL, wk.HOSTNAME_LABEL)
            },
            fixed=False,
            claim=c,
        )
        if not ok:
            return False, {}
        committed.update(aff_committed)
        # fill in remaining single-valued domains for bookkeeping
        for key in (wk.ZONE_LABEL, wk.CAPACITY_TYPE_LABEL):
            if key in committed:
                continue
            vals = c.domain_values(key, self.topo.universe(key))
            if len(vals) == 1:
                committed[key] = vals[0]
        return True, committed

    def _affinity_admits(
        self,
        pod: Pod,
        node_domains: Mapping[str, set],
        fixed: bool,
        claim: Optional[VirtualNode] = None,
    ) -> Tuple[bool, Dict[str, str]]:
        committed: Dict[str, str] = {}
        # claim-local symmetry: a pod matching an anti term OWNED by a pod
        # already on this claim may not join it (same claim ⇒ same domain)
        if claim is not None:
            for sel_sig, _key in claim.anti_sigs:
                if _matches(dict(sel_sig), pod.meta.labels):
                    return False, {}
        # symmetric anti-affinity from placed pods
        for key, blocked in self.topo.symmetric_anti_blocked(pod.meta.labels).items():
            doms = node_domains.get(key)
            if doms is None:
                continue
            remaining = doms - blocked
            if not remaining:
                return False, {}
            if not fixed and len(doms) > len(remaining) and key != wk.HOSTNAME_LABEL and claim is not None:
                if not claim.narrow(key, remaining):
                    return False, {}
                node_domains = dict(node_domains)
                node_domains[key] = remaining
        for term in pod.affinity_terms:
            if term.weight is not None:
                continue  # preferred: relaxation handles
            key = term.topology_key
            doms = set(node_domains.get(key, set()))
            if not doms:
                return False, {}
            claim_local = claim is not None and key != wk.HOSTNAME_LABEL and any(
                _matches(term.label_selector, pl) for pl in claim.pod_label_list
            )
            match = self.topo.affinity_domains(term.label_selector, key)
            if term.anti:
                if claim_local:
                    return False, {}  # matching pod co-located on this claim
                # joint blocked set: this term, the pod's other anti terms,
                # and symmetric blocks — the committed domain must satisfy all
                # (and any positive present-set restriction on the same key)
                blocked = self._anti_blocked_domains(pod, key)
                remaining = doms - blocked
                aff_r = self._affinity_present_restriction(pod, key, claim)
                if aff_r is not None:
                    remaining = remaining & aff_r
                if not remaining:
                    return False, {}
                if not fixed and key != wk.HOSTNAME_LABEL and claim is not None and len(doms) > 1:
                    # an owned anti term COMMITS the claim to one domain —
                    # leaving it multi-valued would let two claims later
                    # materialize in the same zone and violate the term
                    # (SPEC.md: anti commits like spread; lex-first allowed)
                    d_star = min(remaining)
                    if not claim.narrow(key, {d_star}):
                        return False, {}
                    committed[key] = d_star
                    node_domains = dict(node_domains)
                    node_domains[key] = {d_star}
            elif claim_local:
                continue  # co-located matching pod satisfies the term
            else:
                present = {d for d, cnt in match.items() if cnt > 0}
                if not present:
                    # self-affinity bootstrap
                    if _matches(term.label_selector, pod.meta.labels):
                        continue
                    return False, {}
                inter = doms & present
                # joint with the pod's OTHER positive terms on this key, so
                # the committed domain satisfies all of them at once
                aff_r = self._affinity_present_restriction(pod, key, claim)
                if aff_r is not None:
                    inter = inter & aff_r
                if not inter:
                    return False, {}
                d_star = min(inter, key=lambda d: (-match.get(d, 0), d))
                if not fixed and key != wk.HOSTNAME_LABEL and claim is not None and len(doms) > 1:
                    if not claim.narrow(key, {d_star}):
                        return False, {}
                    committed[key] = d_star
                    node_domains = dict(node_domains)
                    node_domains[key] = {d_star}
        return True, committed

    # -- limits -------------------------------------------------------------

    def _limits_exceeded(self, pool: NodePoolSpec) -> bool:
        if not pool.limits:
            return False
        usage = self.pool_usage[pool.name]
        return any(usage.get(k, 0) >= v for k, v in pool.limits.items())

    def _charge_pool(self, pool: NodePoolSpec, c: VirtualNode) -> None:
        """Charge the minimum resources among surviving options (SPEC.md)."""
        if not c.options:
            return
        mins = Resources()
        for key in ("cpu", "memory"):
            mins[key] = min(it.capacity.get_(key) for it in c.options)
        self.pool_usage[pool.name] = self.pool_usage[pool.name].add(mins)


def solve(inp: SolverInput) -> SolverResult:
    return Scheduler(inp).solve()
