"""Provisioner: pending pods -> solver -> NodeClaims.

The main loop of SURVEY.md §3.1: batch pending pods (idle/max windows,
settings.md:15-16 — defaults 1s/10s, 0 in tests), assemble the SolverInput
from cluster state + NodePools + ICE-masked instance types, run the pluggable
Solver backend (TPU or reference), then create NodeClaim objects; the
lifecycle launch controller turns claims into cloud capacity asynchronously
(NodeClaim state machine, concepts/nodeclaims.md).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ..api import wellknown as wk
from ..api.objects import NodeClaim, NodePool, ObjectMeta, Pod
from ..cloudprovider.types import CloudProvider
from ..controllers import store as st
from ..metrics.registry import (
    PODS_UNSCHEDULABLE,
    PROVISIONER_SCHEDULING_DURATION,
    SCHEDULER_QUEUE_DEPTH,
)
from ..obs import trace as obstrace
from ..scheduling.requirements import IN, Requirement
from ..solver.backend import Solver
from ..state.cluster import Cluster
from .scheduler import NodePoolSpec, SolverInput


class Provisioner:
    name = "provisioner"

    def __init__(
        self,
        store: st.Store,
        cluster: Cluster,
        cloud_provider: CloudProvider,
        solver: Solver,
        batch_idle_s: float = 1.0,
        batch_max_s: float = 10.0,
        clock=time.monotonic,
        preference_policy: str = "Respect",
        solve_service=None,
        preemption=None,
        recorder=None,
        streaming=None,
    ):
        self.store = store
        self.cluster = cluster
        self.cloud_provider = cloud_provider
        self.solver = solver
        self.batch_idle_s = batch_idle_s
        self.batch_max_s = batch_max_s
        self.clock = clock
        self.preference_policy = preference_policy  # settings.md:38
        # pipelined device owner (solver/pipeline.py): solves queue through
        # it so provisioning snapshots coalesce and interleave fairly with
        # disruption probes; None = call the solver seam directly
        self._solve_service = solve_service
        # scheduling-class outputs (solver/scheduling_class.py): planned
        # evictions hand off to the PreemptionController; gang verdicts and
        # preemptions surface as pod events through the recorder
        self._preemption = preemption
        self._recorder = recorder
        # streaming delta-solve (solver/streaming.py, --solver-streaming):
        # when set, reconcile folds journal event batches into the resident
        # model and assembles the solve input from it instead of scanning
        # the store — decision-identical, event-rate-proportional
        self._streaming = streaming
        self._first_seen: Optional[float] = None
        self._last_count = 0
        self._claim_seq = 0

    # -- batching (settings.md:15-16) ---------------------------------------

    def _batch_ready(self, pending: List[Pod]) -> bool:
        now = self.clock()
        if not pending:
            self._first_seen = None
            self._last_count = 0
            return False
        if self._first_seen is None:
            self._first_seen = now
            self._last_count = len(pending)
            self._idle_since = now
            return self.batch_idle_s == 0
        if len(pending) != self._last_count:
            self._last_count = len(pending)
            self._idle_since = now
        return (now - self._idle_since) >= self.batch_idle_s or (
            now - self._first_seen
        ) >= self.batch_max_s

    # -- input assembly -----------------------------------------------------

    def build_input(self, pending: List[Pod]) -> SolverInput:
        usage = self.cluster.nodepool_usage()
        pools: List[NodePoolSpec] = []
        zones: set = set()
        cts: set = set()
        for np_obj in self.store.list(st.NODEPOOLS):
            if np_obj.meta.deleting:
                continue
            types = self.cloud_provider.get_instance_types(np_obj.name)
            reqs = np_obj.scheduling_requirements()
            pools.append(
                NodePoolSpec(
                    name=np_obj.name,
                    weight=np_obj.weight,
                    requirements=reqs,
                    taints=list(np_obj.template.taints),
                    instance_types=types,
                    limits=np_obj.limits,
                    usage=usage.get(np_obj.name, type(np_obj.limits)()),
                    solver_backend=np_obj.meta.labels.get(
                        wk.SOLVER_BACKEND_LABEL
                    ),
                )
            )
            for it in types:
                zr = it.requirements.get(wk.ZONE_LABEL)
                if zr:
                    zones.update(zr.values_list())
                cr = it.requirements.get(wk.CAPACITY_TYPE_LABEL)
                if cr:
                    cts.update(cr.values_list())
        daemonsets = [d for d in self.store.list(st.DAEMONSETS)]
        # Encode-cache stamp: (tracker, (store catalog rev, provider catalog
        # token), pods rev, nodes rev). Store events alone don't cover
        # ICE/reservation masking (the provider re-masks with no store
        # event), so without a provider token the stamp stays None and the
        # encoder does the full catalog-key compare instead.
        state_rev = None
        deltas = getattr(self.cluster, "encode_deltas", None)
        tok_fn = getattr(self.cloud_provider, "catalog_token", None)
        if deltas is not None and callable(tok_fn):
            tok = tok_fn()
            if tok is not None:
                tracker, crev, prev, nrev = deltas.snapshot()
                state_rev = (tracker, (crev, tok), prev, nrev)
        return SolverInput(
            pods=pending,
            nodes=self.cluster.existing_nodes_for_scheduler(),
            nodepools=pools,
            daemonset_pods=daemonsets,
            zones=tuple(sorted(zones)),
            capacity_types=tuple(sorted(cts)) or ("on-demand", "spot"),
            preference_policy=self.preference_policy,
            state_rev=state_rev,
        )

    def _nodepools(self) -> Dict[str, NodePool]:
        """Name-keyed NodePool snapshot, fetched once per solve alongside the
        in-flight handle (the claim-creation loop and the oracle-replay path
        both key replacements off it)."""
        return {p.name: p for p in self.store.list(st.NODEPOOLS)}

    def _next_claim_name(self, nodepool: str, suffix: str = "") -> str:
        """Store-aware name allocation: a freshly-promoted HA standby (or a
        restart) must not collide with claims the previous leader created."""
        while True:
            self._claim_seq += 1
            name = f"{nodepool}-{suffix}{self._claim_seq:05d}"
            if self.store.try_get(st.NODECLAIMS, name) is None:
                return name

    # -- reconcile ----------------------------------------------------------

    def reconcile(self) -> bool:
        journal_seq = None
        if self._streaming is not None:
            journal_seq = self._streaming.pump()
            pending = self._streaming.pending_pods()
        else:
            pending = self.cluster.pending_pods()
        SCHEDULER_QUEUE_DEPTH.set(len(pending))
        PODS_UNSCHEDULABLE.set(float(len(pending)), state="pending")
        if not self._batch_ready(pending):
            return False
        self._first_seen = None
        t0 = time.perf_counter()
        # mint the solve's trace HERE — the provisioner is the top of the
        # span tree; the service/fleet/backend layers below adopt it
        _tr = obstrace.begin("provisioning")
        # streamed solves have no snapshot boundary: the journal seq of the
        # newest folded event batch IS the solve's identity (obs/explain,
        # flight-recorder dumps key on it)
        obstrace.set_journal(_tr, journal_seq)
        with obstrace.attached(_tr):
            obstrace.annotate(pending_pods=len(pending))
            with obstrace.span("provision.build_input"):
                inp = (
                    self._streaming.build_input(pending)
                    if self._streaming is not None
                    else self.build_input(pending)
                )
        try:
            if self._solve_service is not None:
                # pipelined path: the service owns the device — this snapshot
                # queues behind (and fairly interleaves with) disruption
                # probes, and a newer snapshot submitted while this one is
                # still queued supersedes it (Superseded below)
                with obstrace.attached(_tr):
                    ticket = self._solve_service.submit(
                        inp, kind="provisioning", rev=inp.state_rev
                    )
                nodepools = self._nodepools()
                result = ticket.result()
            else:
                solve_async = getattr(self.solver, "solve_async", None)
                if solve_async is not None:
                    # async seam: kernel + link transfer run while the
                    # claim-creation lookups below are prepared on host
                    # (backend.AsyncSolve)
                    with obstrace.attached(_tr):
                        handle = solve_async(inp)
                        nodepools = self._nodepools()
                        result = handle.result()
                else:
                    with obstrace.attached(_tr):
                        result = self.solver.solve(inp)
                    nodepools = self._nodepools()
        except Exception as e:
            from ..solver.pipeline import Superseded

            if isinstance(e, Superseded):
                # a newer cluster snapshot's solve covers this batch; acting
                # on the stale result would double-provision — defer and let
                # the next tick pick up whatever that solve leaves pending
                obstrace.finish(_tr, "superseded")
                return False
            # a solver exception must degrade, not abort the batch: the
            # configured solver (even ResilientSolver, if its whole chain is
            # exhausted) gets one last replay on the python oracle so the
            # pending pods still make progress this tick; a second failure
            # defers the batch to the next tick instead of crash-looping the
            # manager at full rate
            import logging

            from ..metrics.registry import SOLVER_FALLBACK
            from ..solver.backend import ReferenceSolver

            SOLVER_FALLBACK.inc(reason="solver_exception")
            logging.getLogger("karpenter_tpu").exception(
                "solver failed beyond its fallback chain (%s) — replaying "
                "batch on the reference oracle", e,
            )
            try:
                with obstrace.attached(_tr), obstrace.span("provision.oracle_replay"):
                    result = ReferenceSolver().solve(inp)
            except Exception:
                logging.getLogger("karpenter_tpu").exception(
                    "oracle replay failed too; deferring batch to next tick"
                )
                obstrace.finish(_tr, "error")
                return False
            obstrace.finish(_tr, "oracle_replay")
            _tr = None  # already finished
            nodepools = self._nodepools()
        obstrace.finish(_tr, "ok")
        PROVISIONER_SCHEDULING_DURATION.observe(time.perf_counter() - t0)
        did = False
        # gang membership: claims carrying a gang member batch all-or-nothing
        # — a rejected claim rolls back the gang's already-created siblings
        # (deleted before launch; the termination path GCs them) instead of
        # leaving the gang half-provisioned
        gang_of = {
            p.meta.uid: p.gang()[0] for p in pending if p.gang() is not None
        }
        gang_claims: Dict[str, List[str]] = {}
        failed_gangs: set = set()
        for claim_res in result.claims:
            np_obj = nodepools.get(claim_res.nodepool)
            if np_obj is None:
                continue
            claim_gangs = {
                gang_of[uid] for uid in claim_res.pod_uids if uid in gang_of
            }
            if claim_gangs & failed_gangs:
                continue
            name = self._next_claim_name(claim_res.nodepool)
            reqs = type(claim_res.requirements)(claim_res.requirements)
            reqs.add(
                Requirement.create(
                    wk.INSTANCE_TYPE_LABEL, IN, claim_res.instance_type_names
                )
            )
            annotations = {}
            from ..controllers.nodeclass import nodepool_static_hash

            annotations[wk.NODEPOOL_HASH_ANNOTATION] = nodepool_static_hash(np_obj)
            nc = self.store.try_get(st.NODECLASSES, np_obj.template.node_class_ref)
            if nc is not None:
                annotations[wk.NODECLASS_HASH_ANNOTATION] = nc.static_hash()
            claim = NodeClaim(
                meta=ObjectMeta(
                    name=name,
                    labels={wk.NODEPOOL_LABEL: claim_res.nodepool},
                    annotations=annotations,
                    finalizers=[wk.TERMINATION_FINALIZER],
                    # stamp from the injected clock, not the wall default:
                    # GC grace and disruption age math compare against
                    # self.clock(), which may be a sim clock
                    creation_timestamp=self.clock(),
                ),
                nodepool=claim_res.nodepool,
                node_class_ref=np_obj.template.node_class_ref,
                requirements=reqs,
                resource_requests=claim_res.requests,
                taints=list(np_obj.template.taints),
                startup_taints=list(np_obj.template.startup_taints),
                expire_after_s=np_obj.template.expire_after_s,
                termination_grace_period_s=np_obj.template.termination_grace_period_s,
                instance_type_options=list(claim_res.instance_type_names),
            )
            try:
                self.store.create(st.NODECLAIMS, claim)
            except Exception as e:
                # per-claim isolation (the reference handles create errors
                # per NodeClaim): one rejected claim (admission/conflict)
                # must not starve the rest of the batch or the nominations
                import logging

                logging.getLogger("karpenter_tpu").warning(
                    "nodeclaim %s rejected: %s", name, e
                )
                if claim_gangs:
                    # all-or-nothing: strike the gangs this claim carried and
                    # delete their already-created sibling claims
                    failed_gangs |= claim_gangs
                    for gid in claim_gangs:
                        for sib in gang_claims.pop(gid, []):
                            try:
                                self.store.delete(st.NODECLAIMS, sib)
                            except Exception:
                                pass
                continue
            for gid in claim_gangs:
                gang_claims.setdefault(gid, []).append(name)
            did = True
        for uid, placement in result.placements.items():
            if placement[0] == "node":
                self.cluster.nominate(placement[1])
        # scheduling-class handoff: evictions execute through the preemption
        # controller; gang verdicts surface as pod events
        if result.evictions and self._preemption is not None:
            self._preemption.submit(result.evictions)
        unplaced_gangs = set(result.gangs_unschedulable) | failed_gangs
        if unplaced_gangs and self._recorder is not None:
            from ..events import recorder as ev

            for p in pending:
                g = p.gang()
                if g is not None and g[0] in unplaced_gangs:
                    self._recorder.publish(
                        ev.gang_unschedulable(p.meta.name, g[0])
                    )
        return did
