"""Preemption executor: planned evictions -> pod evictions.

The solver PLANS preemptions (solver/scheduling_class.py emits
SolverResult.evictions — victim uid, node, and the pending pod the capacity
is for); this controller EXECUTES them through the same store/binder path
every other pod transition takes: the victim is unbound (node_name cleared,
phase back to Pending) and a Preempted event records why. The freed capacity
shows up in cluster state on the next snapshot, the pending pod lands there
on a later provisioner/binder reconcile, and the victim re-queues as an
ordinary pending pod — exactly Kubernetes' asynchronous preemption shape
(convergence over reconciles, not within one solve).

Stale plans drop harmlessly: an eviction row is executed only if the victim
is still bound to the planned node and still strictly lower priority than
the pod it yields to (the world may have moved between solve and execute —
the pod finished, moved, or priorities changed). Dropped rows are not
retried; the next solve re-plans against current state.
"""

from __future__ import annotations

import logging
from typing import List, Optional

from ..controllers import store as st
from ..events import recorder as ev
from ..provisioning.scheduler import Eviction

log = logging.getLogger("karpenter_tpu")


class PreemptionController:
    name = "preemption"

    def __init__(self, store: st.Store, recorder: Optional[ev.Recorder] = None):
        self.store = store
        self.recorder = recorder
        self._queue: List[Eviction] = []
        self.executed = 0
        self.dropped_stale = 0

    def submit(self, evictions: List[Eviction]) -> None:
        """Queue a solve's planned evictions for the next reconcile tick."""
        self._queue.extend(evictions)

    def reconcile(self) -> bool:
        if not self._queue:
            return False
        plan, self._queue = self._queue, []
        by_uid = {p.meta.uid: p for p in self.store.list(st.PODS)}
        preemptors = by_uid  # pending pods live in the same table
        did = False
        for row in plan:
            victim = by_uid.get(row.pod_uid)
            if (
                victim is None
                or victim.node_name != row.node_id
                or victim.meta.deleting
            ):
                self.dropped_stale += 1
                continue
            beneficiary = preemptors.get(row.for_pod)
            if beneficiary is not None and beneficiary.priority <= victim.priority:
                # priorities moved since the plan: no longer a preemption
                self.dropped_stale += 1
                continue
            victim.node_name = None
            victim.phase = "Pending"
            self.store.update(st.PODS, victim)
            self.executed += 1
            did = True
            if self.recorder is not None:
                self.recorder.publish(
                    ev.preempted(victim.meta.name, row.node_id, row.for_pod)
                )
            log.info(
                "preempted pod %s from %s for higher-priority pod %s",
                victim.meta.name, row.node_id, row.for_pod,
            )
        return did
