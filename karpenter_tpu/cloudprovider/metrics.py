"""CloudProvider metrics decorator (cloudprovider/metrics decorator,
cmd/controller/main.go:42; metrics.md:298-322): every CloudProvider call is
wrapped with a duration histogram and an error counter, without the
provider implementation knowing."""

from __future__ import annotations

import time

from ..metrics.registry import CLOUDPROVIDER_DURATION, CLOUDPROVIDER_ERRORS

_WRAPPED = (
    "create",
    "delete",
    "get",
    "list",
    "get_instance_types",
    "is_drifted",
    "repair_policies",
)


class MeteredCloudProvider:
    """Delegating proxy: metrics.Decorate analog."""

    def __init__(self, inner):
        self._inner = inner

    def __getattr__(self, name):
        attr = getattr(self._inner, name)
        if name not in _WRAPPED or not callable(attr):
            return attr

        def wrapped(*args, **kwargs):
            t0 = time.perf_counter()
            try:
                return attr(*args, **kwargs)
            except Exception as e:
                CLOUDPROVIDER_ERRORS.inc(method=name, error=type(e).__name__)
                raise
            finally:
                CLOUDPROVIDER_DURATION.observe(time.perf_counter() - t0, method=name)

        return wrapped


def decorate(cloud_provider) -> MeteredCloudProvider:
    return MeteredCloudProvider(cloud_provider)
