"""CloudProvider contract: InstanceType / Offering model + typed errors.

Behavioral mirror of karpenter core `pkg/cloudprovider` as implemented by the
reference at pkg/cloudprovider/cloudprovider.go:56-305 (SURVEY.md §2.1/§2.3):

  InstanceType{Name, Requirements, Offerings, Capacity, Overhead}
  Offering{Requirements, Price, Available, ReservationCapacity}
  typed errors: InsufficientCapacityError, NodeClaimNotFoundError,
                CreateError, NodeClassNotReadyError
  InstanceTypes.Truncate (pkg/providers/instance/instance.go:260)
"""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence

from ..api import wellknown as wk
from ..api.objects import NodeClaim, Taint
from ..scheduling.requirements import IN, Requirement, Requirements
from ..utils import resources as res
from ..utils.resources import Resources


@dataclass
class Offering:
    """One (instance-type, zone, capacity-type) purchasable unit."""

    zone: str
    capacity_type: str  # on-demand | spot | reserved
    price: float
    available: bool = True
    reservation_capacity: int = 0  # for capacity_type == reserved
    reservation_id: str = ""

    def requirements(self) -> Requirements:
        return Requirements.of(
            Requirement.create(wk.ZONE_LABEL, IN, [self.zone]),
            Requirement.create(wk.CAPACITY_TYPE_LABEL, IN, [self.capacity_type]),
        )


@dataclass
class InstanceType:
    name: str
    # The label universe this type offers (arch, os, zone set, capacity types,
    # cpu, memory-mib, family, size, ... ~25 keys in the reference,
    # pkg/providers/instancetype/types.go:158-284).
    requirements: Requirements
    capacity: Resources
    overhead: Resources  # kube-reserved + system-reserved + eviction threshold
    offerings: List[Offering] = field(default_factory=list)

    def allocatable(self) -> Resources:
        # fresh copy: callers assign the result onto claims and must never
        # share (and risk mutating) the memoized instance
        return Resources(self.allocatable_view())

    def allocatable_view(self) -> Resources:
        """READ-ONLY view of allocatable() (no defensive copy) — for hot
        fit checks that never mutate (the oracle probes this per
        (claim, type); copying dominated the memo win). Memoized per
        (capacity, overhead) OBJECT identity — the memo pins both objects so
        a swapped-in replacement can never alias a freed id (the
        _QUANTIZED_TYPE_CACHE `is`-check discipline)."""
        cached = getattr(self, "_alloc_memo", None)
        if (
            cached is None
            or cached[0] is not self.capacity
            or cached[1] is not self.overhead
        ):
            out = self.capacity.sub(self.overhead)
            cached = (
                self.capacity,
                self.overhead,
                Resources({k: max(0, v) for k, v in out.items()}),
            )
            self._alloc_memo = cached
        return cached[2]

    def cheapest_available(self, reqs: Optional[Requirements] = None) -> Optional[Offering]:
        best = None
        for o in self.offerings:
            if not o.available:
                continue
            if reqs is not None and not reqs.compatible(o.requirements()):
                continue
            if best is None or o.price < best.price:
                best = o
        return best

    def available(self, reqs: Optional[Requirements] = None) -> bool:
        return self.cheapest_available(reqs) is not None


def truncate(
    instance_types: Sequence[InstanceType],
    reqs: Requirements,
    max_items: int = 60,
) -> List[InstanceType]:
    """Order by cheapest compatible offering price ascending and keep the first
    `max_items` — the launch-path truncation at
    pkg/providers/instance/instance.go:60,260.

    Raises ValueError if truncation would violate a minValues floor, matching
    the reference's minValues enforcement during truncation.
    """
    def key(it: InstanceType) -> float:
        o = it.cheapest_available(reqs)
        return o.price if o else float("inf")

    ordered = sorted(instance_types, key=lambda it: (key(it), it.name))
    kept = ordered[:max_items]
    if reqs.has_min_values():
        _check_min_values(kept, reqs)
    return kept


def _check_min_values(instance_types: Sequence[InstanceType], reqs: Requirements) -> None:
    for k, r in reqs.items():
        if not r.min_values:
            continue
        domain = set()
        for it in instance_types:
            itr = it.requirements.get(k)
            if itr is not None and not itr.complement:
                domain |= set(itr.values_list())
        if len(domain) < r.min_values:
            raise ValueError(
                f"minValues violation: key {k} has {len(domain)} values, needs {r.min_values}"
            )


# ---------------------------------------------------------------------------
# Typed errors (cloudprovider.go:96,104,107)
# ---------------------------------------------------------------------------


class CloudProviderError(Exception):
    pass


class InsufficientCapacityError(CloudProviderError):
    """All attempted offerings were unavailable (ICE)."""

    def __init__(self, message: str, offerings: Sequence[tuple] = ()):  # (instance_type, zone, capacity_type)
        super().__init__(message)
        self.offerings = list(offerings)


class NodeClaimNotFoundError(CloudProviderError):
    pass


class NodeClassNotReadyError(CloudProviderError):
    pass


class CreateError(CloudProviderError):
    pass


# ---------------------------------------------------------------------------
# Provider interface (cloudprovider.go:56-305)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class RepairPolicy:
    """Node condition + toleration duration after which the node is replaced
    (cloudprovider.go:264-305)."""

    condition_type: str
    condition_status: str
    toleration_duration_s: float


class CloudProvider(abc.ABC):
    @abc.abstractmethod
    def create(self, node_claim: NodeClaim) -> NodeClaim:
        """Launch capacity for the claim; returns the claim with status
        (provider_id, instance_type, zone, capacity_type, capacity) filled."""

    @abc.abstractmethod
    def delete(self, node_claim: NodeClaim) -> None:
        ...

    @abc.abstractmethod
    def get(self, provider_id: str) -> NodeClaim:
        ...

    @abc.abstractmethod
    def list(self) -> List[NodeClaim]:
        ...

    @abc.abstractmethod
    def get_instance_types(self, nodepool_name: str) -> List[InstanceType]:
        ...

    def catalog_token(self) -> Optional[tuple]:
        """Change token for the instance-type catalog beyond store events
        (ICE masking, reservations, discovered capacity). None means the
        provider cannot prove catalog stability — the provisioner then skips
        the encode-cache state_rev stamp and the encoder compares catalog
        keys in full (always safe, just slower)."""
        return None

    def is_drifted(self, node_claim: NodeClaim) -> Optional[str]:
        return None

    def repair_policies(self) -> List[RepairPolicy]:
        return [
            RepairPolicy("Ready", "False", 30 * 60),
            RepairPolicy("Ready", "Unknown", 30 * 60),
        ]
