"""Synthetic instance-type catalog.

The reference ships ~800 EC2 instance types discovered live plus generated
static price/bandwidth/vpc-limit tables (SURVEY.md §2.2 instancetype, §2.11
codegen). For hermetic operation we *generate* a deterministic EC2-shaped
catalog instead: families × sizes with per-family price curves, zonal spot
discounts, accelerator families, and kube-reserved/eviction overhead formulas
mirroring pkg/providers/instancetype/types.go:453-546 behaviorally.

Nothing here is copied from the reference's generated data; the generator is
seeded and pure so every run (and both solver backends) see identical inputs.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
import re

from typing import Dict, List, Optional, Sequence

from ..api import wellknown as wk
from ..cloudprovider.types import InstanceType, Offering
from ..scheduling.requirements import IN, Requirement, Requirements
from ..utils import resources as res
from ..utils.resources import Resources

GIB = 1024**3
MIB = 1024**2

# family -> (vcpu:mem-GiB ratio, $/vcpu-hr OD base, arch, accelerator per 8xl)
_FAMILIES = [
    # general purpose
    ("m5", 4, 0.048, "amd64", None),
    ("m5a", 4, 0.043, "amd64", None),
    ("m6i", 4, 0.048, "amd64", None),
    ("m6g", 4, 0.0385, "arm64", None),
    ("m7i", 4, 0.0504, "amd64", None),
    ("m7g", 4, 0.0408, "arm64", None),
    # compute optimized
    ("c5", 2, 0.0425, "amd64", None),
    ("c5a", 2, 0.0385, "amd64", None),
    ("c6i", 2, 0.0425, "amd64", None),
    ("c6g", 2, 0.034, "arm64", None),
    ("c7i", 2, 0.04463, "amd64", None),
    ("c7g", 2, 0.0363, "arm64", None),
    # memory optimized
    ("r5", 8, 0.063, "amd64", None),
    ("r5a", 8, 0.0565, "amd64", None),
    ("r6i", 8, 0.063, "amd64", None),
    ("r6g", 8, 0.0504, "arm64", None),
    ("r7i", 8, 0.06615, "amd64", None),
    ("r7g", 8, 0.05355, "arm64", None),
    # high memory
    ("x2gd", 16, 0.0835, "arm64", None),
    ("z1d", 8, 0.093, "amd64", None),
    # burstable
    ("t3", 4, 0.0416, "amd64", None),
    ("t3a", 4, 0.0376, "amd64", None),
    ("t4g", 4, 0.0336, "arm64", None),
    # AMD 3rd/4th-gen line
    ("m6a", 4, 0.0432, "amd64", None),
    ("c6a", 2, 0.0383, "amd64", None),
    ("r6a", 8, 0.0567, "amd64", None),
    ("m7a", 4, 0.05796, "amd64", None),
    ("c7a", 2, 0.05133, "amd64", None),
    ("r7a", 8, 0.07607, "amd64", None),
    # graviton 4
    ("c8g", 2, 0.03987, "arm64", None),
    # storage optimized
    ("i3", 8, 0.078, "amd64", None),
    ("i3en", 8, 0.1092, "amd64", None),
    ("i4i", 8, 0.0858, "amd64", None),
    ("im4gn", 6, 0.091, "arm64", None),
    ("d3", 8, 0.0624, "amd64", None),
    # high memory network/storage
    ("x2iedn", 32, 0.1668, "amd64", None),
    # accelerated
    ("g4dn", 8, 0.1578, "amd64", ("nvidia.com/gpu", 1)),
    ("g5", 8, 0.1512, "amd64", ("nvidia.com/gpu", 1)),
    ("p3", 8, 0.3825, "amd64", ("nvidia.com/gpu", 4)),
    ("p4d", 12, 0.3410, "amd64", ("nvidia.com/gpu", 8)),
    ("inf1", 8, 0.057, "amd64", ("aws.amazon.com/neuron", 4)),
    ("trn1", 16, 0.4169, "amd64", ("aws.amazon.com/neuron", 8)),
    ("dl1", 24, 0.1277, "amd64", ("habana.ai/gaudi", 8)),
]

# Variant suffixes applied to mainstream families, shaped like EC2's d (local
# NVMe), n (network-optimized), and dn combos — expands the catalog to the
# reference's ~700-type scale (726+ with the round-4 families).
_VARIANTS = [
    ("d", 1.06, {"m5", "m6i", "m6g", "c5", "c6i", "c6g", "r5", "r6i", "r6g", "i3", "z1d"}),
    ("n", 1.12, {"m5", "c5", "r5", "c6g", "m6i", "c6i"}),
    ("dn", 1.18, {"m5", "c5", "r5"}),
    ("b", 1.04, {"r5", "m5"}),
    ("zn", 1.32, {"m5"}),
]


def _expanded_families():
    fams = list(_FAMILIES)
    base = {f[0]: f for f in _FAMILIES}
    for suffix, markup, members in _VARIANTS:
        for fam in sorted(members):
            name, ratio, price, arch, accel = base[fam]
            variant = f"{name}{suffix}"
            if any(f[0] == variant for f in fams):
                continue
            fams.append((variant, ratio, round(price * markup, 6), arch, accel))
    return fams

# size suffix -> vcpu count
_SIZES = [
    ("medium", 1),
    ("large", 2),
    ("xlarge", 4),
    ("2xlarge", 8),
    ("4xlarge", 16),
    ("8xlarge", 32),
    ("12xlarge", 48),
    ("16xlarge", 64),
    ("24xlarge", 96),
    ("32xlarge", 128),
    ("48xlarge", 192),
    ("metal", 96),
]

_BURSTABLE = {"t3", "t3a", "t4g"}  # name-prefix tests would eat trn1 too

_GPU_SIZES = {"xlarge", "2xlarge", "4xlarge", "8xlarge", "12xlarge", "16xlarge", "24xlarge", "48xlarge"}

DEFAULT_ZONES = ("zone-1a", "zone-1b", "zone-1c")


def _h(s: str) -> float:
    """Deterministic hash -> [0,1)."""
    return int(hashlib.sha256(s.encode()).hexdigest()[:8], 16) / 0xFFFFFFFF


def _max_pods(vcpus: int) -> int:
    """ENI-limited pod density, shaped like types.go:453-467's formula."""
    if vcpus <= 2:
        return 29
    if vcpus <= 4:
        return 58
    if vcpus <= 16:
        return 110
    return 234


def _kube_reserved_cpu_milli(vcpus: int) -> int:
    """Banded CPU reservation (types.go:484-517): 6% of first core, 1% of the
    next, 0.5% of the next two, 0.25% of the rest."""
    cores = vcpus
    milli = 0
    bands = [(1, 60), (1, 10), (2, 5), (cores, 2.5)]
    remaining = cores
    for width, per_core_milli in bands:
        take = min(remaining, width)
        if take <= 0:
            break
        milli += int(take * per_core_milli)
        remaining -= take
    return milli


def _kube_reserved_memory(pods: int) -> int:
    """255Mi + 11Mi per pod (the reference's max-pods-based formula)."""
    return (255 + 11 * pods) * MIB


def _eviction_threshold() -> int:
    """100Mi hard eviction threshold (types.go:519-546 default)."""
    return 100 * MIB


@dataclass(frozen=True)
class CatalogSpec:
    zones: Sequence[str] = DEFAULT_ZONES
    spot: bool = True
    vm_memory_overhead_percent: float = 0.075  # settings.md / options.go:36-56


def generate(spec: CatalogSpec = CatalogSpec()) -> List[InstanceType]:
    """Build the full deterministic catalog (~730 instance types)."""
    out: List[InstanceType] = []
    for family, ratio, per_vcpu, arch, accel in _expanded_families():
        for size, vcpus in _SIZES:
            if accel and size not in _GPU_SIZES:
                continue
            if family in _BURSTABLE and vcpus > 8:
                continue  # burstable families stop at 2xlarge
            if family in ("p3", "p4d", "trn1", "dl1") and vcpus < 16:
                continue
            name = f"{family}.{size}"
            mem_gib = vcpus * ratio
            # VM overhead: the hypervisor + CMA carve-out the reference models
            # with vm-memory-overhead-percent (instancetype.go:320-344 learns
            # the true value; we apply the configured percent).
            mem_bytes = int(mem_gib * GIB * (1 - spec.vm_memory_overhead_percent))
            pods = _max_pods(vcpus)
            capacity = Resources(
                {
                    res.CPU: vcpus * 1000,
                    res.MEMORY: mem_bytes,
                    res.EPHEMERAL_STORAGE: 50 * GIB,
                    res.PODS: pods,
                }
            )
            if accel:
                accel_name, per_8xl = accel
                count = max(1, (vcpus // 32) * per_8xl)
                capacity[accel_name] = count
            overhead = Resources(
                {
                    res.CPU: _kube_reserved_cpu_milli(vcpus),
                    res.MEMORY: _kube_reserved_memory(pods) + _eviction_threshold(),
                }
            )
            od_price = round(per_vcpu * vcpus * (1.0 + 0.03 * _h(name)), 5)
            offerings: List[Offering] = []
            for zone in spec.zones:
                offerings.append(Offering(zone=zone, capacity_type=wk.CAPACITY_TYPE_ON_DEMAND, price=od_price))
                if spec.spot and family not in _BURSTABLE:
                    discount = 0.55 + 0.25 * _h(f"{name}/{zone}")  # 55-80% off-ish band
                    offerings.append(
                        Offering(
                            zone=zone,
                            capacity_type=wk.CAPACITY_TYPE_SPOT,
                            price=round(od_price * (1 - discount), 5),
                        )
                    )
            m_gen = re.search(r"\d", family)
            generation = int(m_gen.group()) if m_gen else 0
            reqs = Requirements.of(
                Requirement.create("karpenter.tpu/instance-cpu", IN, [str(vcpus * 1000)]),
                Requirement.create("karpenter.tpu/instance-memory-mib", IN, [str(mem_bytes // MIB)]),
                Requirement.create("karpenter.tpu/instance-family", IN, [family]),
                Requirement.create("karpenter.tpu/instance-size", IN, [size]),
                Requirement.create("karpenter.tpu/instance-generation", IN, [str(generation)]),
                Requirement.create("karpenter.tpu/instance-category", IN, [family[0]]),
                Requirement.create(wk.INSTANCE_TYPE_LABEL, IN, [name]),
                Requirement.create(wk.ARCH_LABEL, IN, [arch]),
                Requirement.create(wk.OS_LABEL, IN, ["linux"]),
                Requirement.create(wk.ZONE_LABEL, IN, sorted({o.zone for o in offerings})),
                Requirement.create(
                    wk.CAPACITY_TYPE_LABEL, IN, sorted({o.capacity_type for o in offerings})
                ),
            )
            if accel:
                reqs.add(Requirement.create("karpenter.tpu/instance-accelerator", IN, [accel[0]]))
            out.append(
                InstanceType(
                    name=name,
                    requirements=reqs,
                    capacity=capacity,
                    overhead=overhead,
                    offerings=offerings,
                )
            )
    return out
