"""Requirements set-algebra.

Re-implements (TPU-first, from behavior) the label-keyed constraint algebra of
karpenter core `pkg/scheduling` as consumed by the reference at
pkg/providers/instancetype/types.go:179-283 and
pkg/providers/instance/instance.go:241 (SURVEY.md §2.1):

  - per-key value sets with operators In / NotIn / Exists / DoesNotExist /
    Gt / Lt (k8s NodeSelectorRequirement semantics)
  - `minValues` per-key flexibility floors
    (website/content/en/preview/concepts/nodepools.md:268-330)
  - Intersects / Compatible / Intersection over whole requirement sets

A per-key `Requirement` is canonically either:
  * a finite allow-set    (complement=False, values=frozenset)
  * a co-finite deny-set  (complement=True,  values=frozenset)  # NotIn/Exists
plus optional numeric bounds greater_than / less_than (exclusive), mirroring
how karpenter folds Gt/Lt into the same per-key structure.

This module is also the host-side front end of the TPU solver: requirement
sets are lowered to integer-coded masks in `karpenter_tpu.solver.encode`.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Dict, Iterable, Iterator, Mapping, Optional, Sequence

# Operators (k8s corev1.NodeSelectorOperator spelling).
IN = "In"
NOT_IN = "NotIn"
EXISTS = "Exists"
DOES_NOT_EXIST = "DoesNotExist"
GT = "Gt"
LT = "Lt"

OPERATORS = (IN, NOT_IN, EXISTS, DOES_NOT_EXIST, GT, LT)


class IncompatibleError(Exception):
    """Two requirement sets (or a set and labels) cannot be satisfied together."""


@dataclass(frozen=True)
class Requirement:
    """The set of acceptable values for one label key.

    `require_present` distinguishes operators that demand the label exist on
    the node (In / Exists / Gt / Lt — kube NodeSelectorRequirement semantics)
    from those vacuously satisfied by an absent label (NotIn / DoesNotExist).
    """

    key: str
    complement: bool = False  # True => values is a deny-set over all strings
    values: frozenset = field(default_factory=frozenset)
    greater_than: Optional[int] = None  # exclusive lower bound
    less_than: Optional[int] = None  # exclusive upper bound
    min_values: Optional[int] = None  # flexibility floor (NodePool minValues)
    require_present: bool = True

    # -- constructors -------------------------------------------------------

    @staticmethod
    def create(key: str, operator: str, values: Sequence[str] = (), min_values: Optional[int] = None) -> "Requirement":
        vals = frozenset(str(v) for v in values)
        if operator == IN:
            return Requirement(key, False, vals, min_values=min_values, require_present=True)
        if operator == NOT_IN:
            return Requirement(key, True, vals, min_values=min_values, require_present=False)
        if operator == EXISTS:
            return Requirement(key, True, frozenset(), min_values=min_values, require_present=True)
        if operator == DOES_NOT_EXIST:
            return Requirement(key, False, frozenset(), min_values=min_values, require_present=False)
        if operator == GT:
            (v,) = vals if len(vals) == 1 else (None,)
            if v is None:
                raise ValueError(f"{GT} requires exactly one value, got {sorted(vals)}")
            return Requirement(key, True, frozenset(), greater_than=int(v), min_values=min_values)
        if operator == LT:
            (v,) = vals if len(vals) == 1 else (None,)
            if v is None:
                raise ValueError(f"{LT} requires exactly one value, got {sorted(vals)}")
            return Requirement(key, True, frozenset(), less_than=int(v), min_values=min_values)
        raise ValueError(f"unknown operator {operator!r}")

    # -- predicates ---------------------------------------------------------

    def _bounds_ok(self, value: str) -> bool:
        if self.greater_than is None and self.less_than is None:
            return True
        try:
            n = int(value)
        except ValueError:
            return False
        if self.greater_than is not None and not n > self.greater_than:
            return False
        if self.less_than is not None and not n < self.less_than:
            return False
        return True

    def has(self, value: str) -> bool:
        """Does this requirement admit `value`?"""
        if not self._bounds_ok(value):
            return False
        if self.complement:
            return value not in self.values
        return value in self.values

    def is_complement(self) -> bool:
        return self.complement

    def allows_absent(self) -> bool:
        """DoesNotExist <=> empty allow-set."""
        return not self.complement and not self.values

    def is_empty(self) -> bool:
        """True if NO value can ever satisfy this requirement.

        Finite sets: no value passes the bounds. Co-finite sets: only empty
        when both numeric bounds are present and no integer lies strictly
        between them (bounds force numeric-only values, making the admissible
        set finite)."""
        if not self.complement:
            return not any(self._bounds_ok(v) for v in self.values) if self.values else True
        if self.greater_than is not None and self.less_than is not None:
            return not any(
                str(n) not in self.values
                for n in range(self.greater_than + 1, self.less_than)
            )
        return False

    def satisfiable(self) -> bool:
        """A value exists, or absence is acceptable (NotIn/DoesNotExist)."""
        return not self.is_empty() or not self.require_present

    def any_value(self) -> Optional[str]:
        """A representative admissible value (finite sets only)."""
        for v in sorted(self.values):
            if self.has(v):
                return v
        return None

    def len_hint(self) -> Optional[int]:
        """Cardinality if finite, else None (infinite)."""
        if self.complement:
            return None
        return sum(1 for v in self.values if self._bounds_ok(v))

    # -- algebra ------------------------------------------------------------

    def intersect(self, other: "Requirement") -> "Requirement":
        gt = _max_opt(self.greater_than, other.greater_than)
        lt = _min_opt(self.less_than, other.less_than)
        mv = _max_opt(self.min_values, other.min_values)
        rp = self.require_present or other.require_present
        if self.complement and other.complement:
            return Requirement(self.key, True, self.values | other.values, gt, lt, mv, rp)
        if self.complement:
            vals = frozenset(v for v in other.values if v not in self.values)
            return Requirement(self.key, False, vals, gt, lt, mv, rp)
        if other.complement:
            vals = frozenset(v for v in self.values if v not in other.values)
            return Requirement(self.key, False, vals, gt, lt, mv, rp)
        return Requirement(self.key, False, self.values & other.values, gt, lt, mv, rp)

    def intersects(self, other: "Requirement") -> bool:
        # allocation-free fast path for the overwhelmingly common bounds-free
        # case (the oracle's compatible() calls this millions of times per
        # large solve): without Gt/Lt, emptiness reduces to set algebra.
        if (
            self.greater_than is None
            and self.less_than is None
            and other.greater_than is None
            and other.less_than is None
        ):
            if self.complement:
                if other.complement:
                    return True  # co-finite ∩ co-finite is co-finite
                return any(v not in self.values for v in other.values)
            if other.complement:
                return any(v not in other.values for v in self.values)
            return not self.values.isdisjoint(other.values)
        return not self.intersect(other).is_empty()

    def values_list(self) -> list:
        return sorted(v for v in self.values if self._bounds_ok(v))

    def __repr__(self) -> str:  # pragma: no cover
        if self.complement and not self.values and self.greater_than is None and self.less_than is None:
            body = "Exists"
        elif self.complement:
            body = f"NotIn{sorted(self.values)}"
        else:
            body = f"In{sorted(self.values)}" if self.values else "DoesNotExist"
        bounds = ""
        if self.greater_than is not None:
            bounds += f" >{self.greater_than}"
        if self.less_than is not None:
            bounds += f" <{self.less_than}"
        return f"Req({self.key} {body}{bounds})"


def _max_opt(a: Optional[int], b: Optional[int]) -> Optional[int]:
    if a is None:
        return b
    if b is None:
        return a
    return max(a, b)


def _min_opt(a: Optional[int], b: Optional[int]) -> Optional[int]:
    if a is None:
        return b
    if b is None:
        return a
    return min(a, b)


class Requirements(Dict[str, Requirement]):
    """A conjunction of per-key requirements."""

    @classmethod
    def of(cls, *reqs: Requirement) -> "Requirements":
        out = cls()
        out.add(*reqs)
        return out

    @classmethod
    def from_labels(cls, labels: Mapping[str, str]) -> "Requirements":
        return cls.of(*(Requirement.create(k, IN, [v]) for k, v in (labels or {}).items()))

    @classmethod
    def from_node_selector_terms(cls, terms: Iterable[Mapping]) -> "Requirements":
        """Parse a list of {key, operator, values, minValues?} dicts."""
        out = cls()
        for t in terms or ():
            out.add(
                Requirement.create(
                    t["key"], t.get("operator", IN), t.get("values", ()), t.get("minValues")
                )
            )
        return out

    def add(self, *reqs: Requirement) -> "Requirements":
        for r in reqs:
            cur = self.get(r.key)
            self[r.key] = cur.intersect(r) if cur is not None else r
        return self

    def union(self, other: "Requirements") -> "Requirements":
        out = Requirements(self)
        out.add(*other.values())
        return out

    # -- compatibility ------------------------------------------------------

    def compatible(self, other: "Requirements") -> bool:
        """Can a node satisfy both requirement sets?

        Mirrors karpenter `Requirements.Compatible`: for every key in `self`,
        the intersection with `other`'s requirement (Exists if absent) must be
        non-empty; and vice versa for keys only in `other` whose requirement
        forbids absence. Absent keys behave as unconstrained (Exists).
        """
        for key, req in self.items():
            o = other.get(key)
            if o is None:
                # Other side unconstrained: any non-DoesNotExist req is fine,
                # DoesNotExist is also fine (the label may simply be absent).
                continue
            if not req.intersects(o):
                return False
        return True

    def strictly_compatible(self, other: "Requirements") -> bool:
        """Compatible, and every key whose operator demands label presence
        (In/Exists/Gt/Lt) is actually defined by `other` — used when `other`
        is a concrete node label universe rather than another constraint set.
        NotIn/DoesNotExist are vacuously satisfied by an absent label (kube
        NodeSelectorRequirement semantics)."""
        for key, req in self.items():
            o = other.get(key)
            if o is None:
                if req.require_present:
                    return False
                continue
            if not req.intersects(o):
                return False
        return True

    def labels(self) -> Dict[str, str]:
        """Single-valued keys rendered as node labels (reference:
        pkg/cloudprovider/cloudprovider.go:377-436 builds NodeClaim labels
        from single-valued requirements)."""
        out: Dict[str, str] = {}
        for key, req in self.items():
            if not req.complement and len(req.values) == 1:
                (v,) = req.values
                out[key] = v
        return out

    def has_min_values(self) -> bool:
        return any(r.min_values for r in self.values())

    def __repr__(self) -> str:  # pragma: no cover
        return "Requirements(" + ", ".join(repr(r) for r in self.values()) + ")"
