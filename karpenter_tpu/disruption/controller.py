"""Disruption engine: drift, emptiness, multi- and single-node consolidation.

The single-actor loop of karpenter core's disruption controller (SURVEY.md
§2.1 disruption, §3.2; website/.../concepts/disruption.md;
designs/consolidation.md:5-36, designs/deprovisioning.md:3-33):

  - Methods evaluated in order Drift -> Emptiness -> MultiNodeConsolidation
    -> SingleNodeConsolidation; ONE command executes per loop.
  - Consolidation = delete (pods fit on remaining capacity) or replace
    (remaining capacity + exactly one cheaper new node). Multi-node deletes
    >=2 nodes with <=1 cheaper replacement, searching the largest
    cost-ordered candidate prefix (heuristic subset, disruption.md:104-106)
    via binary search.
  - Spot->spot single-node replacement requires >=15 cheaper instance types
    (disruption.md:133-137).
  - Rate-limited by NodePool budgets (% or count per reason,
    disruption.md:274-330; default nodes=10%).
  - Control flow: taint karpenter.sh/disrupted, pre-spin replacements, wait
    for initialization, then delete candidates; rollback on failed init
    (disruption.md:15-28).
  - Blockers: karpenter.sh/do-not-disrupt on pod or node, PDB-blocked
    eviction, nominated nodes (disruption.md:335-409).

Every simulation is a re-solve through the pluggable Solver backend — on the
TPU backend, candidate subsets batch as a leading vmap axis (SURVEY.md §2.10
"TPU-equivalent").
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..api import wellknown as wk
from ..api.objects import Node, NodeClaim, NodePool, Pod, Taint
from ..cloudprovider.types import CloudProvider
from ..controllers import store as st
from ..metrics.registry import DISRUPTION_DECISIONS, DISRUPTION_EVAL_DURATION
from ..provisioning.provisioner import Provisioner
from ..scheduling.requirements import IN, Requirement
from ..solver.backend import Solver
from ..state.cluster import Cluster
from ..termination.controller import EvictionQueue


def _pod_cost(p: Pod) -> float:
    """Per-pod move cost: base 1, shifted by priority and the
    pod-deletion-cost annotation (higher deletion cost / priority = more
    expensive to move; negative deletion cost makes a pod cheaper)."""
    cost = 1.0 + p.priority / 1000.0
    raw = p.meta.annotations.get(wk.POD_DELETION_COST_ANNOTATION)
    if raw is not None:
        try:
            cost += float(raw) / 1000.0
        except ValueError:
            pass  # malformed annotation: ignored, like the kube controllers
    return cost


@dataclass
class Candidate:
    claim: NodeClaim
    node: Node
    pods: List[Pod]
    price: float
    cost: float  # disruption cost (ranking key, ascending = disrupt first)


@dataclass
class Command:
    method: str  # drifted | empty | multi-consolidation | single-consolidation
    candidates: List[Candidate]
    replacement_names: List[str] = field(default_factory=list)
    created_at: float = 0.0


class DisruptionController:
    name = "disruption"

    def __init__(
        self,
        store: st.Store,
        cluster: Cluster,
        cloud_provider: CloudProvider,
        solver: Solver,
        clock=time.monotonic,
        wall_clock=time.time,
        preference_policy: str = "Respect",
        replacement_timeout_s: float = 10 * 60,
        multi_node_max_candidates: int = 100,
        multi_node_max_candidates_batched: int = 10_000,
        batch_phase_width: int = 64,  # single-consolidation chunk width
        probe_batch_max: int = 512,  # widest speculative-probe frontier
        solve_service=None,  # pipelined device owner (solver/pipeline.py)
    ):
        self.store = store
        self.cluster = cluster
        self.cloud_provider = cloud_provider
        self.solver = solver
        self.clock = clock
        self.wall_clock = wall_clock  # cron budget windows need civil time
        self.preference_policy = preference_policy
        self.eviction = EvictionQueue(store)
        self.replacement_timeout_s = replacement_timeout_s
        self.multi_node_max_candidates = multi_node_max_candidates
        # the batched (device) path spans the fleet: subset rows are cheap,
        # so the heuristic candidate pool is 100× the sequential cap
        # (config 5: 10k-node multi-node consolidation)
        self.multi_node_max_candidates_batched = multi_node_max_candidates_batched
        self.batch_phase_width = batch_phase_width
        # speculative binary probes: one dispatch carries up to this many
        # candidate-prefix rows (all O(n) prefixes when the search interval
        # fits, else the top levels of the binary decision tree)
        self.probe_batch_max = probe_batch_max
        # when a SolveService owns the device, simulate re-solves and probe
        # batches queue through it — interleaved fairly with provisioning
        # instead of grabbing the device directly
        self._solve_service = solve_service
        self._command: Optional[Command] = None
        self._provisioner_helper: Optional[Provisioner] = None
        self._prep_cache = None  # per-reconcile prepared batched universe
        self._prep_rev = 0  # journal state_rev the prepared universe observed
        self.stats: Dict[str, int] = {}
        # TPU backend: evaluate candidate subsets as one vmapped batch
        # (solver/tpu/consolidate.py); sequential path remains ground truth
        from ..solver.backend import TPUSolver, concrete_backend

        self._batched = None
        # unwrap the wrapper chain (resilience, scheduling classes, ...): the
        # batched evaluator keys off the concrete device backend at the bottom
        inner = concrete_backend(solver)
        if isinstance(inner, TPUSolver):
            from .batched import BatchedConsolidationEvaluator

            self._batched = BatchedConsolidationEvaluator(inner)
        # convex backend: one-shot whole-cluster consolidation proposals
        # (solver/convex.py consolidate_global) — the probe ladder stays the
        # fallback and the cross-check oracle when the global path declines
        from ..solver.convex import find_convex

        self._convex = find_convex(solver)

    # ------------------------------------------------------------------ main

    def reconcile(self) -> bool:
        if self._command is not None:
            return self._progress_command()
        self._prep_cache = None  # cluster state may have changed since last loop
        candidates = self._candidates()
        if not candidates:
            return False
        budgets = self._budget_allowance(candidates)
        t0 = time.perf_counter()
        from ..solver.pipeline import Superseded

        for method in ("drifted", "empty", "multi-consolidation", "single-consolidation"):
            try:
                cmd = self._evaluate(method, candidates, budgets)
            except Superseded:
                # a streamed journal batch was applied while a speculative
                # probe was in flight: the prepared universe is older than
                # the provisioner's last-solved state. Defer the whole tick
                # (same contract as a superseded provisioning snapshot) and
                # re-prepare at the new journal rev next loop.
                self.stats["superseded_defers"] = self.stats.get("superseded_defers", 0) + 1
                DISRUPTION_EVAL_DURATION.observe(time.perf_counter() - t0, method="superseded")
                return False
            if cmd is not None:
                DISRUPTION_EVAL_DURATION.observe(time.perf_counter() - t0, method=method)
                self._execute(cmd)
                return True
        DISRUPTION_EVAL_DURATION.observe(time.perf_counter() - t0, method="none")
        return False

    # ------------------------------------------------------------ candidates

    def _candidates(self) -> List[Candidate]:
        pods_by_node = self.cluster.bound_pods()
        nodepools = {p.name: p for p in self.store.list(st.NODEPOOLS)}
        out: List[Candidate] = []
        for sn in self.cluster.state_nodes():
            claim, node = sn.claim, sn.node
            if claim is None or node is None:
                continue
            if not claim.initialized or claim.meta.deleting or node.meta.deleting:
                continue
            np_obj = nodepools.get(claim.nodepool)
            if np_obj is None:
                continue
            if node.meta.annotations.get(wk.DO_NOT_DISRUPT_ANNOTATION) == "true":
                continue
            if self.cluster.is_nominated(node.meta.name):
                continue
            pods = pods_by_node.get(node.meta.name, [])
            if any(p.meta.annotations.get(wk.DO_NOT_DISRUPT_ANNOTATION) == "true" for p in pods):
                continue
            if any(not self.eviction.can_evict(p) for p in pods if p.owner_kind != "DaemonSet"):
                continue  # PDB-blocked (disruption.md:335-409)
            resched = [p for p in pods if p.owner_kind != "DaemonSet"]
            age = self.clock() - claim.meta.creation_timestamp
            # Disruption cost (disruption.md: candidates ranked by pod count,
            # pod-deletion-cost, pod priority, and node lifetime remaining):
            # cheaper-to-move nodes first. Pod cost folds the
            # controller.kubernetes.io/pod-deletion-cost annotation and
            # priority; the sum scales by the claim's remaining share of its
            # expireAfter lifetime — a node close to expiry is nearly free to
            # disrupt (it is about to be replaced anyway).
            cost = float(sum(_pod_cost(p) for p in resched))
            if claim.expire_after_s and cost > 0:
                # scale positive sums only: a negative sum (deletion-cost
                # annotations) scaled toward 0 would INVERT the ranking and
                # make a near-expiry node look more expensive
                remaining = 1.0 - (age / claim.expire_after_s)
                cost *= min(max(remaining, 0.0), 1.0)
            out.append(
                Candidate(claim=claim, node=node, pods=resched, price=claim.price, cost=cost)
            )
        out.sort(key=lambda c: (c.cost, -(self.clock() - c.claim.meta.creation_timestamp), c.claim.name))
        return out

    # ---------------------------------------------------------------- budget

    def _budget_allowance(self, candidates: List[Candidate]) -> Dict[Tuple[str, str], int]:
        """(nodepool, reason) -> how many more nodes may be disrupted now
        (disruption.md:274-330; default 10%)."""
        nodepools = {p.name: p for p in self.store.list(st.NODEPOOLS)}
        total_by_pool: Dict[str, int] = {}
        disrupting_by_pool: Dict[str, int] = {}
        for sn in self.cluster.state_nodes():
            if sn.claim is None:
                continue
            pool = sn.claim.nodepool
            total_by_pool[pool] = total_by_pool.get(pool, 0) + 1
            if sn.claim.meta.deleting or (
                sn.node is not None and any(t.key == wk.DISRUPTED_TAINT_KEY for t in sn.node.taints)
            ):
                disrupting_by_pool[pool] = disrupting_by_pool.get(pool, 0) + 1
        out: Dict[Tuple[str, str], int] = {}
        for pool_name, np_obj in nodepools.items():
            total = total_by_pool.get(pool_name, 0)
            disrupting = disrupting_by_pool.get(pool_name, 0)
            for reason in ("Drifted", "Empty", "Underutilized"):
                allowed = None
                for b in np_obj.disruption.budgets:
                    if b.reasons is not None and reason not in b.reasons:
                        continue
                    if not self._budget_active(b):
                        continue
                    if b.nodes.endswith("%"):
                        cap = math.ceil(total * int(b.nodes[:-1]) / 100.0)
                    else:
                        cap = int(b.nodes)
                    allowed = cap if allowed is None else min(allowed, cap)
                if allowed is None:
                    allowed = math.ceil(total * 0.10)
                out[(pool_name, reason)] = max(0, allowed - disrupting)
        return out

    def _budget_active(self, b) -> bool:
        """Cron-scheduled budgets constrain only inside [match, match+duration]
        (disruption.md:274-330); schedule-less budgets are always active."""
        if b.schedule is None:
            return True
        if b.duration_s is None:
            return False  # schedule requires a duration (CRD validation)
        from .cron import in_window

        try:
            return in_window(b.schedule, b.duration_s, self.wall_clock())
        except ValueError:
            return False  # malformed schedule: never constrains

    @staticmethod
    def _reason(method: str) -> str:
        return {
            "drifted": "Drifted",
            "empty": "Empty",
            "multi-consolidation": "Underutilized",
            "single-consolidation": "Underutilized",
        }[method]

    def _within_budget(self, cands: Sequence[Candidate], method: str, budgets) -> bool:
        reason = self._reason(method)
        need: Dict[str, int] = {}
        for c in cands:
            need[c.claim.nodepool] = need.get(c.claim.nodepool, 0) + 1
        return all(budgets.get((pool, reason), 0) >= n for pool, n in need.items())

    # ------------------------------------------------------------- evaluate

    def _evaluate(self, method: str, candidates: List[Candidate], budgets) -> Optional[Command]:
        if method == "drifted":
            for c in candidates:
                if not c.claim.drifted:
                    continue
                if not self._within_budget([c], method, budgets):
                    continue
                ok, claim_res = self._simulate([c], allow_replacement=True, require_cheaper=False)
                if ok:
                    try:
                        names = [self._create_replacement(claim_res)] if claim_res else []
                    except Exception:
                        continue  # rejected replacement: skip this candidate/prefix
                    return Command(method, [c], replacement_names=names)
            return None

        if method == "empty":
            policies = {p.name: p.disruption for p in self.store.list(st.NODEPOOLS)}
            empties = []
            for c in candidates:
                if c.pods:
                    continue
                pol = policies.get(c.claim.nodepool)
                if pol is None or pol.consolidation_policy not in (
                    "WhenEmpty",
                    "WhenEmptyOrUnderutilized",
                ):
                    continue
                if self.clock() - c.claim.last_transition < pol.consolidate_after_s:
                    continue
                empties.append(c)
            # batch all in-budget empties into one command (reference deletes
            # empty nodes in bulk)
            allowed = [c for c in empties if self._within_budget([c], method, budgets)]
            picked: List[Candidate] = []
            for c in allowed:
                if self._within_budget(picked + [c], method, budgets):
                    picked.append(c)
            if picked:
                return Command(method, picked)
            return None

        consolidatable = [
            c
            for c in candidates
            if self._consolidation_enabled(c) and self._consolidate_after_ok(c)
        ]
        if method == "multi-consolidation":
            # global path first: one ADMM program proposes the deletable
            # SUBSET (not just cost-ordered prefixes, which the binary-search
            # ladder is limited to) + one sequential verify = <=2 device
            # dispatches per decision; any decline falls through to the
            # probe ladder / sequential search unchanged
            if self._convex is not None:
                pool_g = consolidatable[: self.multi_node_max_candidates_batched]
                if (
                    len(pool_g) >= 2
                    and self._max_budget_prefix(pool_g, method, budgets) >= 2
                ):
                    cmd = self._multi_global(pool_g, budgets, method)
                    if cmd is not None:
                        return cmd
            if self._batched is not None:
                cmd = self._multi_batched(consolidatable, budgets)
                if cmd is not NotImplemented:
                    return cmd
            pool = consolidatable[: self.multi_node_max_candidates]
            # sequential: binary search the largest cost-ordered prefix that
            # consolidates (>=2 deletes, <=1 cheaper replacement)
            lo, hi = 2, len(pool)
            best = None
            while lo <= hi:
                mid = (lo + hi) // 2
                subset = pool[:mid]
                if self._within_budget(subset, method, budgets):
                    ok, claim_res = self._simulate(subset, allow_replacement=True, require_cheaper=True)
                else:
                    ok, claim_res = False, None
                if ok:
                    best = (subset, claim_res)
                    lo = mid + 1
                else:
                    hi = mid - 1
            if best is not None:
                subset, claim_res = best
                try:
                    names = [self._create_replacement(claim_res)] if claim_res else []
                except Exception:
                    return None  # rejected replacement: no command this loop
                return Command(method, subset, replacement_names=names)
            return None

        # single-node consolidation
        if self._batched is not None:
            cmd = self._single_batched(consolidatable, budgets)
            if cmd is not NotImplemented:
                return cmd
        for c in consolidatable:
            if not self._within_budget([c], method, budgets):
                continue
            ok, claim_res = self._simulate([c], allow_replacement=True, require_cheaper=True)
            if ok and self._spot_flexibility_ok_res(c, claim_res):
                try:
                    names = [self._create_replacement(claim_res)] if claim_res else []
                except Exception:
                    continue  # rejected replacement: skip this candidate/prefix
                return Command(method, [c], replacement_names=names)
        return None

    # ------------------------------------------------ batched consolidation

    def _prepared_universe(self, consolidatable: List[Candidate]):
        """Encode + upload the simulation universe once per reconcile; both
        consolidation methods evaluate subset batches against it."""
        from ..api.objects import pod_mutation_epoch

        # content-aware key: claim names alone survive pod mutations (a
        # constraint dropped between reconciles leaves the names unchanged),
        # so a stale universe could serve probes against constraints that no
        # longer exist. Pod object identity + the global mutation epoch pin
        # the exact pod contents; the entry pins the pod objects so a freed
        # id can't be recycled into a colliding key. The journal rev pins the
        # store-event history: under --solver-streaming the provisioner folds
        # event batches between our reconcile ticks, and a universe prepared
        # before a fold must not serve probes after it (state/cluster.py).
        key = (
            tuple(c.claim.name for c in consolidatable),
            pod_mutation_epoch(),
            tuple(id(p) for c in consolidatable for p in c.pods),
            self.cluster.journal.rev(),
        )
        if self._prep_cache is not None and self._prep_cache[0] == key:
            return self._prep_cache[1]
        import dataclasses as _dc

        if self._provisioner_helper is None:
            self._provisioner_helper = Provisioner(
                self.store, self.cluster, self.cloud_provider, self.solver,
                batch_idle_s=0, batch_max_s=0, clock=self.clock,
                preference_policy=self.preference_policy,
            )
        base = self._provisioner_helper.build_input([])
        candidate_pods = {
            i: [_dc.replace(p, node_name=None, phase="Pending") for p in c.pods]
            for i, c in enumerate(consolidatable)
        }
        candidate_node = {i: c.node.meta.name for i, c in enumerate(consolidatable)}
        try:
            prep = self._batched.prepare(base, candidate_pods, candidate_node)
        except Exception:
            prep = None
        self._prep_cache = (key, prep, [p for c in consolidatable for p in c.pods])
        # the universe's journal state_rev: probes fired against this prep
        # defer (Superseded) once the streaming consumer applies a newer batch
        self._prep_rev = self.cluster.journal.rev()
        return prep

    def _max_budget_prefix(self, pool: List[Candidate], method: str, budgets) -> int:
        """Largest k with pool[:k] within budget (monotone in k)."""
        lo, hi = 0, len(pool)
        while lo < hi:
            mid = (lo + hi + 1) // 2
            if self._within_budget(pool[:mid], method, budgets):
                lo = mid
            else:
                hi = mid - 1
        return lo

    def _evaluate_probe_batch(self, prep, subsets):
        """One batched speculative-probe dispatch, through the solve service
        when one owns the device (fair interleave with provisioning), else
        straight at the evaluator."""
        if self._solve_service is not None:
            ticket = self._solve_service.submit_fn(
                lambda: self._batched.evaluate_prepared_async(prep, subsets),
                kind="disruption",
            )
            out = ticket.result()
        else:
            out = self._batched.evaluate_prepared(prep, subsets)
        # streaming staleness guard: the provisioner may fold journal batches
        # while the probe is in flight. A probe answered against a universe
        # older than the last APPLIED batch must not drive a disruption
        # command — defer exactly like a superseded provisioning snapshot.
        from ..solver.pipeline import Superseded

        if self.cluster.journal.applied_rev > self._prep_rev:
            raise Superseded()
        return out

    def _multi_batched(self, consolidatable: List[Candidate], budgets):
        """Batched speculative probes: a decision-for-decision replay of the
        sequential binary search over cost-ordered prefixes, with the probe
        frontier evaluated as 1-2 vmapped dispatches against the prepared
        (arena-resident, mesh-replicated) universe instead of one device
        round-trip per probe (batched.speculative_binary_search). Budget-
        clamped prefixes (k > kmax) answer host-side — the sequential loop
        rejects them without solving too, so the replay stays faithful.
        Returns Command | None, or NotImplemented to use the sequential path.
        """
        method = "multi-consolidation"
        pool = consolidatable[: self.multi_node_max_candidates_batched]
        if len(pool) < 2:
            return None
        kmax = min(self._max_budget_prefix(pool, method, budgets), len(pool))
        if kmax < 2:
            return None  # budget admits no >=2-node command this loop
        prep = self._prepared_universe(consolidatable)
        if prep is None:
            return NotImplemented
        cum_price = [0.0]
        for c in pool:
            cum_price.append(cum_price[-1] + c.price)

        def acceptable(k: int, v) -> bool:
            if v is None or not v.ok:
                return False
            if v.has_replacement and (
                v.replacement_price is None or v.replacement_price >= cum_price[k]
            ):
                return False
            return True

        from .batched import speculative_binary_search

        dispatches = 0

        def eval_ks(ks):
            nonlocal dispatches
            # out-of-budget prefixes reject host-side (None verdict) exactly
            # like the sequential loop's `ok = False` without a solve
            dev_ks = [k for k in ks if k <= kmax]
            by_k = {}
            if dev_ks:
                verdicts = self._evaluate_probe_batch(
                    prep, [list(range(k)) for k in dev_ks]
                )
                dispatches += 1
                by_k = dict(zip(dev_ks, verdicts))
            return [by_k.get(k) for k in ks]

        try:
            best_k, probed, _batches = speculative_binary_search(
                eval_ks, 2, len(pool), acceptable,
                probe_batch_max=max(self.probe_batch_max, 2),
            )
        except Exception:
            return NotImplemented  # device failure mid-search: sequential path
        self.stats["batched_prefixes_evaluated"] = (
            self.stats.get("batched_prefixes_evaluated", 0) + len(probed)
        )
        self.stats["probe_dispatches"] = (
            self.stats.get("probe_dispatches", 0) + dispatches
        )
        self.stats["probe_decisions"] = self.stats.get("probe_decisions", 0) + 1
        if best_k is None:
            return None
        # re-materialize the winner sequentially so command construction is
        # bit-identical to the sequential path; on (unexpected) divergence,
        # degrade to the next-largest accepted probe BELOW the decision —
        # speculative rows above best_k sit on paths the replay rejected and
        # must never outrank the binary-search decision
        ranked = [best_k] + sorted(
            (
                k
                for k, v in probed.items()
                if k < best_k and k <= kmax and acceptable(k, v)
            ),
            reverse=True,
        )
        for k in ranked:
            ok, claim_res = self._simulate(pool[:k], allow_replacement=True, require_cheaper=True)
            if ok:
                try:
                    names = [self._create_replacement(claim_res)] if claim_res else []
                except Exception:
                    continue  # rejected replacement: skip this candidate/prefix
                return Command(method, pool[:k], replacement_names=names)
        return None

    def _multi_global(self, pool: List[Candidate], budgets, method: str):
        """One-shot whole-cluster consolidation via the convex backend
        (solver/convex.py consolidate_global): dispatch 1 proposes the
        deletable candidate SUBSET — any subset, not just cost-ordered
        prefixes, which the binary-search ladder structurally cannot find —
        and dispatch 2 is ONE sequential `_simulate` that verifies the
        proposal under the exact command-safety rules (no unschedulable
        pods, <=1 replacement claim, cheaper) before anything is commanded.
        Every decline (no convex layer wired / out-of-scope input /
        non-convergence / budget trim below 2 / verify reject) returns None
        and the probe ladder cross-checks as before."""
        if self._convex is None:
            return None

        def bump(key: str) -> None:
            self.stats[key] = self.stats.get(key, 0) + 1

        bump("global_decisions")
        if self._provisioner_helper is None:
            self._provisioner_helper = Provisioner(
                self.store, self.cluster, self.cloud_provider, self.solver,
                batch_idle_s=0, batch_max_s=0, clock=self.clock,
                preference_policy=self.preference_policy,
            )
        import dataclasses

        pods = [
            dataclasses.replace(p, node_name=None, phase="Pending")
            for c in pool
            for p in c.pods
        ]
        # candidates' nodes stay PRESENT: the global program models staying
        # put as a priced column per candidate, so removal is a per-column
        # decision instead of a pre-filtered universe
        inp = self._provisioner_helper.build_input(pods)
        cands_arg = [
            (c.node.meta.name, c.price, frozenset(p.meta.uid for p in c.pods))
            for c in pool
        ]
        try:
            proposal = self._convex.consolidate_global(inp, cands_arg)
        except Exception:
            proposal = None
        if proposal is None:
            bump("global_declines")
            return None
        bump("global_dispatches")  # dispatch 1: the ADMM proposal
        delete = set(proposal["delete"])
        subset: List[Candidate] = []
        for c in pool:  # cost order: greedy trim to the per-pool budgets
            if c.node.meta.name in delete and self._within_budget(
                subset + [c], method, budgets
            ):
                subset.append(c)
        if len(subset) < 2:
            bump("global_declines")
            return None
        ok, claim_res = self._simulate(
            subset, allow_replacement=True, require_cheaper=True
        )
        bump("global_dispatches")  # dispatch 2: the sequential verify
        if not ok:
            bump("global_verify_rejects")
            return None
        try:
            names = [self._create_replacement(claim_res)] if claim_res else []
        except Exception:
            bump("global_verify_rejects")
            return None
        bump("global_commands")
        return Command(method, subset, replacement_names=names)

    def _single_batched(self, consolidatable: List[Candidate], budgets):
        """Chunked single-candidate verdicts in cost order; first acceptable
        chunk short-circuits (the sequential scan's first-success order)."""
        method = "single-consolidation"
        if not consolidatable:
            return None
        prep = self._prepared_universe(consolidatable)
        if prep is None:
            return NotImplemented
        chunk = max(self.batch_phase_width, 2) * 2
        for start in range(0, len(consolidatable), chunk):
            idxs = list(range(start, min(start + chunk, len(consolidatable))))
            try:
                verdicts = self._batched.evaluate_prepared(prep, [[i] for i in idxs])
            except Exception:
                return NotImplemented  # device failure: sequential path
            for i, v in zip(idxs, verdicts):
                c = consolidatable[i]
                if not self._within_budget([c], method, budgets):
                    continue
                if not v.ok:
                    continue
                if v.has_replacement:
                    if v.replacement_price is None or v.replacement_price >= c.price:
                        continue
                    if (
                        c.claim.capacity_type == wk.CAPACITY_TYPE_SPOT
                        and v.replacement_type_count < 15
                    ):
                        continue
                ok, claim_res = self._simulate([c], allow_replacement=True, require_cheaper=True)
                if ok and self._spot_flexibility_ok_res(c, claim_res):
                    try:
                        names = [self._create_replacement(claim_res)] if claim_res else []
                    except Exception:
                        continue  # rejected replacement: skip this candidate/prefix
                    return Command(method, [c], replacement_names=names)
        return None

    def _consolidation_enabled(self, c: Candidate) -> bool:
        for p in self.store.list(st.NODEPOOLS):
            if p.name == c.claim.nodepool:
                return p.disruption.consolidation_policy == "WhenEmptyOrUnderutilized"
        return False

    def _consolidate_after_ok(self, c: Candidate) -> bool:
        for p in self.store.list(st.NODEPOOLS):
            if p.name == c.claim.nodepool:
                return self.clock() - c.claim.last_transition >= p.disruption.consolidate_after_s
        return False

    def _spot_flexibility_ok_res(self, c: Candidate, claim_res) -> bool:
        """Spot->spot replacement needs >=15 cheaper types (disruption.md:
        133-137) so consolidation doesn't chase the spot market's tail."""
        if c.claim.capacity_type != wk.CAPACITY_TYPE_SPOT or claim_res is None:
            return True
        ct = claim_res.requirements.get(wk.CAPACITY_TYPE_LABEL)
        if ct is not None and not ct.has(wk.CAPACITY_TYPE_SPOT):
            return True
        return len(claim_res.instance_type_names) >= 15

    # ------------------------------------------------------------- simulate

    def _simulate(
        self, cands: List[Candidate], allow_replacement: bool, require_cheaper: bool
    ):
        """Re-solve with the candidates' pods pending and the candidates
        removed (SURVEY.md §3.2 HOT LOOP #2). Success iff nothing is
        unschedulable, <=1 new claim results, and (if required) the
        replacement is cheaper than the removed capacity. Returns
        (ok, claim_result_or_None); the caller materializes the replacement
        NodeClaim only for the command it actually executes (binary-search
        probes must not leak claims)."""
        if self._provisioner_helper is None:
            self._provisioner_helper = Provisioner(
                self.store, self.cluster, self.cloud_provider, self.solver,
                batch_idle_s=0, batch_max_s=0, clock=self.clock,
                preference_policy=self.preference_policy,
            )
        import dataclasses

        # simulate the candidates' pods as pending (they are bound right now;
        # the scheduler rightly ignores bound pods)
        pods = [
            dataclasses.replace(p, node_name=None, phase="Pending")
            for c in cands
            for p in c.pods
        ]
        removed = {c.node.meta.name for c in cands}
        inp = self._provisioner_helper.build_input(pods)
        inp.nodes = [n for n in inp.nodes if n.id not in removed]
        if self._solve_service is not None:
            # disruption-class: never coalesced (each probe is a distinct
            # hypothetical universe, not a cluster snapshot), fair-interleaved
            # with provisioning solves on the shared device queue
            result = self._solve_service.submit(inp, kind="disruption").result()
        else:
            result = self.solver.solve(inp)
        if result.errors:
            return False, None
        if len(result.claims) > 1:
            return False, None
        if not allow_replacement and result.claims:
            return False, None
        if result.claims:
            claim_res = result.claims[0]
            if require_cheaper:
                new_price = self._min_price(claim_res)
                old_price = sum(c.price for c in cands)
                if new_price is None or new_price >= old_price:
                    return False, None
            return True, claim_res
        return True, None

    def _min_price(self, claim_res) -> Optional[float]:
        # name->type dict cached by catalog-list identity (the provider
        # returns the same list object until the ICE SeqNum moves), so the
        # disruption hot path doesn't rebuild a 600-entry dict per simulation
        lst = self.cloud_provider.get_instance_types("")
        cached = getattr(self, "_types_by_name", None)
        if cached is None or cached[0] is not lst:
            cached = (lst, {it.name: it for it in lst})
            self._types_by_name = cached
        types = cached[1]
        best = None
        for tn in claim_res.instance_type_names:
            it = types.get(tn)
            if it is None:
                continue
            o = it.cheapest_available(claim_res.requirements)
            if o is not None and (best is None or o.price < best):
                best = o.price
        return best

    def _create_replacement(self, claim_res) -> str:
        nodepools = {p.name: p for p in self.store.list(st.NODEPOOLS)}
        np_obj = nodepools[claim_res.nodepool]
        name = self._provisioner_helper._next_claim_name(claim_res.nodepool, suffix="r")
        reqs = type(claim_res.requirements)(claim_res.requirements)
        reqs.add(Requirement.create(wk.INSTANCE_TYPE_LABEL, IN, claim_res.instance_type_names))
        from ..api.objects import NodeClaim, ObjectMeta

        claim = NodeClaim(
            meta=ObjectMeta(
                name=name,
                labels={wk.NODEPOOL_LABEL: claim_res.nodepool},
                finalizers=[wk.TERMINATION_FINALIZER],
                creation_timestamp=self.clock(),
            ),
            nodepool=claim_res.nodepool,
            node_class_ref=np_obj.template.node_class_ref,
            requirements=reqs,
            resource_requests=claim_res.requests,
            taints=list(np_obj.template.taints),
            startup_taints=list(np_obj.template.startup_taints),
            expire_after_s=np_obj.template.expire_after_s,
            instance_type_options=list(claim_res.instance_type_names),
        )
        # may raise (admission/conflict): callers treat a failed replacement
        # as "no command this loop" instead of crashing the reconcile
        self.store.create(st.NODECLAIMS, claim)
        return name

    # -------------------------------------------------------------- execute

    def _execute(self, cmd: Command) -> None:
        for c in cmd.candidates:
            node = self.store.try_get(st.NODES, c.node.meta.name)
            if node is not None and not any(t.key == wk.DISRUPTED_TAINT_KEY for t in node.taints):
                node.taints.append(Taint(key=wk.DISRUPTED_TAINT_KEY, effect=wk.EFFECT_NO_SCHEDULE))
                node.unschedulable = True
                self.store.update(st.NODES, node)
        cmd.created_at = self.clock()
        self._command = cmd
        DISRUPTION_DECISIONS.inc(decision="delete" if not cmd.replacement_names else "replace",
                                 reason=self._reason(cmd.method))
        if not cmd.replacement_names:
            self._finish_command()  # no replacement to wait for

    def _progress_command(self) -> bool:
        cmd = self._command
        assert cmd is not None
        replacements = [self.store.try_get(st.NODECLAIMS, n) for n in cmd.replacement_names]
        if any(r is None for r in replacements):
            self._rollback("replacement disappeared")
            return True
        if all(r.initialized for r in replacements):
            self._finish_command()
            return True
        if self.clock() - cmd.created_at > self.replacement_timeout_s:
            self._rollback("replacement failed to initialize in time")
            return True
        return False  # keep waiting

    def _finish_command(self) -> None:
        cmd = self._command
        self._command = None
        if cmd is None:
            return
        for c in cmd.candidates:
            try:
                self.store.delete(st.NODECLAIMS, c.claim.name)
            except st.NotFound:
                pass

    def _rollback(self, why: str) -> None:
        """Untaint candidates; delete replacements (disruption.md:15-28)."""
        cmd = self._command
        self._command = None
        if cmd is None:
            return
        for c in cmd.candidates:
            node = self.store.try_get(st.NODES, c.node.meta.name)
            if node is not None:
                node.taints = [t for t in node.taints if t.key != wk.DISRUPTED_TAINT_KEY]
                node.unschedulable = False
                self.store.update(st.NODES, node)
        for name in cmd.replacement_names:
            if self.store.try_get(st.NODECLAIMS, name) is not None:
                try:
                    self.store.delete(st.NODECLAIMS, name)
                except st.NotFound:
                    pass
