"""Disruption engine: drift, emptiness, multi- and single-node consolidation.

The single-actor loop of karpenter core's disruption controller (SURVEY.md
§2.1 disruption, §3.2; website/.../concepts/disruption.md;
designs/consolidation.md:5-36, designs/deprovisioning.md:3-33):

  - Methods evaluated in order Drift -> Emptiness -> MultiNodeConsolidation
    -> SingleNodeConsolidation; ONE command executes per loop.
  - Consolidation = delete (pods fit on remaining capacity) or replace
    (remaining capacity + exactly one cheaper new node). Multi-node deletes
    >=2 nodes with <=1 cheaper replacement, searching the largest
    cost-ordered candidate prefix (heuristic subset, disruption.md:104-106)
    via binary search.
  - Spot->spot single-node replacement requires >=15 cheaper instance types
    (disruption.md:133-137).
  - Rate-limited by NodePool budgets (% or count per reason,
    disruption.md:274-330; default nodes=10%).
  - Control flow: taint karpenter.sh/disrupted, pre-spin replacements, wait
    for initialization, then delete candidates; rollback on failed init
    (disruption.md:15-28).
  - Blockers: karpenter.sh/do-not-disrupt on pod or node, PDB-blocked
    eviction, nominated nodes (disruption.md:335-409).

Every simulation is a re-solve through the pluggable Solver backend — on the
TPU backend, candidate subsets batch as a leading vmap axis (SURVEY.md §2.10
"TPU-equivalent").
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..api import wellknown as wk
from ..api.objects import Node, NodeClaim, NodePool, Pod, Taint
from ..cloudprovider.types import CloudProvider
from ..controllers import store as st
from ..metrics.registry import DISRUPTION_DECISIONS, DISRUPTION_EVAL_DURATION
from ..provisioning.provisioner import Provisioner
from ..scheduling.requirements import IN, Requirement
from ..solver.backend import Solver
from ..state.cluster import Cluster
from ..termination.controller import EvictionQueue


@dataclass
class Candidate:
    claim: NodeClaim
    node: Node
    pods: List[Pod]
    price: float
    cost: float  # disruption cost (ranking key, ascending = disrupt first)


@dataclass
class Command:
    method: str  # drifted | empty | multi-consolidation | single-consolidation
    candidates: List[Candidate]
    replacement_names: List[str] = field(default_factory=list)
    created_at: float = 0.0


class DisruptionController:
    name = "disruption"

    def __init__(
        self,
        store: st.Store,
        cluster: Cluster,
        cloud_provider: CloudProvider,
        solver: Solver,
        clock=time.monotonic,
        replacement_timeout_s: float = 10 * 60,
        multi_node_max_candidates: int = 100,
    ):
        self.store = store
        self.cluster = cluster
        self.cloud_provider = cloud_provider
        self.solver = solver
        self.clock = clock
        self.eviction = EvictionQueue(store)
        self.replacement_timeout_s = replacement_timeout_s
        self.multi_node_max_candidates = multi_node_max_candidates
        self._command: Optional[Command] = None
        self._provisioner_helper: Optional[Provisioner] = None
        # TPU backend: evaluate candidate subsets as one vmapped batch
        # (solver/tpu/consolidate.py); sequential path remains ground truth
        from ..solver.backend import TPUSolver

        self._batched = None
        if isinstance(solver, TPUSolver):
            from .batched import BatchedConsolidationEvaluator

            self._batched = BatchedConsolidationEvaluator(solver)

    # ------------------------------------------------------------------ main

    def reconcile(self) -> bool:
        if self._command is not None:
            return self._progress_command()
        candidates = self._candidates()
        if not candidates:
            return False
        budgets = self._budget_allowance(candidates)
        t0 = time.perf_counter()
        for method in ("drifted", "empty", "multi-consolidation", "single-consolidation"):
            cmd = self._evaluate(method, candidates, budgets)
            if cmd is not None:
                DISRUPTION_EVAL_DURATION.observe(time.perf_counter() - t0, method=method)
                self._execute(cmd)
                return True
        DISRUPTION_EVAL_DURATION.observe(time.perf_counter() - t0, method="none")
        return False

    # ------------------------------------------------------------ candidates

    def _candidates(self) -> List[Candidate]:
        pods_by_node = self.cluster.bound_pods()
        nodepools = {p.name: p for p in self.store.list(st.NODEPOOLS)}
        out: List[Candidate] = []
        for sn in self.cluster.state_nodes():
            claim, node = sn.claim, sn.node
            if claim is None or node is None:
                continue
            if not claim.initialized or claim.meta.deleting or node.meta.deleting:
                continue
            np_obj = nodepools.get(claim.nodepool)
            if np_obj is None:
                continue
            if node.meta.annotations.get(wk.DO_NOT_DISRUPT_ANNOTATION) == "true":
                continue
            if self.cluster.is_nominated(node.meta.name):
                continue
            pods = pods_by_node.get(node.meta.name, [])
            if any(p.meta.annotations.get(wk.DO_NOT_DISRUPT_ANNOTATION) == "true" for p in pods):
                continue
            if any(not self.eviction.can_evict(p) for p in pods if p.owner_kind != "DaemonSet"):
                continue  # PDB-blocked (disruption.md:335-409)
            resched = [p for p in pods if p.owner_kind != "DaemonSet"]
            age = self.clock() - claim.meta.creation_timestamp
            # disruption cost: fewer/cheaper-to-move pods first; ties by age
            # (older first) then name for determinism
            cost = float(
                sum(1 + p.priority / 1000.0 for p in resched)
            )
            out.append(
                Candidate(claim=claim, node=node, pods=resched, price=claim.price, cost=cost)
            )
        out.sort(key=lambda c: (c.cost, -(self.clock() - c.claim.meta.creation_timestamp), c.claim.name))
        return out

    # ---------------------------------------------------------------- budget

    def _budget_allowance(self, candidates: List[Candidate]) -> Dict[Tuple[str, str], int]:
        """(nodepool, reason) -> how many more nodes may be disrupted now
        (disruption.md:274-330; default 10%)."""
        nodepools = {p.name: p for p in self.store.list(st.NODEPOOLS)}
        total_by_pool: Dict[str, int] = {}
        disrupting_by_pool: Dict[str, int] = {}
        for sn in self.cluster.state_nodes():
            if sn.claim is None:
                continue
            pool = sn.claim.nodepool
            total_by_pool[pool] = total_by_pool.get(pool, 0) + 1
            if sn.claim.meta.deleting or (
                sn.node is not None and any(t.key == wk.DISRUPTED_TAINT_KEY for t in sn.node.taints)
            ):
                disrupting_by_pool[pool] = disrupting_by_pool.get(pool, 0) + 1
        out: Dict[Tuple[str, str], int] = {}
        for pool_name, np_obj in nodepools.items():
            total = total_by_pool.get(pool_name, 0)
            disrupting = disrupting_by_pool.get(pool_name, 0)
            for reason in ("Drifted", "Empty", "Underutilized"):
                allowed = None
                for b in np_obj.disruption.budgets:
                    if b.reasons is not None and reason not in b.reasons:
                        continue
                    if b.nodes.endswith("%"):
                        cap = math.ceil(total * int(b.nodes[:-1]) / 100.0)
                    else:
                        cap = int(b.nodes)
                    allowed = cap if allowed is None else min(allowed, cap)
                if allowed is None:
                    allowed = math.ceil(total * 0.10)
                out[(pool_name, reason)] = max(0, allowed - disrupting)
        return out

    @staticmethod
    def _reason(method: str) -> str:
        return {
            "drifted": "Drifted",
            "empty": "Empty",
            "multi-consolidation": "Underutilized",
            "single-consolidation": "Underutilized",
        }[method]

    def _within_budget(self, cands: Sequence[Candidate], method: str, budgets) -> bool:
        reason = self._reason(method)
        need: Dict[str, int] = {}
        for c in cands:
            need[c.claim.nodepool] = need.get(c.claim.nodepool, 0) + 1
        return all(budgets.get((pool, reason), 0) >= n for pool, n in need.items())

    # ------------------------------------------------------------- evaluate

    def _evaluate(self, method: str, candidates: List[Candidate], budgets) -> Optional[Command]:
        if method == "drifted":
            for c in candidates:
                if not c.claim.drifted:
                    continue
                if not self._within_budget([c], method, budgets):
                    continue
                ok, claim_res = self._simulate([c], allow_replacement=True, require_cheaper=False)
                if ok:
                    names = [self._create_replacement(claim_res)] if claim_res else []
                    return Command(method, [c], replacement_names=names)
            return None

        if method == "empty":
            policies = {p.name: p.disruption for p in self.store.list(st.NODEPOOLS)}
            empties = []
            for c in candidates:
                if c.pods:
                    continue
                pol = policies.get(c.claim.nodepool)
                if pol is None or pol.consolidation_policy not in (
                    "WhenEmpty",
                    "WhenEmptyOrUnderutilized",
                ):
                    continue
                if self.clock() - c.claim.last_transition < pol.consolidate_after_s:
                    continue
                empties.append(c)
            # batch all in-budget empties into one command (reference deletes
            # empty nodes in bulk)
            allowed = [c for c in empties if self._within_budget([c], method, budgets)]
            picked: List[Candidate] = []
            for c in allowed:
                if self._within_budget(picked + [c], method, budgets):
                    picked.append(c)
            if picked:
                return Command(method, picked)
            return None

        consolidatable = [
            c
            for c in candidates
            if self._consolidation_enabled(c) and self._consolidate_after_ok(c)
        ]
        verdicts = self._batched_verdicts(method, consolidatable, budgets)
        if method == "multi-consolidation":
            pool = consolidatable[: self.multi_node_max_candidates]
            if verdicts is not None:
                # all prefixes were evaluated in one vmapped batch; take the
                # largest feasible one (same answer the binary search finds)
                for k in range(len(pool), 1, -1):
                    v = verdicts.get(k)
                    if v is None or not self._within_budget(pool[:k], method, budgets):
                        continue
                    old_price = sum(c.price for c in pool[:k])
                    if v.has_replacement and (
                        v.replacement_price is None or v.replacement_price >= old_price
                    ):
                        continue
                    ok, claim_res = self._simulate(pool[:k], allow_replacement=True, require_cheaper=True)
                    if ok:
                        names = [self._create_replacement(claim_res)] if claim_res else []
                        return Command(method, pool[:k], replacement_names=names)
                return None
            # sequential: binary search the largest cost-ordered prefix that
            # consolidates (>=2 deletes, <=1 cheaper replacement)
            lo, hi = 2, len(pool)
            best = None
            while lo <= hi:
                mid = (lo + hi) // 2
                subset = pool[:mid]
                if self._within_budget(subset, method, budgets):
                    ok, claim_res = self._simulate(subset, allow_replacement=True, require_cheaper=True)
                else:
                    ok, claim_res = False, None
                if ok:
                    best = (subset, claim_res)
                    lo = mid + 1
                else:
                    hi = mid - 1
            if best is not None:
                subset, claim_res = best
                names = [self._create_replacement(claim_res)] if claim_res else []
                return Command(method, subset, replacement_names=names)
            return None

        # single-node consolidation
        for i, c in enumerate(consolidatable):
            if not self._within_budget([c], method, budgets):
                continue
            if verdicts is not None:
                v = verdicts.get(i)
                if v is None or not v.ok:
                    continue
                if v.has_replacement:
                    if v.replacement_price is None or v.replacement_price >= c.price:
                        continue
                    if (
                        c.claim.capacity_type == wk.CAPACITY_TYPE_SPOT
                        and v.replacement_type_count < 15
                    ):
                        continue
            ok, claim_res = self._simulate([c], allow_replacement=True, require_cheaper=True)
            if ok and self._spot_flexibility_ok_res(c, claim_res):
                names = [self._create_replacement(claim_res)] if claim_res else []
                return Command(method, [c], replacement_names=names)
        return None

    def _batched_verdicts(self, method: str, consolidatable: List[Candidate], budgets):
        """One vmapped evaluation of every subset this method will consider.
        Returns {key: SubsetVerdict} or None (no TPU backend / inexpressible
        constraints). Keys: candidate index (single) or prefix length (multi)."""
        if self._batched is None or not consolidatable:
            return None
        if method not in ("multi-consolidation", "single-consolidation"):
            return None
        import dataclasses as _dc

        if self._provisioner_helper is None:
            self._provisioner_helper = Provisioner(
                self.store, self.cluster, self.cloud_provider, self.solver,
                batch_idle_s=0, batch_max_s=0, clock=self.clock,
            )
        base = self._provisioner_helper.build_input([])
        candidate_pods = {
            i: [_dc.replace(p, node_name=None, phase="Pending") for p in c.pods]
            for i, c in enumerate(consolidatable)
        }
        candidate_node = {i: c.node.meta.name for i, c in enumerate(consolidatable)}
        if method == "single-consolidation":
            subsets = [[i] for i in range(len(consolidatable))]
            keys = list(range(len(consolidatable)))
        else:
            pool_n = min(len(consolidatable), self.multi_node_max_candidates)
            if pool_n < 2:
                return None
            subsets = [list(range(k)) for k in range(2, pool_n + 1)]
            keys = list(range(2, pool_n + 1))
        try:
            verdicts = self._batched.evaluate(base, candidate_pods, candidate_node, subsets)
        except Exception:
            return None
        if verdicts is None:
            return None
        return dict(zip(keys, verdicts))

    def _consolidation_enabled(self, c: Candidate) -> bool:
        for p in self.store.list(st.NODEPOOLS):
            if p.name == c.claim.nodepool:
                return p.disruption.consolidation_policy == "WhenEmptyOrUnderutilized"
        return False

    def _consolidate_after_ok(self, c: Candidate) -> bool:
        for p in self.store.list(st.NODEPOOLS):
            if p.name == c.claim.nodepool:
                return self.clock() - c.claim.last_transition >= p.disruption.consolidate_after_s
        return False

    def _spot_flexibility_ok_res(self, c: Candidate, claim_res) -> bool:
        """Spot->spot replacement needs >=15 cheaper types (disruption.md:
        133-137) so consolidation doesn't chase the spot market's tail."""
        if c.claim.capacity_type != wk.CAPACITY_TYPE_SPOT or claim_res is None:
            return True
        ct = claim_res.requirements.get(wk.CAPACITY_TYPE_LABEL)
        if ct is not None and not ct.has(wk.CAPACITY_TYPE_SPOT):
            return True
        return len(claim_res.instance_type_names) >= 15

    # ------------------------------------------------------------- simulate

    def _simulate(
        self, cands: List[Candidate], allow_replacement: bool, require_cheaper: bool
    ):
        """Re-solve with the candidates' pods pending and the candidates
        removed (SURVEY.md §3.2 HOT LOOP #2). Success iff nothing is
        unschedulable, <=1 new claim results, and (if required) the
        replacement is cheaper than the removed capacity. Returns
        (ok, claim_result_or_None); the caller materializes the replacement
        NodeClaim only for the command it actually executes (binary-search
        probes must not leak claims)."""
        if self._provisioner_helper is None:
            self._provisioner_helper = Provisioner(
                self.store, self.cluster, self.cloud_provider, self.solver,
                batch_idle_s=0, batch_max_s=0, clock=self.clock,
            )
        import dataclasses

        # simulate the candidates' pods as pending (they are bound right now;
        # the scheduler rightly ignores bound pods)
        pods = [
            dataclasses.replace(p, node_name=None, phase="Pending")
            for c in cands
            for p in c.pods
        ]
        removed = {c.node.meta.name for c in cands}
        inp = self._provisioner_helper.build_input(pods)
        inp.nodes = [n for n in inp.nodes if n.id not in removed]
        result = self.solver.solve(inp)
        if result.errors:
            return False, None
        if len(result.claims) > 1:
            return False, None
        if not allow_replacement and result.claims:
            return False, None
        if result.claims:
            claim_res = result.claims[0]
            if require_cheaper:
                new_price = self._min_price(claim_res)
                old_price = sum(c.price for c in cands)
                if new_price is None or new_price >= old_price:
                    return False, None
            return True, claim_res
        return True, None

    def _min_price(self, claim_res) -> Optional[float]:
        types = {it.name: it for it in self.cloud_provider.get_instance_types("")}
        best = None
        for tn in claim_res.instance_type_names:
            it = types.get(tn)
            if it is None:
                continue
            o = it.cheapest_available(claim_res.requirements)
            if o is not None and (best is None or o.price < best):
                best = o.price
        return best

    def _create_replacement(self, claim_res) -> str:
        nodepools = {p.name: p for p in self.store.list(st.NODEPOOLS)}
        np_obj = nodepools[claim_res.nodepool]
        self._provisioner_helper._claim_seq += 1
        name = f"{claim_res.nodepool}-r{self._provisioner_helper._claim_seq:05d}"
        reqs = type(claim_res.requirements)(claim_res.requirements)
        reqs.add(Requirement.create(wk.INSTANCE_TYPE_LABEL, IN, claim_res.instance_type_names))
        from ..api.objects import NodeClaim, ObjectMeta

        claim = NodeClaim(
            meta=ObjectMeta(
                name=name,
                labels={wk.NODEPOOL_LABEL: claim_res.nodepool},
                finalizers=[wk.TERMINATION_FINALIZER],
            ),
            nodepool=claim_res.nodepool,
            node_class_ref=np_obj.template.node_class_ref,
            requirements=reqs,
            resource_requests=claim_res.requests,
            taints=list(np_obj.template.taints),
            startup_taints=list(np_obj.template.startup_taints),
            expire_after_s=np_obj.template.expire_after_s,
            instance_type_options=list(claim_res.instance_type_names),
        )
        self.store.create(st.NODECLAIMS, claim)
        return name

    # -------------------------------------------------------------- execute

    def _execute(self, cmd: Command) -> None:
        for c in cmd.candidates:
            node = self.store.try_get(st.NODES, c.node.meta.name)
            if node is not None and not any(t.key == wk.DISRUPTED_TAINT_KEY for t in node.taints):
                node.taints.append(Taint(key=wk.DISRUPTED_TAINT_KEY, effect=wk.EFFECT_NO_SCHEDULE))
                node.unschedulable = True
                self.store.update(st.NODES, node)
        cmd.created_at = self.clock()
        self._command = cmd
        DISRUPTION_DECISIONS.inc(decision="delete" if not cmd.replacement_names else "replace",
                                 reason=self._reason(cmd.method))
        if not cmd.replacement_names:
            self._finish_command()  # no replacement to wait for

    def _progress_command(self) -> bool:
        cmd = self._command
        assert cmd is not None
        replacements = [self.store.try_get(st.NODECLAIMS, n) for n in cmd.replacement_names]
        if any(r is None for r in replacements):
            self._rollback("replacement disappeared")
            return True
        if all(r.initialized for r in replacements):
            self._finish_command()
            return True
        if self.clock() - cmd.created_at > self.replacement_timeout_s:
            self._rollback("replacement failed to initialize in time")
            return True
        return False  # keep waiting

    def _finish_command(self) -> None:
        cmd = self._command
        self._command = None
        if cmd is None:
            return
        for c in cmd.candidates:
            try:
                self.store.delete(st.NODECLAIMS, c.claim.name)
            except st.NotFound:
                pass

    def _rollback(self, why: str) -> None:
        """Untaint candidates; delete replacements (disruption.md:15-28)."""
        cmd = self._command
        self._command = None
        if cmd is None:
            return
        for c in cmd.candidates:
            node = self.store.try_get(st.NODES, c.node.meta.name)
            if node is not None:
                node.taints = [t for t in node.taints if t.key != wk.DISRUPTED_TAINT_KEY]
                node.unschedulable = False
                self.store.update(st.NODES, node)
        for name in cmd.replacement_names:
            if self.store.try_get(st.NODECLAIMS, name) is not None:
                try:
                    self.store.delete(st.NODECLAIMS, name)
                except st.NotFound:
                    pass
