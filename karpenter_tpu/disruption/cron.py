"""Minimal 5-field cron matcher for disruption budget schedules
(website/.../concepts/disruption.md:274-330: budgets carry an optional
`schedule` cron + `duration`; the budget constrains only inside the window
[match, match+duration], evaluated in UTC).

Supports: "*", numbers, ranges "a-b", steps "*/n" and "a-b/n", and comma
lists — the subset the reference's budget examples use.
"""

from __future__ import annotations

from datetime import datetime, timedelta, timezone
from typing import List, Optional


def _parse_field(spec: str, lo: int, hi: int) -> Optional[frozenset]:
    vals: set = set()
    for part in spec.split(","):
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            if not step_s.isdigit() or int(step_s) < 1:
                return None
            step = int(step_s)
        if part == "*":
            start, end = lo, hi
        elif "-" in part:
            a, _, b = part.partition("-")
            if not (a.isdigit() and b.isdigit()):
                return None
            start, end = int(a), int(b)
        elif part.isdigit():
            start = end = int(part)
        else:
            return None
        if start < lo or end > hi or start > end:
            return None
        vals.update(range(start, end + 1, step))
    return frozenset(vals)


class Cron:
    """Parsed 5-field cron expression (minute hour dom month dow)."""

    def __init__(self, expr: str):
        fields = expr.split()
        if len(fields) != 5:
            raise ValueError(f"cron needs 5 fields: {expr!r}")
        bounds = [(0, 59), (0, 23), (1, 31), (1, 12), (0, 7)]
        parsed: List[frozenset] = []
        for f, (lo, hi) in zip(fields, bounds):
            p = _parse_field(f, lo, hi)
            if p is None:
                raise ValueError(f"bad cron field {f!r} in {expr!r}")
            parsed.append(p)
        self.minute, self.hour, self.dom, self.month, self.dow = parsed
        if 7 in self.dow:  # standard cron: 7 is Sunday too
            self.dow = self.dow | frozenset([0])
        # kube cron quirk: dom and dow are OR'd when both are restricted
        self._dom_star = self.dom == frozenset(range(1, 32))
        self._dow_star = frozenset(range(0, 7)) <= self.dow

    def matches(self, dt: datetime) -> bool:
        if dt.minute not in self.minute or dt.hour not in self.hour:
            return False
        if dt.month not in self.month:
            return False
        dom_ok = dt.day in self.dom
        dow_ok = dt.isoweekday() % 7 in self.dow  # cron: 0 = Sunday
        if self._dom_star or self._dow_star:
            return dom_ok and dow_ok
        return dom_ok or dow_ok


def in_window(expr: str, duration_s: float, now_epoch: float) -> bool:
    """True iff some cron match t0 satisfies t0 <= now < t0 + duration.
    Scans minute marks backwards over the duration (UTC, like the
    reference's budget schedules)."""
    cron = Cron(expr)
    now = datetime.fromtimestamp(now_epoch, tz=timezone.utc)
    mark = now.replace(second=0, microsecond=0)
    steps = int(duration_s // 60) + 1
    for _ in range(min(steps, 60 * 24 * 32)):  # bound: one month of minutes
        if cron.matches(mark):
            start = mark.timestamp()
            if start <= now_epoch < start + duration_s:
                return True
        mark -= timedelta(minutes=1)
    return False
