"""TPU-batched consolidation evaluation.

Wraps solver/tpu/consolidate.py for the disruption controller: encodes the
simulation universe ONCE (all candidates' pods pending, all nodes present),
then evaluates candidate subsets as one vmapped batch. Used as a fast filter
— the winning subset is re-materialized through the sequential simulate path,
so command construction (and therefore behavior) is bit-identical to the
reference-style sequential evaluation; only wall-clock changes.

Falls back (returns None) when the universe contains constructs the device
kernel can't express (topology/affinity/fallback groups — encode.py).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..provisioning.scheduler import SolverInput, ffd_sort
from ..solver.backend import TPUSolver, kernel_args
from ..solver.encode import UnpackableInput, encode, quantize_input
from ..solver.tpu.consolidate import replacement_min_price, simulate_subsets


@dataclasses.dataclass
class SubsetVerdict:
    ok: bool  # feasible (everything reschedules, <=1 new claim)
    has_replacement: bool
    replacement_price: Optional[float]  # cheapest offering of the new claim
    replacement_type_count: int  # surviving instance types (spot >=15 rule)


class BatchedConsolidationEvaluator:
    def __init__(self, solver: TPUSolver, max_claims: int = 16):
        self.solver = solver
        self.max_claims = max_claims

    def evaluate(
        self,
        base_input: SolverInput,
        candidate_pods: Dict[int, list],  # candidate id -> pods (unbound copies)
        candidate_node: Dict[int, str],  # candidate id -> existing-node id
        subsets: Sequence[Sequence[int]],
    ) -> Optional[List[SubsetVerdict]]:
        all_pods = [p for pods in candidate_pods.values() for p in pods]
        inp = dataclasses.replace(base_input, pods=all_pods)
        enc = encode(quantize_input(inp))
        if enc.group_fallback.any() or enc.has_topology or enc.has_affinity or enc.G == 0:
            return None

        # (group, candidate)-granular runs following the exact FFD order
        uid_to_cid = {
            p.meta.uid: cid for cid, pods in candidate_pods.items() for p in pods
        }
        uid_to_gid = {
            p.meta.uid: g for g, pods in enumerate(enc.group_pods) for p in pods
        }
        pods_sorted = ffd_sort(all_pods)
        run_group: List[int] = []
        run_count: List[int] = []
        run_cand: List[int] = []
        for p in pods_sorted:
            g, c = uid_to_gid[p.meta.uid], uid_to_cid[p.meta.uid]
            if run_group and run_group[-1] == g and run_cand[-1] == c:
                run_count[-1] += 1
            else:
                run_group.append(g)
                run_count.append(1)
                run_cand.append(c)
        enc.run_group = np.asarray(run_group, dtype=np.int32)
        enc.run_count = np.asarray(run_count, dtype=np.int32)

        try:
            args, dims = kernel_args(enc, self.solver._bucket)
        except UnpackableInput:
            return None  # Z*C > 32 — sequential path takes over
        Sp = len(np.asarray(args[0]))
        run_candidate = np.full(Sp, -1, dtype=np.int32)
        run_candidate[: len(run_cand)] = run_cand

        id_to_e = {nid: e for e, nid in enumerate(enc.node_ids)}
        node_idx = {cid: id_to_e[nid] for cid, nid in candidate_node.items()
                    if nid in id_to_e}
        # Removed candidates' bound pods are re-posed as pending; their share
        # of the initial zone counts must come OUT per subset, or zone-TSC/
        # anti verdicts double-count them vs the sequential simulate (which
        # removes the node object entirely) — VERDICT r3 "what's weak" #1.
        v_delta = None
        if enc.V:
            v_delta = {}
            for cid, e in node_idx.items():
                z = int(enc.node_zone[e])
                if z < 0:
                    continue
                d = np.zeros((enc.V, len(enc.zones)), dtype=np.int32)
                d[:, z] = enc.node_v_member[e]
                if d.any():
                    v_delta[cid] = d
        out = simulate_subsets(args, run_candidate, subsets, node_idx, self.max_claims,
                               candidate_v_delta=v_delta)

        T, Z, C = enc.T, len(enc.zones), len(enc.capacity_types)
        used = np.asarray(out.state.used)
        leftover = np.asarray(out.leftover).sum(axis=1)
        c_mask = np.asarray(out.state.c_mask)[:, :, :T]
        from ..solver.backend import unpack_zc_bits

        zc_bits = np.asarray(out.state.c_zc_bits)  # [B, M]
        B_, M_ = zc_bits.shape
        c_zone_flat, c_ct_flat = unpack_zc_bits(zc_bits.reshape(-1), Z, C)
        c_zone = c_zone_flat.reshape(B_, M_, Z)
        c_ct = c_ct_flat.reshape(B_, M_, C)
        verdicts: List[SubsetVerdict] = []
        for b in range(len(subsets)):
            feasible = leftover[b] == 0 and used[b] <= 1
            price = None
            type_count = 0
            if feasible and used[b] == 1:
                price = replacement_min_price(
                    c_mask[b, 0], c_zone[b, 0], c_ct[b, 0], enc.offer_avail, enc.offer_price
                )
                type_count = int(c_mask[b, 0].sum())
                if price is None:
                    feasible = False
            verdicts.append(
                SubsetVerdict(
                    ok=bool(feasible),
                    has_replacement=bool(used[b] == 1),
                    replacement_price=price,
                    replacement_type_count=type_count,
                )
            )
        return verdicts
