"""TPU-batched consolidation evaluation.

Wraps solver/tpu/consolidate.py for the disruption controller: encodes the
simulation universe ONCE (all candidates' pods pending, all nodes present),
then evaluates candidate subsets as vmapped batches. Used as a fast filter
— the winning subset is re-materialized through the sequential simulate path,
so command construction (and therefore behavior) is bit-identical to the
reference-style sequential evaluation; only wall-clock changes.

prepare() builds and uploads the shared universe once; evaluate_prepared()
dispatches one batch of subsets against it — the controller's speculative
binary replay (speculative_binary_search; config 5: 10k-node multi-node
consolidation) issues 1-2 batched dispatches against a single prepared
universe instead of one sequential round-trip per binary-search probe.
tiered_prefix_search (the previous largest-acceptable ladder) remains for
callers that want maximal-prefix semantics rather than binary-search parity.

Falls back (returns None) when the universe contains constructs the device
kernel can't express (fallback groups / off-device topology-affinity forms —
encode.py). Zone-granular constraints (V axis) ARE expressible: each subset
row subtracts its removed candidates' zone-count contributions.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import faults
from ..metrics.registry import PROBE_BATCH_SIZE
from ..provisioning.scheduler import SolverInput
from ..solver.backend import TPUSolver, host_kernel_args, unpack_zc_bits
from ..solver.encode import UnpackableInput, encode, quantize_input
from ..solver.tpu.consolidate import (
    _V_COUNT0,
    fetch_verdicts,
    replacement_min_price,
    simulate_subsets,
)


@dataclasses.dataclass
class SubsetVerdict:
    ok: bool  # feasible (everything reschedules, <=1 new claim)
    has_replacement: bool
    replacement_price: Optional[float]  # cheapest offering of the new claim
    replacement_type_count: int  # surviving instance types (spot >=15 rule)


def tiered_prefix_search(evaluate_ks, n_max: int, acceptable, width: int = 64):
    """Largest-acceptable-prefix search over prefix lengths [2, n_max].

    evaluate_ks(ks) -> verdicts for prefixes of those lengths;
    acceptable(k, verdict) -> bool. Phase 1 probes ≤width evenly spaced
    lengths over the whole range; each later phase refines between the
    largest accepted probe and the next probe above it, until the gap is
    fully enumerated — O(log_width(N)) batched dispatches instead of O(N)
    sequential re-solves (config 5). Shared by the disruption controller
    and bench.py so the measured loop IS the production loop.

    width=64 makes fleets up to ~width² (≈4k) candidates exactly TWO
    dispatches (ladder + one enumerated gap): on a tunneled link each
    dispatch costs a ~70-80 ms roundtrip, which dominates the kernel, while
    the wider batch row count is nearly free on device.

    Returns (k_best — 1 when nothing accepted, probed {k: verdict},
    dispatches)."""
    probed: Dict[int, object] = {}
    k_lo, k_hi = 1, n_max + 1
    dispatches = 0
    while k_hi - k_lo > 1:
        span = [k for k in range(k_lo + 1, k_hi) if k not in probed]
        if not span:
            break
        if len(span) > width:
            step = (len(span) - 1) / (width - 1)
            ks = sorted({span[int(round(i * step))] for i in range(width)})
        else:
            ks = span
        verdicts = evaluate_ks(ks)
        dispatches += 1
        for k, v in zip(ks, verdicts):
            probed[k] = v
        acc = [k for k in ks if acceptable(k, probed[k])]
        if acc:
            k_lo = max(acc)
            higher = [k for k in probed if k > k_lo]
            k_hi = min(higher) if higher else k_hi
        else:
            k_hi = min(ks)
    return k_lo, probed, dispatches


def binary_probe_frontier(lo: int, hi: int, levels: int) -> List[int]:
    """Every prefix length the sequential binary search over [lo, hi] can
    probe within its first `levels` iterations — the top of its decision
    tree. Enumerable WITHOUT verdicts: each probe's (lo, hi) interval is
    fully determined by the accept/reject outcomes above it, and the tree
    covers both outcomes of every node. Level d holds ≤ 2^(d-1) mids, so
    `levels` levels cost ≤ 2^levels − 1 rows."""
    out: List[int] = []
    frontier = [(lo, hi)]
    for _ in range(max(0, levels)):
        nxt: List[Tuple[int, int]] = []
        for l, h in frontier:
            if l > h:
                continue
            m = (l + h) // 2
            out.append(m)
            nxt.append((m + 1, h))  # accepted: search above
            nxt.append((l, m - 1))  # rejected: search below
        if not nxt:
            break
        frontier = nxt
    return sorted(set(out))


def speculative_binary_search(
    evaluate_ks, lo: int, hi: int, acceptable, probe_batch_max: int = 512
):
    """Decision-for-decision replay of the sequential binary search

        while lo <= hi:
            mid = (lo + hi) // 2
            if acceptable(mid): best = mid; lo = mid + 1
            else:               hi = mid - 1

    with the probe frontier evaluated in BATCHED dispatches instead of one
    round-trip per probe. When the remaining interval fits `probe_batch_max`
    every prefix in it is evaluated at once (all O(n) prefixes in a bucket);
    otherwise one dispatch covers the top levels of the binary decision tree
    (all candidate mids of those levels — speculative: half are on paths
    the replay won't take) and the replay consumes cached verdicts until it
    runs dry. One tree dispatch narrows the interval by 2^levels, so any
    fleet up to ~probe_batch_max² candidates resolves in ≤ 2 dispatches.

    Because the replay consumes verdicts in exactly the sequential order,
    the returned best_k is IDENTICAL to the sequential search's — batching
    changes wall-clock, never the decision.

    evaluate_ks(ks) -> verdict per k (the caller decides what a verdict is
    and whether some ks can be answered without touching the device, e.g.
    budget-clamped prefixes). Returns (best_k | None, probed {k: verdict},
    eval_batches)."""
    probe_batch_max = max(1, int(probe_batch_max))
    # 2^levels − 1 ≤ probe_batch_max: the deepest full tree that fits a batch
    levels = max(1, (probe_batch_max + 1).bit_length() - 1)
    probed: Dict[int, object] = {}
    batches = 0
    best: Optional[int] = None
    while lo <= hi:
        mid = (lo + hi) // 2
        if mid not in probed:
            if hi - lo + 1 <= probe_batch_max:
                ks = [k for k in range(lo, hi + 1) if k not in probed]
            else:
                ks = [
                    k
                    for k in binary_probe_frontier(lo, hi, levels)
                    if k not in probed
                ]
            verdicts = evaluate_ks(ks)
            batches += 1
            for k, v in zip(ks, verdicts):
                probed[k] = v
        if acceptable(mid, probed[mid]):
            best = mid
            lo = mid + 1
        else:
            hi = mid - 1
    return best, probed, batches


@dataclasses.dataclass
class PreparedUniverse:
    enc: object  # EncodedInput
    args: tuple  # device-resident shared kernel args (ffd.ARG_SPEC order)
    pod_cand: np.ndarray  # [N] int64 — candidate id per pod, FFD order
    pod_run: np.ndarray  # [N] int64 — natural run index per pod, FFD order
    node_idx: Dict[int, int]  # candidate id -> E index
    v_delta: Optional[Dict[int, np.ndarray]]  # cid -> [V, Z] zone-count share
    v_count0_host: Optional[np.ndarray] = None  # host copy (per-dispatch base)


class BatchedConsolidationEvaluator:
    def __init__(self, solver: TPUSolver, max_claims: int = 16):
        self.solver = solver
        self.max_claims = max_claims

    def prepare(
        self,
        base_input: SolverInput,
        candidate_pods: Dict[int, list],  # candidate id -> pods (unbound copies)
        candidate_node: Dict[int, str],  # candidate id -> existing-node id
    ) -> Optional[PreparedUniverse]:
        import jax

        all_pods = [p for pods in candidate_pods.values() for p in pods]
        inp = dataclasses.replace(base_input, pods=all_pods)
        enc = encode(quantize_input(inp))
        if enc.group_fallback.any() or enc.has_topology or enc.has_affinity or enc.G == 0:
            return None
        # positive hostname affinity (kind 2) is handled on the batched path
        # too: the evaluator zeroes removed nodes' node_q_member/node_q_owner
        # ROWS per subset on device (consolidate._batched_ffd_core), so the
        # kernel's global member sums (tot_m_q — the bootstrap check) match
        # the sequential simulate's node deletion exactly.

        # Runs stay at NATURAL group granularity (enc.run_group/run_count):
        # same-group pods are fungible, so each subset is expressed as
        # per-run member COUNTS — the device scan length stays O(distinct
        # pod specs) instead of O(candidates) (config 5: 2000 candidates
        # collapse to ~#groups scan steps).
        uid_to_cid = {
            p.meta.uid: cid for cid, pods in candidate_pods.items() for p in pods
        }
        pod_cand = np.fromiter(
            (uid_to_cid[u] for u in enc.sorted_uids), np.int64, len(enc.sorted_uids)
        )
        pod_run = np.repeat(
            np.arange(len(enc.run_count), dtype=np.int64), enc.run_count
        )

        try:
            host_args, dims, prov = host_kernel_args(enc, self.solver._bucket)
        except UnpackableInput:
            return None  # Z*C > 32 — sequential path takes over
        v_count0_host = host_args[_V_COUNT0]
        # upload the shared arrays once — replicated across the candidate
        # mesh when one exists, so per-dispatch traffic is the batched axes
        # only, never the constant universe. With the solver's argument
        # arena, the universe adopts INTO it: shape-identical universes
        # (re-prepares within one disruption tick, or the single-solve
        # path's bucket) share residency and upload only stale entries as
        # one packed buffer; the mesh sharding keys a separate bucket so
        # replicated and single-device buffers never mix.
        arena = getattr(self.solver, "arena", None)
        if arena is not None:
            from ..solver.tpu.consolidate import universe_sharding

            args = arena.adopt(host_args, prov, sharding=universe_sharding())
        else:
            from ..solver.tpu.consolidate import replicate_shared

            args = replicate_shared(tuple(host_args))

        id_to_e = {nid: e for e, nid in enumerate(enc.node_ids)}
        node_idx = {cid: id_to_e[nid] for cid, nid in candidate_node.items()
                    if nid in id_to_e}
        # Removed candidates' bound pods are re-posed as pending; their share
        # of the initial zone counts must come OUT per subset, or zone-TSC/
        # anti verdicts double-count them vs the sequential simulate (which
        # removes the node object entirely) — VERDICT r3 "what's weak" #1.
        v_delta = None
        if enc.V:
            v_delta = {}
            n_dom = len(enc.v_domains) if enc.v_domains is not None else len(enc.zones)
            for cid, e in node_idx.items():
                z = int(enc.v_node_domain[e])
                z2 = (
                    int(enc.node_dom2[e]) if enc.node_dom2 is not None else -1
                )
                if z < 0 and z2 < 0:
                    continue
                d = np.zeros((enc.V, n_dom), dtype=np.int32)
                if z >= 0:
                    d[:, z] = enc.node_v_member[e]
                if z2 >= 0:
                    # mixed-axis universes: the node contributed to BOTH its
                    # zone and its ct column (encode fills both) — subtract
                    # both or ct-sig verdicts double-count removed pods
                    d[:, z2] = enc.node_v_member[e]
                if d.any():
                    v_delta[cid] = d
        return PreparedUniverse(
            enc=enc, args=args, pod_cand=pod_cand, pod_run=pod_run,
            node_idx=node_idx, v_delta=v_delta, v_count0_host=v_count0_host,
        )

    def evaluate_prepared_async(
        self, prep: PreparedUniverse, subsets: Sequence[Sequence[int]]
    ):
        """Dispatch one probe batch; returns a finish() callable that blocks
        on the device→host fetch and builds the verdicts. The split lets the
        pipelined solve service run the dispatch on its dispatcher thread
        and the fetch/decode on its decoder thread, like any other solve.
        The probe batch passes the same `solver.device_dispatch` fault site
        as single solves, so chaos plans kill it too."""
        faults.check("solver.device_dispatch")
        PROBE_BATCH_SIZE.observe(len(subsets))
        enc = prep.enc
        out = simulate_subsets(
            prep.args, prep.pod_cand, prep.pod_run, subsets, prep.node_idx,
            self.max_claims, candidate_v_delta=prep.v_delta, verdict_only=True,
            zone_engine=enc.V > 0, v_count0_host=prep.v_count0_host,
        )
        return lambda: self._finish_verdicts(prep, out, len(subsets))

    def evaluate_prepared(
        self, prep: PreparedUniverse, subsets: Sequence[Sequence[int]]
    ) -> List[SubsetVerdict]:
        return self.evaluate_prepared_async(prep, subsets)()

    def _finish_verdicts(
        self, prep: PreparedUniverse, out, n_subsets: int
    ) -> List[SubsetVerdict]:
        enc = prep.enc
        T, Z, C = enc.T, len(enc.zones), len(enc.capacity_types)
        leftover, used, zc_bits, c_mask = fetch_verdicts(out, T, n_subsets)
        B_, M_ = zc_bits.shape
        c_zone_flat, c_ct_flat = unpack_zc_bits(zc_bits.reshape(-1), Z, C)
        c_zone = c_zone_flat.reshape(B_, M_, Z)
        c_ct = c_ct_flat.reshape(B_, M_, C)
        verdicts: List[SubsetVerdict] = []
        for b in range(n_subsets):
            feasible = leftover[b] == 0 and used[b] <= 1
            price = None
            type_count = 0
            if feasible and used[b] == 1:
                # claims open sequentially from slot 0, so used==1 pins the
                # replacement to slot 0 — asserted so a future
                # multi-replacement relaxation cannot silently price the
                # wrong claim (VERDICT r4 weak #6)
                assert not c_mask[b, 1:].any(), (
                    "replacement-claim invariant violated: used==1 but "
                    "higher slots carry surviving types"
                )
                price = replacement_min_price(
                    c_mask[b, 0], c_zone[b, 0], c_ct[b, 0], enc.offer_avail, enc.offer_price
                )
                type_count = int(c_mask[b, 0].sum())
                if price is None:
                    feasible = False
            verdicts.append(
                SubsetVerdict(
                    ok=bool(feasible),
                    has_replacement=bool(used[b] == 1),
                    replacement_price=price,
                    replacement_type_count=type_count,
                )
            )
        return verdicts

    def evaluate(
        self,
        base_input: SolverInput,
        candidate_pods: Dict[int, list],
        candidate_node: Dict[int, str],
        subsets: Sequence[Sequence[int]],
    ) -> Optional[List[SubsetVerdict]]:
        prep = self.prepare(base_input, candidate_pods, candidate_node)
        if prep is None:
            return None
        return self.evaluate_prepared(prep, subsets)
