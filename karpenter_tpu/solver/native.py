"""ctypes bridge to the native FFD core (native/ffd_core.cpp).

Builds the shared library on first use (g++ -O2, cached by source mtime) and
exposes `NativeSolver` — the compiled CPU fallback implementing the same
encoded-tensor contract as the TPU kernel. Third leg of the differential
parity suite (python oracle == native == TPU).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

import numpy as np

from ..provisioning.scheduler import SolverInput, SolverResult
from ..metrics.registry import SOLVER_SOLVES
from ..obs import explain as obsexplain
from .backend import ReferenceSolver, Solver, decode
from .encode import EncodedInput, encode, quantize_input

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "ffd_core.cpp")
_BUILD_DIR = os.path.join(_REPO_ROOT, "native", "build")
_LIB = os.path.join(_BUILD_DIR, "libffd_core.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None


def _build() -> str:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    if not os.path.exists(_LIB) or os.path.getmtime(_LIB) < os.path.getmtime(_SRC):
        subprocess.run(
            ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", _LIB, _SRC],
            check=True,
            capture_output=True,
        )
    return _LIB


def load() -> ctypes.CDLL:
    global _lib
    with _lock:
        if _lib is None:
            lib = ctypes.CDLL(_build())
            i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
            u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
            lib.ffd_solve_native.restype = ctypes.c_int
            lib.ffd_solve_native.argtypes = (
                [ctypes.c_int32] * 12  # dims (incl. DD domain columns)
                + [i32p, i32p]  # runs
                + [i32p, u8p, u8p, u8p, u8p, u8p, u8p]  # groups
                + [i32p, i32p, u8p]  # types
                + [u8p, u8p, u8p, i32p, i32p, i32p]  # pools
                + [i32p, u8p, i32p]  # nodes (free, compat, zone)
                + [u8p, u8p, i32p, i32p, i32p, i32p]  # hostname sigs (Q)
                + [u8p, u8p, i32p, i32p, i32p, i32p, i32p]  # domain sigs (V)
                + [i32p, i32p, i32p]  # mixed-axis: sig_axis, group_daxis, node_ct
                + [i32p, i32p, i32p, u8p, u8p, u8p, u8p, i32p, i32p, i32p]  # outputs
            )
            _lib = lib
    return _lib


def solve_encoded(enc: EncodedInput, max_claims: int = 1024):
    """Run the native core on an (unpadded) EncodedInput; returns the same
    tuple decode() consumes, or None on slot overflow."""
    if enc.V and (np.asarray(enc.v_kind) == 3).any():
        # Kind-3 (admission-only weighted antis, relax-materialized): the
        # C++ core's `v_kind != 1` guards would silently DROP their
        # admission semantics. Unreachable today (weighted antis route to
        # fallback before native), but a future routing change must fall
        # back loudly here, never mis-solve.
        return None
    lib = load()
    S, G, T, E, P = len(enc.run_group), enc.G, enc.T, enc.E, enc.P
    R = enc.group_req.shape[1]
    Z, C = len(enc.zones), len(enc.capacity_types)
    Q, V = enc.Q, enc.V
    M = max_claims
    u8 = lambda a: np.ascontiguousarray(a, dtype=np.uint8)
    i32 = lambda a: np.ascontiguousarray(a, dtype=np.int32)
    INT32_MAX = np.int32(2**31 - 1)
    type_charge = np.where(enc.charge_axes[None, :], enc.type_capacity, 0).astype(np.int32)

    # Domain swap (v_axis == "ct"): the C++ core's "zone" axis is its V-sig
    # domain axis, so capacity-type-granular constraints run by swapping the
    # zone/ct roles at the marshaling boundary — group/pool admission
    # matrices trade places, offer_avail transposes, and the ct side is
    # re-ordered LEX (the core's index-order tiebreaks must match the
    # oracle's string-lex domain tiebreaks). Zero C++ changes; outputs swap
    # back below.
    swap = enc.v_axis == "ct" and V > 0
    mixed = enc.v_axis == "mixed"
    ct_perm = None  # lex permutation of the C axis under mixed mode
    if swap:
        # canonical domain order (enc.v_domain_perm — shared with backend's
        # device column masks)
        perm = enc.v_domain_perm
        inv = np.argsort(perm)
        g_zone = enc.group_ct[:, perm]
        g_ct = enc.group_zone
        p_zone = enc.pool_ct[:, perm]
        p_ct = enc.pool_zone
        offer = enc.offer_avail.transpose(0, 2, 1)[:, perm, :]
        n_zone = enc.v_node_domain
        n_ct = np.full(enc.E, -1, np.int32)
        Zn, Cn = C, Z
    elif mixed:
        # BOTH axes drive domain columns (core arg DD = Z + C): the C axis
        # is permuted to LEX order so ct index == ct domain rank, matching
        # v_count0's column layout (zones, then lex cts) and the core's
        # index-order tiebreaks. Outputs un-permute below.
        ct_perm = sorted(range(C), key=lambda i: enc.capacity_types[i])
        ct_inv = np.argsort(ct_perm)
        g_zone, g_ct = enc.group_zone, enc.group_ct[:, ct_perm]
        p_zone, p_ct = enc.pool_zone, enc.pool_ct[:, ct_perm]
        offer = enc.offer_avail[:, :, ct_perm]
        n_zone = enc.node_zone
        # node's ct DOMAIN rank (lex) — node_dom2 already carries Z + rank
        n_ct = np.where(enc.node_dom2 >= 0, enc.node_dom2 - Z, -1).astype(np.int32)
        Zn, Cn = Z, C
    else:
        g_zone, g_ct = enc.group_zone, enc.group_ct
        p_zone, p_ct = enc.pool_zone, enc.pool_ct
        offer = enc.offer_avail
        n_zone = enc.node_zone
        n_ct = np.full(enc.E, -1, np.int32)
        Zn, Cn = Z, C
    DD = Zn + Cn if mixed else Zn
    # encode always populates these; a silent zeros-default here would
    # misclassify every sig as zone-axis on a mixed solve — fail loudly
    sig_axis, group_daxis = enc.sig_axis, enc.group_daxis

    take_e = np.zeros((S, E), np.int32)
    take_c = np.zeros((S, M), np.int32)
    leftover = np.zeros(S, np.int32)
    c_mask = np.zeros((M, T), np.uint8)
    c_zone = np.zeros((M, Zn), np.uint8)
    c_ct = np.zeros((M, Cn), np.uint8)
    c_gmask = np.zeros((M, G), np.uint8)
    c_pool = np.zeros(M, np.int32)
    c_cum = np.zeros((M, R), np.int32)
    used = np.zeros(1, np.int32)

    rc = lib.ffd_solve_native(
        S, G, T, E, P, R, Zn, Cn, M, Q, V, DD,
        i32(enc.run_group), i32(enc.run_count),
        i32(enc.group_req), u8(enc.group_compat_t), u8(g_zone), u8(g_ct),
        u8(enc.group_pool), u8(enc.group_pair), u8(~enc.group_fallback),
        i32(enc.type_alloc), i32(type_charge), u8(offer),
        u8(enc.pool_type), u8(p_zone), u8(p_ct),
        i32(enc.pool_daemon),
        i32(np.where(enc.pool_limit < 0, INT32_MAX, enc.pool_limit)),
        i32(enc.pool_usage),
        i32(enc.node_free), u8(enc.node_compat), i32(n_zone),
        u8(enc.q_member), u8(enc.q_owner), i32(enc.q_kind), i32(enc.q_cap),
        i32(enc.node_q_member), i32(enc.node_q_owner),
        u8(enc.v_member), u8(enc.v_owner), i32(enc.v_kind), i32(enc.v_cap),
        i32(enc.v_primary), i32(enc.v_aff), i32(enc.v_count0),
        i32(sig_axis), i32(group_daxis), i32(n_ct),
        take_e, take_c, leftover, c_mask, c_zone, c_ct, c_gmask, c_pool, c_cum, used,
    )
    if rc != 0:
        return None
    if swap:
        c_zone, c_ct = c_ct, c_zone[:, inv]
    elif mixed:
        c_ct = c_ct[:, ct_inv]  # un-permute the lex C axis back to cid order
    # decode() argument order: ..., c_pool, c_gmask, c_cum, used
    return take_e, take_c, leftover, c_mask.astype(bool), c_zone.astype(bool), \
        c_ct.astype(bool), c_pool, c_gmask.astype(bool), c_cum, int(used[0])


class NativeSolver(Solver):
    """Compiled CPU solver behind the same seam (fallback: python oracle)."""

    def __init__(self, max_claims: int = 4096, fallback: Optional[Solver] = None):
        self.max_claims = max_claims
        self.fallback = fallback or ReferenceSolver()
        self.stats = {"native_solves": 0, "fallback_solves": 0}

    def solve(self, inp: SolverInput) -> SolverResult:
        qinp = quantize_input(inp)
        enc = encode(qinp)
        if (
            enc.group_fallback.any()
            or enc.has_topology
            or enc.has_affinity
            or enc.G == 0
        ):
            # hostname (Q, incl. kind-2 positive affinity), zone/ct-domain
            # (V) constraints all run in the native core; what still routes
            # to the oracle is the same set the device kernel can't express
            self.stats["fallback_solves"] += 1
            return self.fallback.solve(qinp)  # executor counts itself
        try:
            out = solve_encoded(enc, self.max_claims)
        except (OSError, subprocess.CalledProcessError):
            out = None  # no toolchain / build failure: degrade gracefully
        if out is None:
            self.stats["fallback_solves"] += 1
            return self.fallback.solve(qinp)
        result = decode(enc, *out)
        from .backend import min_values_post_check

        if not min_values_post_check(qinp, result):
            # claim narrowed below a NodePool flexibility floor: replay on
            # the oracle, which enforces minValues during packing
            self.stats["fallback_solves"] += 1
            return self.fallback.solve(qinp)
        self.stats["native_solves"] += 1
        SOLVER_SOLVES.inc(backend="native")
        if obsexplain.enabled():
            obsexplain.capture(qinp, result, "native", enc=enc)
        return result


# ---------------------------------------------------------------------------
# Scheduling classes: host reference planners (ISSUE 9)
# ---------------------------------------------------------------------------
#
# Bit-identical numpy mirrors of the device side kernels in tpu/ffd.py
# (gang_commit / preemption_plan) — the "native host" leg of the 3-way
# parity surface. solver/scheduling_class.py selects these when the inner
# backend is the native core (or as the fallback planner when jax is
# unavailable); tests/test_scheduling_class.py asserts exact equality of
# every output against both the device kernels and the python oracle.


def gang_commit_host(run_placed, run_gang, gang_size, gang_min_ranks):
    """numpy mirror of ffd.gang_commit: per-gang placed counts by segment
    sum over runs, committed iff placed >= min_ranks (> 0)."""
    import numpy as np

    ng = int(np.asarray(gang_size).shape[0])
    run_gang = np.asarray(run_gang, dtype=np.int64)
    placed = np.zeros(ng, np.int32)
    hot = run_gang >= 0
    np.add.at(placed, run_gang[hot],
              np.asarray(run_placed, dtype=np.int32)[hot])
    min_ranks = np.asarray(gang_min_ranks, dtype=np.int32)
    commit = (placed >= min_ranks) & (min_ranks > 0)
    return commit, placed


def preemption_plan_host(node_free, victim_prio, victim_req, victim_ok,
                         node_ok, need, pod_prio):
    """numpy mirror of ffd.preemption_plan: first node (ascending) whose
    free capacity plus the minimal eligible-victim prefix (victims arrive
    pre-sorted by ascending (priority, uid)) covers `need`. Returns
    (node_idx, victim_mask [E, Vm] bool)."""
    import numpy as np

    node_free = np.asarray(node_free, dtype=np.int64)
    victim_prio = np.asarray(victim_prio, dtype=np.int64)
    victim_req = np.asarray(victim_req, dtype=np.int64)
    victim_ok = np.asarray(victim_ok, dtype=bool)
    node_ok = np.asarray(node_ok, dtype=bool)
    need = np.asarray(need, dtype=np.int64)
    E, Vm = victim_prio.shape
    eligible = victim_ok & (victim_prio < int(pod_prio))
    reclaim = np.where(eligible[:, :, None], victim_req, 0)
    cum = node_free[:, None, :] + np.cumsum(reclaim, axis=1)
    fit0 = np.all(node_free >= need[None, :], axis=1)
    fit_at = np.all(cum >= need[None, None, :], axis=2)
    any_fit = node_ok & (fit0 | fit_at.any(axis=1))
    if not any_fit.any():
        return -1, np.zeros((E, Vm), dtype=bool)
    node_idx = int(np.argmax(any_fit))
    take = np.zeros((E, Vm), dtype=bool)
    if not fit0[node_idx]:
        kmin = int(np.argmax(fit_at[node_idx]))
        take[node_idx] = eligible[node_idx] & (np.arange(Vm) <= kmin)
    return node_idx, take
