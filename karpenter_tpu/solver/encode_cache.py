"""Incremental encode cache: delta-patch `_EncodeCore` instead of rebuilding.

The control loop's dominant host cost at scale is re-deriving the encode
tables every tick (solver/encode.py). The existing `_CORE_CACHE` already
serves the *identical-input* case; this layer serves the next delta class
out: the pod set CHANGED, but only within the known signature universe —
pods added to / removed from existing groups, pods bound (they drop out of
the filtered set), disruption simulations re-placing a subset that spans
the same groups. For those, every [G]/[T]/[P]-indexed table in the cached
core is reusable verbatim, because each is a pure function of

    (ordered distinct signature sequence, catalog segment of the cache key)

— the signature covers requests, selectors, affinities, tolerations,
spreads, labels, priority, and volume zones, and the catalog segment covers
pools (content + instance-type identity), daemonsets, axes, and the
preference policy. Only the run split (`run_group`/`run_count`), the pod
lists (`group_pods`), and `sorted_uids` depend on pod multiplicity, and
those are rebuilt from the vectorized FFD sort in O(pods) NumPy.

Invalidation rules (solver/SPEC.md "Encode cache"): any delta the patch
cannot express — catalog/daemonset/axes/policy change, a signature entering
or leaving the universe, a signature-order change, an intern-epoch reset —
falls back to a full `_build_core`. The patch must be SEMANTICS-INVISIBLE:
a patched core feeds `_encode_with_nodes` exactly the arrays a fresh build
would (tests/test_encode_cache.py asserts field-by-field equality).

The cluster store side of the channel is `state/cluster.py:EncodeDeltas`,
which stamps `SolverInput.state_rev`; a matching catalog revision lets the
donor scan skip the deep catalog-key compare.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

# Visible counters for bench/tests: exact-key hits, successful patches,
# full rebuilds, and vault-donor adoptions (the encoder bumps these; reset
# freely between measurements).
STATS: Dict[str, int] = {
    "hits": 0, "patches": 0, "rebuilds": 0, "vault_adopts": 0,
}


def reset_stats() -> None:
    for k in STATS:
        STATS[k] = 0


# Per-table revision tags (solver/arena.py provenance): every full
# `_build_core` stamps its core with the next value, and try_patch's
# dataclasses.replace PRESERVES the donor's stamp because every [G]/[T]/[P]
# table is shared verbatim — so (core_rev, table name) is a content-identity
# token for core-derived kernel args, and a patched encode's static tables
# provably need no re-hash and no re-upload. Monotonic, never reused.
_CORE_REV = 0


def next_core_rev() -> int:
    global _CORE_REV
    _CORE_REV += 1
    return _CORE_REV


# Tenancy (solver/tenancy.py): per-tenant core-cache NAMESPACES. Each tenant
# hits/patches/evicts inside its own dict (same _CORE_CACHE_MAX budget per
# namespace), so one tenant's churn can never evict another's hot core and a
# patch donor can never cross clusters. tenant_id=None maps to the caller's
# default dict (encode.py _CORE_CACHE) so the single-tenant path — including
# tests/bench that clear `em._CORE_CACHE` directly — is byte-identical.
_TENANT_CORE_CACHES: Dict[str, dict] = {}


def tenant_core_cache(tenant_id: Optional[str], default: dict) -> dict:
    if tenant_id is None:
        return default
    cache = _TENANT_CORE_CACHES.get(tenant_id)
    if cache is None:
        cache = _TENANT_CORE_CACHES[tenant_id] = {}
    return cache


def drop_tenant(tenant_id: str) -> None:
    """Release a removed tenant's encode namespace (TenantRegistry.remove)."""
    _TENANT_CORE_CACHES.pop(tenant_id, None)


def try_patch(key, presort, structure, core_cache, state_rev=None):
    """Scan `core_cache` for a donor core with the same catalog segment and
    the same ordered distinct-signature sequence as the new pod set; return
    a patched copy (new run split / pod lists, every derived table shared)
    or None when no delta-compatible donor exists.

    `key` is the new `_core_key` tuple — [2:4] is the deep catalog segment
    (pools, daemonsets) and [4:7] the cheap one (zones, capacity types,
    preference policy; small tuples, always compared). `state_rev` is the
    cluster delta-channel stamp (tracker identity + catalog element); an
    equal stamp prefix proves the DEEP segment's identity without the tuple
    compare — it says nothing about [4:7], which per-call options control.
    """
    from . import encode as enc

    pods_sorted, sigs, sorted_uids, interned = presort
    if not interned:
        return None  # batch-local sig ids: not comparable across solves
    group_pods, run_group, run_count, group_snums = structure
    for k2, ent2 in core_cache.items():
        core2 = ent2[1]
        if core2.sig_epoch != enc._SIG_EPOCH:
            continue  # intern table reset since the donor was built
        if core2.group_snums != group_snums:
            continue  # universe grew/shrank/reordered: not patchable
        if k2[4:7] != key[4:7]:
            continue  # zone/capacity-type universe or preference policy moved
        rev2 = ent2[3] if len(ent2) > 3 else None
        same_catalog = (
            state_rev is not None
            and rev2 is not None
            # same tracker object + same (store catalog rev, provider
            # catalog token) — proves pools_key/ds_key equality without
            # the deep compare (state/cluster.py:EncodeDeltas)
            and rev2[:2] == state_rev[:2]
        ) or k2[2:4] == key[2:4]
        if not same_catalog:
            continue
        # the donor's core_rev rides through replace() untouched — the
        # patched core's shared tables ARE the donor's, so downstream
        # provenance consumers (backend.host_kernel_args, the argument
        # arena) treat them as unchanged; only the run split / pod lists
        # (content-hashed, never revision-tagged) differ
        return dataclasses.replace(
            core2,
            group_pods=group_pods,
            run_group=run_group,
            run_count=run_count,
            sorted_uids=sorted_uids,
        )
    return None


# --- vault donors (solver/vault.py restore path) ---------------------------
#
# A vault restore cannot re-insert cores into the live cache: `_core_key`
# embeds pod/type OBJECT IDS and interned signature NUMBERS, both of which
# are process-local. Instead, restored cores park here keyed by CONTENT —
# the ordered distinct pod-signature sequence plus the catalog content
# fingerprint (encode._catalog_content_fp) and the cheap key segments — and
# the encoder consults this registry only after an exact hit AND a patch
# both miss. Adoption re-stamps the process-local fields (run split, pod
# lists, interned snums, sig epoch, core_rev) exactly like try_patch, so an
# adopted core is indistinguishable from a fresh build downstream. Content
# keying makes donors self-verifying: a donor whose pods or catalog no
# longer match simply never matches, so a stale vault can slow a restart
# but can never change a decision.

_VAULT_DONORS: Dict[tuple, object] = {}


def _donor_key(sig_seq, ds_key, zones, cts, policy, cat_fp) -> tuple:
    return (sig_seq, ds_key, zones, cts, policy, cat_fp)


def install_vault_donors(donors) -> int:
    """Install exported donor records (vault.export_encode_donors). Each is
    guarded independently — one malformed record never aborts a restore."""
    n = 0
    for d in donors or ():
        try:
            _VAULT_DONORS[_donor_key(
                d["sig_seq"], d["ds_key"], d["zones"], d["cts"],
                d["policy"], d["cat_fp"],
            )] = d["core"]
            n += 1
        except Exception:  # noqa: BLE001 — skip, don't abort the restore
            continue
    return n


def clear_vault_donors() -> None:
    _VAULT_DONORS.clear()


def adopt_vault_donor(key, structure, sig_seq, cat_fp, presort):
    """Match the current encode against the donor registry by content and
    return a fully re-stamped core, or None. Mirrors try_patch's replace()
    but additionally re-stamps group_snums/sig_epoch (interned numbers are
    process-local) and takes a FRESH core_rev — the donor's provenance
    chain died with its process, so arena consumers must treat adopted
    tables as new content."""
    donor = _VAULT_DONORS.get(
        _donor_key(sig_seq, key[3], key[4], key[5], key[6], cat_fp)
    )
    if donor is None:
        return None
    group_pods, run_group, run_count, group_snums = structure
    if donor.group_req.shape[0] != len(group_pods):
        return None  # content key collision paranoia: shapes must agree
    _pods_sorted, _sigs, sorted_uids, interned = presort
    from . import encode as enc

    return dataclasses.replace(
        donor,
        group_pods=group_pods,
        run_group=run_group,
        run_count=run_count,
        sorted_uids=sorted_uids,
        group_snums=group_snums if interned else (),
        sig_epoch=enc._SIG_EPOCH if interned else -1,
        core_rev=next_core_rev(),
    )


# --- run-list prefix identity (checkpointed-scan resume) -------------------
#
# backend.py resumes the FFD scan from a device-resident checkpoint when a
# PREFIX of the sorted run list is unchanged between the previous encode and
# the current one. "Unchanged" must mean decision-identical: the kernel's
# step i reads (run_group[i], run_count[i]) plus [G]-indexed tables, so two
# runs are the same step iff they have the same interned signature number
# (same pod spec — group indices alone can be renumbered by a mid-list
# insert), the same group index (the [G] tables are positional), and the
# same count. Node-table identity (the "node-table revision" leg of the
# prefix rule) is checked separately by the arena's staleness partition —
# see backend._plan_resume.


def run_identity(enc) -> tuple:
    """Tuple of (snum, group, count) per REAL run of `enc`, in scan order.
    () when signatures were not interned (batch-local ids are not
    comparable across solves — resume must not match on them)."""
    snums = getattr(enc, "group_snums", ())
    if not snums:
        return ()
    out = []
    for g, c in zip(enc.run_group, enc.run_count):
        g = int(g)
        c = int(c)
        if c <= 0:
            break  # runs are front-packed; padding never precedes a real run
        out.append((snums[g], g, c))
    return tuple(out)


def run_lcp(prev: tuple, cur: tuple) -> int:
    """Longest common prefix length of two run_identity() tuples."""
    n = min(len(prev), len(cur))
    k = 0
    while k < n and prev[k] == cur[k]:
        k += 1
    return k


def run_table_events(prev_rg, prev_rc, rg, rc, max_events: int = 0):
    """Diff two same-shape padded run tables into the (pos, gid, cnt) edit
    triplets of the streaming event-apply kernel (tpu/ffd.ffd_apply_events).

    Returns an int32 [K, 3] array of the positions where either table
    changed, or None when the tables' shapes differ (different compile
    bucket — a whole-array upload is the only move) or when K exceeds
    `max_events` (> 0; a near-total rewrite is cheaper shipped whole than as
    a triplet table 3x its size). K == 0 returns an empty [0, 3] array —
    the caller skips the dispatch entirely."""
    import numpy as np

    if prev_rg.shape != rg.shape or prev_rc.shape != rc.shape:
        return None
    changed = np.nonzero((prev_rg != rg) | (prev_rc != rc))[0]
    if max_events and len(changed) > max_events:
        return None
    ev = np.empty((len(changed), 3), dtype=np.int32)
    ev[:, 0] = changed
    ev[:, 1] = rg[changed]
    ev[:, 2] = rc[changed]
    return ev


def run_block_identity(ident: tuple, n_shards: int, block: int) -> tuple:
    """Per-mesh-block slices of a run_identity() tuple: block d of a sharded
    solve covers real runs [d*block, min((d+1)*block, len(ident))) of the
    scan order (encode.mesh_run_blocks keeps blocks contiguous; padding
    rides at the tail). The block boundaries are where the sharded path's
    block-boundary carries — its per-device checkpoints — are recorded, so
    shard resume (backend._plan_shard_resume) compares identities block by
    block with the same (snum, group, count) triples plain resume uses."""
    return tuple(
        ident[d * block : (d + 1) * block] for d in range(n_shards)
    )
