"""Global-optimal solver backend: JAX-native projected-ADMM packer.

The second backend behind the `Solver` seam (SPEC.md "Global backend
semantics"). Where the FFD kernel commits pods one run at a time in a
greedy scan, this backend relaxes the whole placement to a dense
fractional assignment tensor `X[pod_runs x candidate_columns]` and
descends a penalized objective — price-weighted node-open cost plus a
quadratic capacity-violation penalty — with every iterate projected back
onto the per-run feasibility simplex (masked rows from the SAME
36-tensor `EncodedInput` tables the FFD kernel consumes; no second
encode path). CvxCluster (PAPERS.md) is the grounding: convex
relaxations of granular allocation solve orders of magnitude faster
than combinatorial search, and the relaxation's fractional optimum is an
excellent guide for a deterministic rounding pass.

Three layers:

- `admm_pack` — the jitted device program. One `jax.lax.scan` body per
  iteration: load -> overload penalty gradient -> cost gradient ->
  masked row-simplex projection. Convergence (first iterate whose max
  |dX| drops under the tolerance) is latched in the scan carry, so the
  iterations-to-converge count comes back with the tensor in the same
  fetch. AOT-prewarmable (`ConvexSolver.prewarm_aot`), arena-resident
  (problem tensors adopt into the inner backend's `ArgumentArena` under
  the `("convex",)` residency namespace), and dispatch-eager behind
  `solve_async` so the pipeline/fleet/tenancy layers above see the same
  async seam as the FFD backend.

- `ConvexSolver` — the `Solver` wrapper. Engages only when every
  NodePool in the input resolves to the convex backend (per-pool
  `karpenter.sh/solver-backend` label, else the operator default) AND
  the input is inside the device-expressible scope the FFD kernel
  itself dispatches (no preference relaxation, no fallback-flagged
  groups, no topology/affinity carve-outs). Everything else delegates
  VERBATIM to the inner solver — byte-identical, pinned by the
  knobs-off inertness test. Non-convergence, invariant-gate rejection,
  or min-values failure falls back LOUDLY to the inner FFD solver:
  counted (karpenter_solver_convex_fallbacks_total) and flight-dumped
  (reason=convex_fallback).

- `consolidate_global` — the one-shot whole-cluster consolidation entry
  (disruption/controller.py `_multi_global`). One batched program over
  rows = (run x owning candidate) with columns = surviving nodes plus a
  priced "stay" column per candidate proposes the candidate SUBSET —
  not just cost-ordered prefixes — whose pods re-place onto the
  surviving fleet. The controller verifies the proposal with ONE
  sequential `_simulate`, so a global decision costs <=2 device
  dispatches; the speculative probe ladder remains the fallback and the
  cross-check oracle.

Rounding determinism (SPEC.md): pods round in solver (run) order; each
pod walks its candidate columns by descending fractional mass, ties
broken by (existing node before new claim, then column price, then
column index); claims fill first-fit in creation order under the exact
integer capacity, pairwise-compatibility, offering, and pool-limit rules
the FFD kernel enforces. The result is assembled by the SAME
`_decode_from_codes` tail the device decode uses, so claim templates,
requirements, and hostnames are constructed identically.
"""

from __future__ import annotations

import functools
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..metrics.registry import (
    SOLVER_CONVEX_FALLBACKS,
    SOLVER_CONVEX_ITERATIONS,
    SOLVER_CONVEX_SOLVES,
    SOLVER_SOLVES,
)
from ..obs import explain as obsexplain
from ..obs import trace as obstrace
from .backend import (
    AsyncSolve,
    Solver,
    _decode_from_codes,
    concrete_backend,
    min_values_post_check,
)
from .encode import EncodedInput, encode, quantize_input

# ---------------------------------------------------------------------------
# jitted ADMM body (tests/test_arg_spec_drift.py pins this signature)
# ---------------------------------------------------------------------------

# positional tensor arguments of admm_pack, in order; `tol` rides as a
# traced scalar so tolerance changes never recompile
CONVEX_ARG_SPEC = ("run_req", "run_count", "cand_cap", "cand_cost", "feas", "tol")
CONVEX_STATICS = ("max_iters",)

# a deleted candidate must shed essentially ALL fractional mass from its
# priced stay column before consolidate_global proposes it
_STAY_EPS = 0.2


# penalty weight on capacity violations (the ADMM-style augmented term)
_RHO = 8.0
# entropic step size and its annealing horizon: eta grows linearly with the
# iteration index (capped at _ETA_MAX), so early iterations explore (mass
# shifts are damped, the capacity penalty can steer) and late iterations
# commit (mass concentrates geometrically on the per-row argmin — the
# multiplicative update's vertex-seeking phase). The damping step beta
# decays geometrically with horizon _TAU: the per-row gradient
# normalization keeps steps O(eta) even near interior (capacity-split)
# equilibria, where the coupled rows otherwise orbit a limit cycle
# forever — the decaying step Cesàro-averages the cycle onto its center,
# which IS the fractional capacity split rounding needs. Tuned on the
# bench configs: full-catalog problems converge in ~20-210 iterations,
# under the default --convex-max-iters with margin.
_ETA0 = 3.0
_ANNEAL = 10.0
_ETA_MAX = 18.0
_TAU = 40.0


@functools.partial(jax.jit, static_argnames=CONVEX_STATICS)
def admm_pack(run_req, run_count, cand_cap, cand_cost, feas, tol, *, max_iters):
    """Penalized proximal-gradient descent over X[S, N] with an entropic
    (multiplicative-weights) prox step — the natural geometry for per-row
    simplex constraints: each iterate multiplies row mass by
    exp(-eta * normalized gradient) and renormalizes, so the feasibility
    simplex is preserved by construction and mass concentrates
    geometrically instead of draining linearly through a Euclidean
    projection. The capacity penalty (quadratic, weight _RHO) is the
    ADMM-style augmented term coupling rows through column load.

    run_req   [S, R] per-pod quantized requests of each run
    run_count [S]    pods per run (0 = padding row)
    cand_cap  [N, R] column capacity (existing free / macro-slot budget)
    cand_cost [N]    per-unit-of-demand open cost (0 = sunk existing node)
    feas      [S, N] bool feasibility mask (compat x offering x fit)
    tol       scalar convergence tolerance on max |dX|

    Returns (X, converged_at): `converged_at` is the 1-based iteration at
    which max |dX| first dropped under `tol`, or -1 (did not converge in
    `max_iters` — the caller falls back loudly to FFD).
    """
    f32 = jnp.float32
    req = run_req.astype(f32)
    cnt = run_count.astype(f32)
    cap = cand_cap.astype(f32)
    cost = cand_cost.astype(f32)
    demand = req * cnt[:, None]  # [S, R]
    ref = jnp.maximum(jnp.max(cap, axis=0), 1.0)  # [R] resource scale
    dn = demand / ref[None, :]
    capn = cap / ref[None, :]
    size = jnp.maximum(dn.sum(axis=1), 1e-6)  # [S] row demand mass
    rho = f32(_RHO)
    costn = cost / jnp.maximum(jnp.max(jnp.abs(cost)), 1e-6)
    maskf = feas.astype(f32)
    X0 = maskf / jnp.maximum(maskf.sum(axis=1, keepdims=True), 1.0)
    tolv = jnp.asarray(tol, f32)
    inf = jnp.float32(jnp.inf)

    def body(carry, i):
        X, conv = carry
        load = X.T @ dn  # [N, R]
        over = jnp.maximum(load - capn, 0.0)
        grad = costn[None, :] * size[:, None] + rho * (dn @ over.T)  # [S, N]
        # per-row gradient normalization: every row steps decisively no
        # matter how small its absolute gradient spread is (rows with tiny
        # demand would otherwise never move mass under a global step)
        gmin = jnp.min(jnp.where(feas, grad, inf), axis=1, keepdims=True)
        g = jnp.where(feas, grad - gmin, 0.0)  # [S, N] in [0, gmax]
        gmax = jnp.maximum(jnp.max(g, axis=1, keepdims=True), 1e-9)
        eta = jnp.minimum(
            f32(_ETA0) * (1.0 + i.astype(f32) / f32(_ANNEAL)), f32(_ETA_MAX)
        )
        W = jnp.where(feas, X * jnp.exp(-eta * g / gmax), 0.0)
        Z = W.sum(axis=1, keepdims=True)
        Xm = jnp.where(Z > 0, W / jnp.maximum(Z, 1e-30), 0.0)
        # geometrically decaying damping: interior (capacity-split) optima
        # put the normalized dynamics on a limit cycle — the shrinking step
        # averages the orbit onto its center while early vertex
        # concentration stays fast (beta is still 0.25 at i = _TAU)
        beta = f32(0.5) * jnp.exp2(-i.astype(f32) / f32(_TAU))
        Xn = (1.0 - beta) * X + beta * Xm
        resid = jnp.max(jnp.abs(Xn - X))
        conv = jnp.where((conv < 0) & (resid < tolv), i + 1, conv)
        return (Xn, conv), resid

    (X, conv), _ = jax.lax.scan(
        body, (X0, jnp.int32(-1)), jnp.arange(max_iters, dtype=jnp.int32)
    )
    return X, conv


def _bucket(n: int, mult: int, floor: int) -> int:
    return max(floor, ((n + mult - 1) // mult) * mult)


# ---------------------------------------------------------------------------
# problem builders (EncodedInput tables -> dense column model)
# ---------------------------------------------------------------------------


@dataclass
class _Problem:
    """One ADMM problem instance: S rows (pod runs) x N columns."""

    E: int  # node columns occupy [0, E); macro/stay columns follow
    req: np.ndarray  # [S, R] float32
    count: np.ndarray  # [S] int32
    feas: np.ndarray  # [S, N] bool
    cap: np.ndarray  # [N, R] float32
    cost: np.ndarray  # [N] float32
    price: np.ndarray  # [N] float64 rounding tie-break (0 for node columns)
    macro_pt: Dict[int, Tuple[int, int]] = field(default_factory=dict)
    alloc: Dict[int, np.ndarray] = field(default_factory=dict)  # col -> type_alloc[t]
    charge: Dict[int, np.ndarray] = field(default_factory=dict)  # pool-limit charge
    adm: Dict[int, np.ndarray] = field(default_factory=dict)  # col -> [Z, C] offering
    stay_owner: Dict[int, int] = field(default_factory=dict)  # consolidation only
    rows_owner: Optional[np.ndarray] = None  # [S] candidate owning each row


def _build_provision(enc: EncodedInput, max_macros: int) -> Optional[_Problem]:
    """Provisioning columns: every existing node plus one "macro" column
    per admissible (pool, instance-type) pair — a macro stands for as many
    claims of that shape as rounding needs, priced at the cheapest
    admissible offering. Wide catalogs truncate to the cheapest
    `max_macros` macros with a per-group feasibility rescue (a group whose
    every feasible macro was cut gets its cheapest one re-added), so the
    dense relaxation stays bounded without losing placeability. Returns
    None only when there are no columns at all (caller counts a decline)."""
    S = len(enc.run_group)
    E = len(enc.node_ids)
    G, R = enc.group_req.shape
    P, T = enc.pool_type.shape
    run_g = enc.run_group.astype(int)
    greq = enc.group_req.astype(np.int64)
    demand_tot = (greq[run_g] * enc.run_count.astype(np.int64)[:, None]).sum(axis=0)

    # existing-node feasibility: admission mask x single-pod fit
    if E:
        nfit = (enc.node_free.astype(np.int64)[None, :, :] >= greq[run_g][:, None, :]).all(
            axis=2
        )
        feas_nodes = enc.node_compat[run_g] & nfit  # [S, E]
    else:
        feas_nodes = np.zeros((S, 0), dtype=bool)

    # group x (zone x ct) joint admissibility, reused per macro column
    gzc = enc.group_zone[:, :, None] & enc.group_ct[:, None, :]  # [G, Z, C]

    macros = []  # (price, p, t, adm, usable, charge, ok_g)
    for p in range(P):
        padm = np.outer(enc.pool_zone[p], enc.pool_ct[p])  # [Z, C]
        daemon = enc.pool_daemon[p].astype(np.int64)
        for t in np.flatnonzero(enc.pool_type[p]):
            t = int(t)
            adm = enc.offer_avail[t] & padm
            if not adm.any():
                continue
            price = float(enc.offer_price[t][adm].min())
            if not np.isfinite(price):
                continue
            usable = enc.type_alloc[t].astype(np.int64) - daemon
            if (usable <= 0).all():
                continue
            charge = np.where(enc.charge_axes, enc.type_capacity[t], 0).astype(np.int64)
            # feasibility: pool + type compat, fit under the daemon
            # overhead, and a jointly admissible offering for the group's
            # zone/ct sets
            ok_g = (
                enc.group_pool[:, p]
                & enc.group_compat_t[:, t]
                & (usable[None, :] >= greq).all(axis=1)
                & (gzc & adm[None]).any(axis=(1, 2))
            )
            if not ok_g.any():
                continue
            macros.append((price, p, t, adm, usable, charge, ok_g))
    macros.sort(key=lambda m: (m[0], m[1], m[2]))
    if len(macros) > max_macros:
        kept = macros[:max_macros]
        covered = np.zeros(G, dtype=bool)
        for m in kept:
            covered |= m[6]
        for m in macros[max_macros:]:  # price order: cheapest rescue wins
            if (m[6] & ~covered).any():
                kept.append(m)
                covered |= m[6]
        macros = kept
    N = E + len(macros)
    if N == 0:
        return None

    feas = np.zeros((S, N), dtype=bool)
    feas[:, :E] = feas_nodes
    cap = np.zeros((N, R), dtype=np.float32)
    cost = np.zeros(N, dtype=np.float32)
    price_col = np.zeros(N, dtype=np.float64)
    if E:
        cap[:E] = enc.node_free.astype(np.float32)
    prob = _Problem(
        E=E,
        req=greq[run_g].astype(np.float32),
        count=enc.run_count.astype(np.int32),
        feas=feas,
        cap=cap,
        cost=cost,
        price=price_col,
    )
    ref = np.maximum(
        np.max(np.concatenate([cap[:E], np.stack([m[4] for m in macros])])
               if macros else cap[:E], axis=0),
        1.0,
    ) if N else np.ones(R)
    # per-node open surcharge amortized over the shape's capacity: kappa /
    # unorm shrinks with instance size, so at comparable per-unit prices
    # the relaxation prefers FEWER, LARGER nodes — the integral objective
    # (node count, then price) that pure per-unit pricing cannot see
    kappa = 0.25 * max(m[0] for m in macros) if macros else 0.0
    for i, (price, p, t, adm, usable, charge, ok_g) in enumerate(macros):
        n = E + i
        prob.macro_pt[n] = (p, t)
        prob.alloc[n] = enc.type_alloc[t].astype(np.int64)
        prob.charge[n] = charge
        prob.adm[n] = adm
        price_col[n] = price
        # open cost per unit of normalized demand: cheaper-per-capacity
        # shapes win the fractional mass
        unorm = float(np.sum(np.maximum(usable, 0) / ref))
        cost[n] = np.float32((price + kappa) / max(unorm, 1e-6))
        # macro budget: enough claim-slots of this shape to hold the whole
        # batch (bounded), so capacity pressure lands on EXISTING nodes
        with np.errstate(divide="ignore"):
            need = demand_tot / np.maximum(usable, 1)
        n_need = int(np.clip(np.ceil(need[demand_tot > 0].max() if (demand_tot > 0).any() else 1), 1, 64))
        cap[n] = (np.maximum(usable, 0) * n_need).astype(np.float32)
        feas[:, n] = ok_g[run_g]
    return prob


def _build_consolidate(
    enc: EncodedInput,
    owners: List[Tuple[int, int, int]],  # (group, count, candidate) per row
    target_nodes: List[int],  # surviving (non-candidate) node indices
    prices: Sequence[float],
) -> _Problem:
    """Consolidation columns: the surviving fleet's nodes (sunk, cost 0)
    plus one priced "stay" column per candidate — mass left on a stay
    column is load that could NOT re-place, so candidates whose rows shed
    their stay mass are the deletable subset."""
    R = enc.group_req.shape[1]
    S = len(owners)
    J = len(prices)
    Nn = len(target_nodes)
    N = Nn + J
    greq = enc.group_req.astype(np.int64)
    req = np.zeros((S, R), dtype=np.float32)
    count = np.zeros(S, dtype=np.int32)
    feas = np.zeros((S, N), dtype=bool)
    rows_owner = np.zeros(S, dtype=np.int64)
    node_free = enc.node_free.astype(np.int64)
    for i, (g, cnt, j) in enumerate(owners):
        req[i] = greq[g]
        count[i] = cnt
        rows_owner[i] = j
        for k, e in enumerate(target_nodes):
            feas[i, k] = bool(enc.node_compat[g, e]) and bool(
                (node_free[e] >= greq[g]).all()
            )
        feas[i, Nn + j] = True  # staying put is always admissible
    cap = np.zeros((N, R), dtype=np.float32)
    for k, e in enumerate(target_nodes):
        cap[k] = node_free[e].astype(np.float32)
    demand_tot = (req * count[:, None].astype(np.float32)).sum(axis=0)
    cap[Nn:] = np.maximum(demand_tot, 1.0)[None, :]  # stay columns never bind
    cost = np.zeros(N, dtype=np.float32)
    price_col = np.zeros(N, dtype=np.float64)
    scale = max(float(np.mean([p for p in prices if p > 0] or [1.0])), 1e-6)
    for j, p in enumerate(prices):
        cost[Nn + j] = np.float32(max(p, 0.0) / scale)
        price_col[Nn + j] = p
    prob = _Problem(
        E=Nn, req=req, count=count, feas=feas, cap=cap, cost=cost, price=price_col
    )
    prob.rows_owner = rows_owner
    for j in range(J):
        prob.stay_owner[Nn + j] = j
    return prob


# ---------------------------------------------------------------------------
# deterministic rounding (SPEC.md "Global backend semantics": rounding rules)
# ---------------------------------------------------------------------------


def _round_provision(enc: EncodedInput, X: np.ndarray, prob: _Problem):
    """Greedy round-to-integral in solver order, guided by fractional mass.

    Pods round run by run through three tiers, mirroring the FFD kernel's
    placement semantics so the relaxation can only improve WHICH shapes
    open, never scatter what FFD would have packed:

    1. existing-node columns (sunk cost — filling free capacity is never
       dearer than opening a claim), ranked by descending X[s, col];
    2. ANY already-open claim, first-fit in creation order under the
       kernel's rules (cumulative fit vs the claim's chosen type,
       pool+type admissibility, pairwise group compatibility, non-empty
       joint offering) — cross-column joins are what keep multi-group
       fleets from opening one claim per group;
    3. a NEW claim from the macro columns ranked by descending X[s, col]
       (ties: price, then index — the fractional mass picks the shape),
       charging the pool limit on open.

    The codes stream feeds the SAME `_decode_from_codes` tail the device
    decode uses."""
    E = prob.E
    G, R = enc.group_req.shape
    S = len(enc.run_group)
    T = enc.pool_type.shape[1]
    Z, C = len(enc.zones), len(enc.capacity_types)
    node_rem = enc.node_free.astype(np.int64).copy()
    room = enc.pool_limit.astype(np.int64) - enc.pool_usage.astype(np.int64)
    pool_adm = [
        np.outer(enc.pool_zone[p], enc.pool_ct[p]) for p in range(enc.pool_zone.shape[0])
    ]
    claims: List[dict] = []
    offs = np.concatenate(([0], np.cumsum(enc.run_count))).astype(int)
    codes = np.full(int(offs[-1]), -1, dtype=np.int64)

    for s in range(S):
        g = int(enc.run_group[s])
        req = enc.group_req[g].astype(np.int64)
        gz = np.outer(enc.group_zone[g], enc.group_ct[g])
        cols = np.flatnonzero(prob.feas[s])
        if cols.size == 0:
            continue  # codes stay -1: unschedulable, surfaced as errors
        ranked = sorted(
            cols.tolist(), key=lambda n: (-float(X[s, n]), prob.price[n], n)
        )
        node_order = [n for n in ranked if n < E]
        macro_order = [n for n in ranked if n >= E]
        for k in range(int(enc.run_count[s])):
            pos = offs[s] + k
            placed = False
            for n in node_order:
                if (node_rem[n] >= req).all():
                    node_rem[n] -= req
                    codes[pos] = n
                    placed = True
                    break
            if placed:
                continue
            # first-fit into ANY open claim, creation order. Claims are
            # type-FLEXIBLE like the kernel's: a pod joins if any type in
            # the claim's still-viable set holds the cumulative sum with
            # a live offering — not just the macro column that opened it
            for ci, cl in enumerate(claims):
                if not enc.group_pool[g, cl["p"]]:
                    continue
                if not all(enc.group_pair[g, g2] for g2 in cl["gset"]):
                    continue
                ngz = cl["gz"] & gz
                if not ngz.any():
                    continue
                new_cum = cl["cum"] + req
                new_tset = [
                    t2 for t2 in cl["tset"]
                    if enc.group_compat_t[g, t2]
                    and (new_cum <= enc.type_alloc[t2].astype(np.int64)).all()
                    and (enc.offer_avail[t2] & ngz).any()
                ]
                if not new_tset:
                    continue
                cl["cum"] = new_cum
                cl["gset"].add(g)
                cl["gz"] = ngz
                cl["tset"] = new_tset
                codes[pos] = E + ci
                placed = True
                break
            if placed:
                continue
            for n in macro_order:
                p, t = prob.macro_pt[n]
                alloc = prob.alloc[n]
                cum0 = enc.pool_daemon[p].astype(np.int64) + req
                if not (cum0 <= alloc).all():
                    continue
                if not (prob.charge[n] <= room[p]).all():
                    continue
                zc0 = prob.adm[n] & gz
                if not zc0.any():
                    continue
                room[p] = room[p] - prob.charge[n]
                gz0 = gz & pool_adm[p]
                tset0 = [
                    t2 for t2 in map(int, np.flatnonzero(enc.pool_type[p]))
                    if enc.group_compat_t[g, t2]
                    and (cum0 <= enc.type_alloc[t2].astype(np.int64)).all()
                    and (enc.offer_avail[t2] & gz0).any()
                ]
                ci = len(claims)
                claims.append(
                    {"p": p, "cum": cum0, "gset": {g}, "gz": gz0,
                     "tset": tset0}
                )
                codes[pos] = E + ci
                break

    used = len(claims)
    c_mask = np.zeros((used, T), dtype=bool)
    c_zone = np.zeros((used, Z), dtype=bool)
    c_ct = np.zeros((used, C), dtype=bool)
    c_pool = np.zeros(used, dtype=np.int64)
    c_gmask = np.zeros((used, G), dtype=bool)
    c_cum = np.zeros((used, R), dtype=np.int64)
    for m, cl in enumerate(claims):
        p = cl["p"]
        c_pool[m] = p
        c_cum[m] = cl["cum"]
        for g in cl["gset"]:
            c_gmask[m, g] = True
        # widen the instance-type set to every shape that still satisfies
        # the claim (spot flexibility / min-values parity with the kernel's
        # narrowing claim masks); the chosen type qualifies by construction
        zc_any = np.zeros((Z, C), dtype=bool)
        for t2 in np.flatnonzero(enc.pool_type[p]):
            t2 = int(t2)
            if not all(enc.group_compat_t[g, t2] for g in cl["gset"]):
                continue
            if not (cl["cum"] <= enc.type_alloc[t2].astype(np.int64)).all():
                continue
            tz = enc.offer_avail[t2] & cl["gz"]
            if not tz.any():
                continue
            c_mask[m, t2] = True
            zc_any |= tz
        c_zone[m] = zc_any.any(axis=1)
        c_ct[m] = zc_any.any(axis=0)
    return _decode_from_codes(
        enc, codes, E, c_mask, c_zone, c_ct, c_pool, c_gmask, c_cum, used
    )


# ---------------------------------------------------------------------------
# the Solver wrapper
# ---------------------------------------------------------------------------


def find_convex(solver) -> Optional["ConvexSolver"]:
    """The ConvexSolver layer inside a wrapper chain, if one is wired
    (same real-`__dict__`-link walk as `concrete_backend`)."""
    seen = set()
    while id(solver) not in seen:
        seen.add(id(solver))
        if isinstance(solver, ConvexSolver):
            return solver
        d = getattr(solver, "__dict__", {})
        nxt = d.get("inner") or d.get("solver")
        if nxt is None or isinstance(nxt, (str, bytes)):
            break
        solver = nxt
    return None


class ConvexSolver(Solver):
    """Per-NodePool global-optimization backend behind the Solver seam.

    Wraps the FFD executor (`inner` is a real __dict__ link, so
    `concrete_backend` keeps resolving through it to the device backend).
    Selection: a solve engages the convex path only when EVERY NodePool in
    the input resolves to "convex" — per-pool `solver_backend` (the
    `karpenter.sh/solver-backend` label, read by the provisioner) takes
    precedence over the operator-level default; a single pool resolving to
    FFD routes the whole solve verbatim to the inner backend, keeping
    semantics unforked. All declines and fallbacks are counted; fallbacks
    additionally flight-dump."""

    def __init__(
        self,
        inner: Solver,
        max_iters: int = 400,
        tolerance: float = 1e-3,
        default_backend: str = "convex",
        max_macros: int = 256,
    ):
        self.inner = inner
        self.max_iters = int(max_iters)
        self.tolerance = float(tolerance)
        self.default_backend = default_backend
        self.max_macros = int(max_macros)
        self._lock = threading.Lock()
        self.convex_stats: Dict[str, int] = {
            "convex_solves": 0,
            "convex_fallbacks": 0,
            "convex_declines": 0,
            "admm_iterations": 0,
            "global_proposals": 0,
            "global_declines": 0,
            "prewarmed_buckets": 0,
        }

    def __getattr__(self, name):
        return getattr(self.__dict__["inner"], name)

    # -- selection ----------------------------------------------------------

    def _resolve(self, pool) -> str:
        return getattr(pool, "solver_backend", None) or self.default_backend

    def selected(self, inp) -> bool:
        pools = getattr(inp, "nodepools", None) or []
        return bool(pools) and all(self._resolve(p) == "convex" for p in pools)

    # -- Solver seam --------------------------------------------------------

    def solve(self, inp):
        return self.solve_async(inp).result()

    def solve_async(self, inp) -> AsyncSolve:
        if not self.selected(inp):
            # per-pool backend labels (or an ffd default) deselect the
            # layer: counted as a decline so a mixed fleet is observable,
            # delegated verbatim so the result is the inner solver's own
            return self._delegate(inp, reason="unselected",
                                  count=self.default_backend == "convex")
        qinp = quantize_input(inp)
        from . import relax as rx

        if rx.plan(qinp) is not None:
            return self._delegate(inp, reason="preferences")
        with obstrace.span("backend.encode"):
            enc = encode(qinp)
        if (
            enc.group_fallback.any()
            or enc.has_topology
            or enc.has_affinity
            or enc.G == 0
            or (enc.v_kind is not None and getattr(enc.v_kind, "size", 0))
            or (enc.q_kind is not None and getattr(enc.q_kind, "size", 0))
        ):
            return self._delegate(inp, reason="scope")
        prob = _build_provision(enc, self.max_macros)
        if prob is None:
            return self._delegate(inp, reason="shape")
        try:
            with obstrace.span("backend.convex.dispatch"):
                handle = self._dispatch(prob)
        except Exception:  # noqa: BLE001 — device failure walks the chain
            handle = None

        def finish():
            if handle is None:
                return self._fallback(qinp, "device")
            try:
                X = np.asarray(handle[0])
                iters = int(np.asarray(handle[1]))
            except Exception:  # noqa: BLE001
                return self._fallback(qinp, "device")
            if iters < 0:
                return self._fallback(qinp, "nonconverged")
            S, N = prob.feas.shape
            with obstrace.span("backend.convex.round"):
                res = _round_provision(enc, X[:S, :N], prob)
            from .resilient import check_invariants

            bad = check_invariants(qinp, res)
            if bad:
                return self._fallback(qinp, "invariant", detail="; ".join(bad[:3]))
            if not min_values_post_check(qinp, res):
                return self._fallback(qinp, "min_values")
            with self._lock:
                self.convex_stats["convex_solves"] += 1
                self.convex_stats["admm_iterations"] = iters
            SOLVER_CONVEX_SOLVES.inc(path="provision")
            SOLVER_CONVEX_ITERATIONS.set(iters)
            SOLVER_SOLVES.inc(backend="convex")
            if obsexplain.enabled():
                obsexplain.capture(qinp, res, "convex", enc=enc)
            return res

        return AsyncSolve(finish)

    # -- one-shot whole-cluster consolidation -------------------------------

    def consolidate_global(
        self, inp, candidates: Sequence[Tuple[str, float, frozenset]]
    ) -> Optional[dict]:
        """Propose the deletable candidate SUBSET for a multi-node
        consolidation decision. `candidates` is [(node_id, price,
        pod_uids)] in the controller's cost order; `inp` carries ALL
        candidates' pods as pending with every node still present.

        One device program: rows are (run x owning candidate) splits,
        columns are the surviving (non-candidate) nodes plus a priced stay
        column per candidate. A candidate whose rows all shed their stay
        mass below the epsilon can empty onto the surviving fleet — those
        form the proposal. Returns {"delete": [node_id...], "iterations",
        "stay_mass"} or None (decline: out of scope / non-converged / no
        >=2-candidate proposal). The caller MUST verify the proposal with
        one sequential simulate before commanding."""
        with self._lock:
            self.convex_stats["global_proposals"] += 1
        if not self.selected(inp):
            return self._global_decline()
        qinp = quantize_input(inp)
        from . import relax as rx

        if rx.plan(qinp) is not None:
            return self._global_decline()
        enc = encode(qinp)
        if (
            enc.group_fallback.any()
            or enc.has_topology
            or enc.has_affinity
            or enc.G == 0
            or (enc.v_kind is not None and getattr(enc.v_kind, "size", 0))
            or (enc.q_kind is not None and getattr(enc.q_kind, "size", 0))
        ):
            return self._global_decline()
        cand_ids = [c[0] for c in candidates]
        id2j = {nid: j for j, nid in enumerate(cand_ids)}
        uid2j: Dict[str, int] = {}
        for j, (_nid, _price, uids) in enumerate(candidates):
            for u in uids:
                uid2j[u] = j
        cand_e = {e for e, nid in enumerate(enc.node_ids) if nid in id2j}
        target_nodes = [e for e in range(len(enc.node_ids)) if e not in cand_e]
        # split each run by the candidate that owns its pods
        offs = np.concatenate(([0], np.cumsum(enc.run_count))).astype(int)
        owners: List[Tuple[int, int, int]] = []
        for s in range(len(enc.run_group)):
            by: Dict[int, int] = {}
            for u in enc.sorted_uids[offs[s] : offs[s + 1]].tolist():
                j = uid2j.get(str(u))
                if j is None:
                    return self._global_decline()  # foreign pending pod
                by[j] = by.get(j, 0) + 1
            for j in sorted(by):
                owners.append((int(enc.run_group[s]), by[j], j))
        if not owners:
            return self._global_decline()
        prob = _build_consolidate(
            enc, owners, target_nodes, [c[1] for c in candidates]
        )
        try:
            handle = self._dispatch(prob)
            X = np.asarray(handle[0])
            iters = int(np.asarray(handle[1]))
        except Exception:  # noqa: BLE001
            return self._global_decline()
        if iters < 0:
            SOLVER_CONVEX_FALLBACKS.inc(reason="consolidate_nonconverged")
            obstrace.dump(
                "convex_fallback", cause="consolidate_nonconverged",
                candidates=len(candidates), max_iters=self.max_iters,
            )
            return self._global_decline()
        SOLVER_CONVEX_SOLVES.inc(path="consolidate")
        SOLVER_CONVEX_ITERATIONS.set(iters)
        with self._lock:
            self.convex_stats["admm_iterations"] = iters
        Nn = len(target_nodes)
        stay_mass = {j: 0.0 for j in range(len(candidates))}
        for i, (_g, _cnt, j) in enumerate(owners):
            stay_mass[j] = max(stay_mass[j], float(X[i, Nn + j]))
        delete = [cand_ids[j] for j in sorted(stay_mass) if stay_mass[j] < _STAY_EPS]
        if len(delete) < 2:
            return self._global_decline()
        return {
            "delete": delete,
            "iterations": iters,
            "stay_mass": {cand_ids[j]: round(m, 4) for j, m in stay_mass.items()},
        }

    # -- dispatch / prewarm -------------------------------------------------

    def _dispatch(self, prob: _Problem):
        """Pad to compile buckets, adopt the problem tensors into the inner
        backend's ArgumentArena (ns=("convex",): packed delta uploads +
        ledger accounting, shared with the FFD residency budget), and
        dispatch the jitted scan eagerly. Returns device handles."""
        S, N = prob.feas.shape
        R = prob.cap.shape[1]
        Sp, Np = _bucket(S, 16, 16), _bucket(N, 16, 16)
        run_req = np.zeros((Sp, R), dtype=np.float32)
        run_req[:S] = prob.req
        run_count = np.zeros(Sp, dtype=np.int32)
        run_count[:S] = prob.count
        cap = np.zeros((Np, R), dtype=np.float32)
        cap[:N] = prob.cap
        cost = np.zeros(Np, dtype=np.float32)
        cost[:N] = prob.cost
        feas = np.zeros((Sp, Np), dtype=bool)
        feas[:S, :N] = prob.feas
        args = (run_req, run_count, cap, cost, feas)
        arena = getattr(concrete_backend(self.inner), "arena", None)
        if arena is not None:
            try:
                args = arena.adopt(args, (None,) * len(args), ns=("convex",))
            except Exception:  # noqa: BLE001 — residency is an optimization
                pass
        X, conv = admm_pack(*args, float(self.tolerance), max_iters=self.max_iters)
        return X, conv

    def prewarm_aot(self, *args, **kwargs):
        """AOT-compile the ADMM scan for the small bucket lattice after
        delegating the inner backend's own prewarm (operator boot path)."""
        inner_fn = getattr(self.inner, "prewarm_aot", None)
        out = inner_fn(*args, **kwargs) if callable(inner_fn) else None
        n = 0
        for Sp, Np in ((16, 16), (32, 32), (64, 64)):
            try:
                admm_pack.lower(
                    jnp.zeros((Sp, 4), jnp.float32),
                    jnp.zeros((Sp,), jnp.int32),
                    jnp.zeros((Np, 4), jnp.float32),
                    jnp.zeros((Np,), jnp.float32),
                    jnp.zeros((Sp, Np), bool),
                    jnp.float32(self.tolerance),
                    max_iters=self.max_iters,
                ).compile()
                n += 1
            except Exception:  # noqa: BLE001 — prewarm is best-effort
                break
        with self._lock:
            self.convex_stats["prewarmed_buckets"] = n
        return out

    # -- decline / fallback plumbing ----------------------------------------

    def _delegate(self, inp, reason: Optional[str] = None, count: bool = True) -> AsyncSolve:
        """Verbatim delegation to the inner solver (the byte-identical
        path the inertness test pins)."""
        if count and reason is not None:
            with self._lock:
                self.convex_stats["convex_declines"] += 1
        fn = getattr(self.inner, "solve_async", None)
        if callable(fn):
            return fn(inp)
        return AsyncSolve(lambda: self.inner.solve(inp))

    def _fallback(self, qinp, reason: str, detail: str = ""):
        """Loud fallback: counted, metric'd, flight-dumped, then the inner
        FFD solver answers (ISSUE 19: non-convergence must never be
        silent)."""
        with self._lock:
            self.convex_stats["convex_fallbacks"] += 1
        SOLVER_CONVEX_FALLBACKS.inc(reason=reason)
        obstrace.dump(
            "convex_fallback", cause=reason, detail=detail,
            pods=len(getattr(qinp, "pods", ()) or ()),
            max_iters=self.max_iters, tolerance=self.tolerance,
        )
        return self.inner.solve(qinp)

    def _global_decline(self) -> None:
        with self._lock:
            self.convex_stats["global_declines"] += 1
        return None
