"""Device-resident argument arena with packed delta uploads.

The decode side of the solver already pays ONE device→host transfer per
solve (backend._pack_outputs); this module gives the upload side the same
treatment. On the tunneled host↔device link every per-array message pays
fixed overhead on top of a shared ~70-80ms roundtrip, so shipping the full
~30-array ffd.ARG_SPEC set per solve costs ~30 messages for data that is
mostly identical to the previous solve (ARCHITECTURE.md §5 "the tunnel
tax").

`ArgumentArena` keeps the kernel args device-resident per shape bucket.
Each solve classifies every ARG_SPEC entry as fresh or stale:

  1. provenance fast path — entries that are pure functions of the cached
     encode core carry a token from `backend.host_kernel_args` (keyed on
     `EncodedInput.core_rev`, the monotonic revision `encode._build_core`
     stamps and `encode_cache.try_patch` preserves). Same token ⇒ same
     bytes, no hash, no upload.
  2. content digest — everything else (node/pool-usage tensors, the run
     split) is blake2b-hashed; equal digest ⇒ fresh. A token mismatch with
     an equal digest (e.g. a rebuilt core with identical tables, as the
     relax loop produces every iteration) refreshes the token and keeps
     the resident buffer.

The stale set packs into ONE contiguous uint8 buffer, uploads as ONE
`jax.device_put` (optionally placed on a mesh sharding for the batched
consolidation universe), and a cached jitted unpack scatters it into typed
device buffers via `lax.bitcast_convert_type`. An exact encode-cache hit
therefore dispatches with ZERO array uploads; a steady-state delta solve
pays one packed message. No jit in this repo donates its inputs
(donate_argnums is never used), so resident buffers are safe to reuse
across dispatches — including the overflow-retry redispatch loop.

`TransferLedger` counts every host→device and device→host byte per solve
(and cumulatively) so tests assert the zero-upload / single-packed-upload
invariants instead of eyeballing timings, and pushes the
`karpenter_tpu_solver_upload_*` / `arena_hit_rate` gauges.

Invalidation: `ResilientSolver` calls `TPUSolver.invalidate_arena()` before
any fallback replay, so a gate-rejected or failed device solve never reuses
possibly-corrupt resident buffers (solver/SPEC.md "Transfer semantics").
"""

from __future__ import annotations

import contextlib
import hashlib
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..metrics.registry import (
    SOLVER_ARENA_BYTES,
    SOLVER_ARENA_EVICTIONS,
    SOLVER_ARENA_HIT_RATE,
    SOLVER_DECODE_BYTES,
    SOLVER_UPLOAD_ARRAYS,
    SOLVER_UPLOAD_BYTES,
)
from ..obs import slo as obsslo
from ..obs import trace as obstrace

_LEDGER_FIELDS = ("h2d_bytes", "h2d_arrays", "h2d_msgs", "d2h_bytes",
                  "d2h_msgs", "h2d_shard_bytes")


class TransferLedger:
    """Per-solve + cumulative host↔device transfer accounting.

    `begin_solve()` opens a per-solve window (`.solve`); uploads/fetches
    recorded inside it accumulate into `.total` as well. Adopt outcomes
    (exact_hit / delta_upload / full_upload) count the arena's hit classes.
    """

    def __init__(self):
        self._lock = threading.Lock()
        # thread-local per-tenant-meter suppression (see unmetered()):
        # ledger counters always record; only the obs/slo attribution is
        # conditionally skipped, and only on the suppressing thread
        self._local = threading.local()
        self.reset()

    @contextlib.contextmanager
    def unmetered(self):
        """Suppress the per-tenant usage-meter attribution (obs/slo
        meter_bytes) for records made by THIS thread inside the block; the
        ledger's own counters still record every byte. The cohort dispatch
        uses this around its stacked-batch adopt: the fused upload is one
        physical transfer whose bytes are then attributed per member
        explicitly (each member pays its own rows), so the ambient-trace
        attribution here would double-charge the lead tenant."""
        self._local.unmetered = getattr(self._local, "unmetered", 0) + 1
        try:
            yield
        finally:
            self._local.unmetered -= 1

    def _metering(self) -> bool:
        return not getattr(self._local, "unmetered", 0)

    def reset(self) -> None:
        self.solves = 0
        self.solve: Dict[str, int] = dict.fromkeys(_LEDGER_FIELDS, 0)
        self.total: Dict[str, int] = dict.fromkeys(_LEDGER_FIELDS, 0)
        self.outcomes: Dict[str, int] = {
            "exact_hit": 0, "delta_upload": 0, "full_upload": 0
        }

    def begin_solve(self) -> None:
        with self._lock:
            self.solves += 1
            self.solve = dict.fromkeys(_LEDGER_FIELDS, 0)

    def record_upload(self, nbytes: int, arrays: int, msgs: int = 1,
                      shard_bytes: int = 0) -> None:
        """`shard_bytes` ≤ `nbytes`: the portion uploaded under a PARTITIONED
        byte sharding (each mesh device receives only its 1/Nd slice of
        those bytes; the remainder replicates to every device)."""
        with self._lock:
            for k, v in (("h2d_bytes", nbytes), ("h2d_arrays", arrays),
                         ("h2d_msgs", msgs), ("h2d_shard_bytes", shard_bytes)):
                self.solve[k] += v
                self.total[k] += v
        # per-tenant usage ledger (obs/slo.py): attribute via the calling
        # thread's trace tenancy — uploads happen inside backend.upload
        if self._metering():
            obsslo.meter_bytes(obstrace.current_tenant_id(), h2d=nbytes)

    def record_fetch(self, nbytes: int, msgs: int = 1) -> None:
        with self._lock:
            for k, v in (("d2h_bytes", nbytes), ("d2h_msgs", msgs)):
                self.solve[k] += v
                self.total[k] += v
        if self._metering():
            obsslo.meter_bytes(obstrace.current_tenant_id(), d2h=nbytes)

    def record_adopt(self, outcome: str) -> None:
        # encode-cache hit class rides on the solve's span tree (the
        # dispatcher is inside backend.upload when adoption happens)
        obstrace.annotate(arena=outcome)
        with self._lock:
            self.outcomes[outcome] += 1

    @property
    def upload_bytes_per_solve(self) -> float:
        return self.total["h2d_bytes"] / self.solves if self.solves else 0.0

    @property
    def arena_hit_rate(self) -> float:
        n = sum(self.outcomes.values())
        return self.outcomes["exact_hit"] / n if n else 0.0

    @property
    def decode_bytes_per_solve(self) -> float:
        """Average device→host result-fetch bytes per solve — the number the
        on-device decode (backend delta packing) is meant to shrink."""
        return self.total["d2h_bytes"] / self.solves if self.solves else 0.0

    def shard_upload_bytes_per_device(self, n_devices: int) -> float:
        """Average host→device bytes landing on EACH device per solve under
        an n-way mesh: partitioned bytes split 1/Nd per device, everything
        else replicates whole. Equals upload_bytes_per_solve at n=1; the
        sharded-solve target is ≈ 1/Nd of the replicated-args baseline on
        run-dominated uploads (SPEC.md "Sharding semantics")."""
        if not self.solves:
            return 0.0
        n = max(1, int(n_devices))
        shard = self.total["h2d_shard_bytes"]
        repl = self.total["h2d_bytes"] - shard
        return (repl + shard / n) / self.solves

    def end_solve(self) -> Dict[str, int]:
        """Close the per-solve window: push gauges, return its counters."""
        with self._lock:
            snap = dict(self.solve)
        SOLVER_UPLOAD_BYTES.set(snap["h2d_bytes"])
        SOLVER_UPLOAD_ARRAYS.set(snap["h2d_arrays"])
        SOLVER_ARENA_HIT_RATE.set(self.arena_hit_rate)
        SOLVER_DECODE_BYTES.set(snap["d2h_bytes"])
        obstrace.annotate(upload_bytes=snap["h2d_bytes"],
                          d2h_bytes=snap["d2h_bytes"])
        return snap

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {
                "solves": self.solves,
                "total": dict(self.total),
                "outcomes": dict(self.outcomes),
                "upload_bytes_per_solve": self.upload_bytes_per_solve,
                "arena_hit_rate": self.arena_hit_rate,
            }


def _nbytes(obj) -> int:
    """Host-side byte estimate of one residency record: numpy / device
    arrays by .nbytes, containers recursively, scalars/metadata free."""
    try:
        if isinstance(obj, np.ndarray):
            return int(obj.nbytes)
        if isinstance(obj, dict):
            return sum(_nbytes(v) for v in obj.values())
        if isinstance(obj, (list, tuple)):
            return sum(_nbytes(v) for v in obj)
        if isinstance(obj, (bytes, bytearray)):
            return len(obj)
        nb = getattr(obj, "nbytes", None)  # jax.Array and friends
        if nb is not None:
            return int(nb)
    except Exception:
        pass
    return 0


def _digest(a: np.ndarray) -> bytes:
    """Content digest of a host array (shape/dtype live in the bucket key)."""
    return hashlib.blake2b(
        np.ascontiguousarray(a).tobytes(), digest_size=16
    ).digest()


# Jitted unpack fns, keyed by ((offset, shape, dtype) per stale entry,
# sharding): a steady-state stale set traces/compiles once. Bounded FIFO —
# the key space is tiny in practice (one per recurring stale pattern).
_UNPACK_CACHE: dict = {}
_UNPACK_CACHE_MAX = 64


def _unpack_fn(specs: tuple, sharding):
    key = (specs, sharding)
    fn = _UNPACK_CACHE.get(key)
    if fn is not None:
        return fn
    import jax
    import jax.numpy as jnp

    def go(buf):
        outs = []
        for off, shape, dstr in specs:
            dt = np.dtype(dstr)
            nb = int(np.prod(shape, dtype=np.int64)) * dt.itemsize
            seg = buf[off : off + nb]
            if dt == np.bool_:
                outs.append((seg != 0).reshape(shape))
            elif dt.itemsize == 1:
                outs.append(jax.lax.bitcast_convert_type(
                    seg.reshape(shape), jnp.dtype(dt)))
            else:
                # uint8 [..., itemsize] -> target dtype [...]: byte order on
                # the packing side is the host array's native (little-endian
                # on every supported platform, matching XLA's layout)
                outs.append(jax.lax.bitcast_convert_type(
                    seg.reshape(tuple(shape) + (dt.itemsize,)), jnp.dtype(dt)))
        return tuple(outs)

    fn = jax.jit(go) if sharding is None else jax.jit(go, out_shardings=sharding)
    while len(_UNPACK_CACHE) >= _UNPACK_CACHE_MAX:
        _UNPACK_CACHE.pop(next(iter(_UNPACK_CACHE)))
    _UNPACK_CACHE[key] = fn
    return fn


def _buffer_sharding(out_sharding):
    """Input placement for a packed upload group: when the group's OUT
    sharding partitions its leading axis over a mesh axis, the 1-D byte
    buffer partitions over the same axis — each device receives only its
    1/Nd byte slice over the tunnel, and the jitted unpack's out_shardings
    redistribute on-device (ICI, not the host link). Returns
    (byte_sharding | None, n_way): replicated groups ship whole to every
    device (n_way = 1)."""
    try:
        from jax.sharding import NamedSharding, PartitionSpec

        if (
            isinstance(out_sharding, NamedSharding)
            and len(out_sharding.spec)
            and out_sharding.spec[0] is not None
        ):
            n = int(out_sharding.mesh.devices.size)
            if n > 1:
                return (
                    NamedSharding(
                        out_sharding.mesh, PartitionSpec(out_sharding.spec[0])
                    ),
                    n,
                )
    except Exception:
        pass
    return None, 1


class ArgumentArena:
    """Per-bucket device-resident kernel args with packed delta uploads.

    A bucket is one padded shape signature ((shape, dtype) per ARG_SPEC
    entry, plus the placement sharding) — exactly the compile-bucket
    granularity of the kernel, so a bucket's resident buffers are always
    shape-compatible with its dispatches. Bounded LRU (adopt re-inserts
    the key on every hit): the `max_buckets` cap and the optional
    `budget_bytes` byte budget both evict whole cold buckets — every
    residency class at once — via `_evict_bucket`, counted on
    `karpenter_solver_arena_evictions_total`.
    """

    def __init__(self, ledger: Optional[TransferLedger] = None,
                 max_buckets: int = 4, budget_bytes: int = 0):
        self.ledger = ledger if ledger is not None else TransferLedger()
        self.max_buckets = max_buckets
        # arena byte budget across EVERY residency class (0 = unbounded):
        # when the accounted total exceeds it, whole cold buckets evict
        # LRU-first (_enforce_budget) — the evicted tenant's next solve
        # pays one cold packed upload, never a wrong answer.
        self.budget_bytes = int(budget_bytes)
        # bucket key -> {residency class -> accounted host-equivalent bytes}
        self._bytes: Dict[tuple, Dict[str, int]] = {}
        # (class, tenant) gauge label sets ever pushed, so stale series
        # zero out when their residency drops instead of lying forever
        self._gauge_keys: set = set()
        # bucket key -> [device buffers per entry, (token, digest) per entry]
        self._buckets: Dict[tuple, list] = {}
        # checkpoint residency class (backend._plan_resume): per-bucket FFD
        # scan checkpoints from the bucket's most recent solves. Device
        # arrays + host metadata live together in the record; keying on the
        # SAME bucket key as the resident args means a checkpoint can only
        # be offered to a dispatch whose shapes (and therefore compiled
        # kernel) match the solve that produced it.
        self._ckpts: Dict[tuple, list] = {}
        self.max_ckpts_per_bucket = 1
        # relax-ladder residency class (backend._ladder_arg): per-bucket
        # device-resident run_ladder tables, keyed on content digest — the
        # same preference fleet re-solving reuses the rung table with zero
        # upload. Dies with the bucket on invalidate(), like checkpoints.
        self._ladders: Dict[tuple, Tuple[bytes, object]] = {}
        # sparse-constraint residency class (backend._sparse_arg): per-
        # bucket device-resident run_q_idx/run_v_idx index-table pairs
        # (SPEC.md "Sparse constraint semantics"), keyed on content digest
        # — the staleness anchor is the encode core rev folded into the
        # digest by the caller, so a re-encoded fleet whose constraint
        # layout is unchanged reuses the tables with zero upload. Dies with
        # the bucket on invalidate()/eviction, like ladders.
        self._sparse: Dict[tuple, Tuple[bytes, object]] = {}
        # mesh-sharded residency class (backend._plan_shard_resume): one
        # record per sharded bucket holding the solve's block-boundary
        # carries (host numpy — the PER-DEVICE checkpoints of the sharded
        # scan), per-block run identities, and the stitched take rows, so a
        # later sharded solve replays only from the first changed block.
        # Dropped by invalidate() with everything else.
        self._shards: Dict[tuple, dict] = {}
        # streaming run-table residency (SPEC.md "Streaming semantics"):
        # host copies (+ digests) of the run_group/run_count pair the
        # bucket's device buffers currently hold, recorded by
        # apply_run_events so the NEXT solve can diff against them and ship
        # only (pos, gid, cnt) edit triplets. Dropped by invalidate().
        self._run_host: Dict[tuple, tuple] = {}
        # ARG_SPEC indices the LAST adopt actually uploaded (() on an exact
        # hit) — observability for tests/bench; checkpoint prefix validity
        # uses context_signature() instead (robust to pipelined dispatches
        # landing between a record's solve and the resuming one).
        self.last_stale: tuple = ()
        self.stats: Dict[str, int] = {
            "adopts": 0, "exact_hits": 0, "delta_uploads": 0,
            "full_uploads": 0, "invalidations": 0,
            "event_batches": 0, "event_edits": 0, "evictions": 0,
        }

    def invalidate(self) -> None:
        """Drop every resident buffer + tag AND the checkpoint ring. Called
        by the resilience layer before fallback replays (a failed device
        solve leaves residency — and any checkpoint derived from it — in an
        unknown state) and safe to call any time — the next adopt simply
        pays one full packed upload and the next solve runs cold."""
        self._buckets.clear()
        self._ckpts.clear()
        self._ladders.clear()
        self._sparse.clear()
        self._shards.clear()
        self._run_host.clear()
        self._bytes.clear()
        self.last_stale = ()
        self.stats["invalidations"] += 1
        self._push_gauges()

    # -- byte accounting + budgeted eviction (ISSUE 14) ---------------------

    @staticmethod
    def _tenant_of(key: tuple) -> str:
        return str(key[2]) if len(key) > 2 and key[2] is not None else "default"

    def total_bytes(self) -> int:
        return sum(sum(cls.values()) for cls in self._bytes.values())

    def bytes_by_class(self) -> Dict[Tuple[str, str], int]:
        """Accounted bytes per (residency class, tenant) — the
        `karpenter_solver_arena_bytes{class,tenant}` label space."""
        out: Dict[Tuple[str, str], int] = {}
        for key, classes in self._bytes.items():
            ten = self._tenant_of(key)
            for cls, nb in classes.items():
                out[(cls, ten)] = out.get((cls, ten), 0) + nb
        return out

    def _push_gauges(self) -> None:
        cur = self.bytes_by_class()
        for (cls, ten) in self._gauge_keys - set(cur):
            SOLVER_ARENA_BYTES.set(0, **{"class": cls, "tenant": ten})
        for (cls, ten), nb in cur.items():
            SOLVER_ARENA_BYTES.set(nb, **{"class": cls, "tenant": ten})
        self._gauge_keys |= set(cur)

    def _account(self, key: tuple, cls: str, nbytes: int) -> None:
        self._bytes.setdefault(key, {})[cls] = int(nbytes)
        self._push_gauges()

    def _evict_bucket(self, key: tuple) -> None:
        """Drop EVERY residency class for one bucket key — resident args,
        checkpoints, ladder tables, shard records, streaming run copies —
        so eviction never strands a derived record whose donor args are
        gone (the old FIFO cap dropped only `_buckets` and leaked the
        rest). Decision-safe by construction: the next adopt of the key
        re-uploads cold and every derived path re-records."""
        self._buckets.pop(key, None)
        self._ckpts.pop(key, None)
        self._shards.pop(key, None)
        self._run_host.pop(key, None)
        for lk in [lk for lk in self._ladders if lk[0] == key]:
            self._ladders.pop(lk, None)
        for sk in [sk for sk in self._sparse if sk[0] == key]:
            self._sparse.pop(sk, None)
        self._bytes.pop(key, None)
        self.stats["evictions"] += 1
        SOLVER_ARENA_EVICTIONS.inc()
        obstrace.annotate(arena_evicted=1)

    def _enforce_budget(self, current_key: Optional[tuple] = None) -> None:
        """Evict coldest-first (insertion order of `_buckets` = LRU, adopt
        re-inserts on every hit) until the accounted total fits the budget.
        `current_key` — the bucket the in-flight dispatch holds live device
        references to — goes last, and only if it alone still busts the
        budget (the caller's references keep its buffers alive through the
        dispatch; residency simply isn't retained for the next solve)."""
        if self.budget_bytes <= 0:
            return
        changed = False
        while self.total_bytes() > self.budget_bytes:
            victim = next(
                (k for k in self._buckets if k != current_key), None)
            if victim is None:
                victim = next(
                    (k for k in self._bytes if k != current_key),
                    current_key if current_key in self._bytes else None)
            if victim is None:
                break
            self._evict_bucket(victim)
            changed = True
        if changed:
            self._push_gauges()

    def bucket_key(self, host_args: tuple, sharding=None, ns=None) -> tuple:
        """Residency key for one dispatch's kernel args. `ns` is the tenant
        RESIDENCY namespace (solver/tenancy.py): it partitions buffers,
        checkpoints, ladders, and shard records per tenant so one tenant's
        churn never thrashes another's resident state — while anything
        shape-keyed (the `_UNPACK_CACHE` below, jit/AOT compile buckets)
        deliberately ignores it, so same-shaped tenants share every compiled
        kernel. ns=None yields the pre-tenancy 2-tuple, byte-identical."""
        shapes = tuple((a.shape, a.dtype.str) for a in host_args)
        if ns is None:
            return (shapes, sharding)
        return (shapes, sharding, ns)

    def put_checkpoint(self, key: tuple, record: dict) -> None:
        """Record a solve's checkpoint set for its bucket (newest first,
        bounded). Records die with the bucket on invalidate()."""
        lst = self._ckpts.setdefault(key, [])
        lst.insert(0, record)
        del lst[self.max_ckpts_per_bucket:]
        self._account(key, "ckpt", sum(_nbytes(r) for r in lst))
        self._enforce_budget(key)

    def get_checkpoints(self, key: tuple) -> list:
        return self._ckpts.get(key, [])

    def put_shard_record(self, key: tuple, record: dict) -> None:
        """Record a sharded solve's block-boundary carries + stitched rows
        for its bucket (one per bucket — the newest sharded solve is the
        only useful resume donor). Dies on invalidate()."""
        self._shards[key] = record
        self._account(key, "shard", _nbytes(record))
        self._enforce_budget(key)

    def get_shard_record(self, key: tuple):
        return self._shards.get(key)

    def put_ladder(self, key: tuple, host_table: np.ndarray, dev) -> None:
        """Record a bucket's device-resident relax-ladder table (one per
        bucket — a bucket's preference fleet has one current rung layout)."""
        self._ladders[(key, host_table.shape)] = (_digest(host_table), dev)
        self._account(key, "ladder", sum(
            _nbytes(v[1]) for lk, v in self._ladders.items() if lk[0] == key))
        self._enforce_budget(key)

    def get_ladder(self, key: tuple, host_table: np.ndarray):
        """The bucket's resident ladder table if its content matches, else
        None (the caller uploads and re-records)."""
        rec = self._ladders.get((key, host_table.shape))
        if rec is None or rec[0] != _digest(host_table):
            return None
        return rec[1]

    @staticmethod
    def _sparse_token(core_rev: int, run_q_idx: np.ndarray,
                      run_v_idx: np.ndarray) -> bytes:
        """Staleness token of a sparse index-table pair: the encode core
        rev (any core rebuild — new signatures, new constraint interning —
        mints a fresh rev) plus the content digests. A delta re-encode
        that kept the constraint layout produces the same token and the
        resident pair delta-uploads nothing."""
        return (str(int(core_rev)).encode()
                + _digest(run_q_idx) + _digest(run_v_idx))

    def put_sparse(self, key: tuple, core_rev: int, run_q_idx: np.ndarray,
                   run_v_idx: np.ndarray, dev_pair) -> None:
        """Record a bucket's device-resident sparse constraint index pair
        (one per bucket + shape — a bucket's fleet has one current
        constraint layout)."""
        shp = (run_q_idx.shape, run_v_idx.shape)
        self._sparse[(key, shp)] = (
            self._sparse_token(core_rev, run_q_idx, run_v_idx), dev_pair)
        self._account(key, "sparse", sum(
            _nbytes(d) for sk, v in self._sparse.items() if sk[0] == key
            for d in v[1]))
        self._enforce_budget(key)

    def get_sparse(self, key: tuple, core_rev: int, run_q_idx: np.ndarray,
                   run_v_idx: np.ndarray):
        """The bucket's resident sparse index pair if its token matches,
        else None (the caller uploads and re-records)."""
        rec = self._sparse.get(
            (key, (run_q_idx.shape, run_v_idx.shape)))
        if rec is None or rec[0] != self._sparse_token(
                core_rev, run_q_idx, run_v_idx):
            return None
        return rec[1]

    def apply_run_events(self, host_args: tuple, prov: tuple, sharding=None,
                         ns=None) -> bool:
        """Streaming event-batch apply (SPEC.md "Streaming semantics"): sync
        the bucket's resident run tables (ARG_SPEC entries 0/1) to
        `host_args` by shipping only the (pos, gid, cnt) edit triplets and
        scattering them on device (tpu/ffd.ffd_apply_events), instead of
        letting adopt() re-upload the whole padded pair. Returns True when
        the resident buffers + tags now match `host_args[0:2]` (adopt's
        digest check then sees them fresh — zero run-table upload bytes).

        Safety: the diff base must provably equal the DEVICE content, so the
        stage only fires when the recorded host copy's digests match the
        bucket's current adopt tags — the same trust anchor adopt itself
        uses. Any mismatch (cold bucket, interleaved non-streamed solve,
        post-invalidate) declines and lets adopt pay the normal upload; the
        new host pair is recorded either way so the NEXT solve can stage.
        """
        if sharding is not None:
            return False  # sharded buckets partition the run tables; the
            # per-device slices are not addressable by a global scatter
        rg = np.ascontiguousarray(host_args[0])
        rc = np.ascontiguousarray(host_args[1])
        key = self.bucket_key(host_args, sharding, ns=ns)
        dig_rg, dig_rc = _digest(rg), _digest(rc)
        prev = self._run_host.get(key)
        self._run_host[key] = (rg.copy(), rc.copy(), dig_rg, dig_rc)
        self._account(key, "run_host", rg.nbytes + rc.nbytes)
        bkt = self._buckets.get(key)
        if bkt is None or prev is None:
            return False
        dev, tags = bkt
        if (dev[0] is None or dev[1] is None
                or tags[0] is None or tags[1] is None
                or tags[0][1] != prev[2] or tags[1][1] != prev[3]):
            return False  # device content is not (provably) the diff base
        from . import encode_cache
        from .tpu import ffd

        events = encode_cache.run_table_events(
            prev[0], prev[1], rg, rc,
            max_events=max(16, rg.shape[0] // 3))
        if events is None:
            return False  # shape moved or near-total rewrite: ship whole
        k = len(events)
        if k == 0:
            return True  # tables unchanged; adopt's digest check hits as-is
        import jax

        # pad to a small power-of-two compile bucket; pad rows carry
        # EVENT_PAD_POS and scatter out of range (mode="drop")
        k2 = 8
        while k2 < k:
            k2 *= 2
        if k2 != k:
            pad = np.zeros((k2 - k, events.shape[1]), dtype=events.dtype)
            pad[:, 0] = ffd.EVENT_PAD_POS
            events = np.concatenate([events, pad])
        dev_ev = jax.device_put(events)
        self.ledger.record_upload(events.nbytes, 1, msgs=1)
        new_rg, new_rc = ffd.ffd_apply_events(dev[0], dev[1], dev_ev)
        dev[0], dev[1] = new_rg, new_rc
        tags[0] = (prov[0], dig_rg)
        tags[1] = (prov[1], dig_rc)
        self.stats["event_batches"] += 1
        self.stats["event_edits"] += k
        obstrace.annotate(run_events=k)
        return True

    def context_signature(self, key: tuple, exclude: tuple = ()) -> Optional[tuple]:
        """Content signature of the bucket's resident entries OUTSIDE
        `exclude` (ARG_SPEC indices), read from the adopt tags. Two equal
        signatures prove byte-identical non-excluded kernel args — the
        node-table/core-identity leg of checkpoint prefix validity
        (backend._plan_resume) — independent of how many solves ran in
        between. None until the bucket is fully tagged."""
        bkt = self._buckets.get(key)
        if bkt is None:
            return None
        tags = bkt[1]
        out = []
        for i, t in enumerate(tags):
            if i in exclude:
                continue
            if t is None:
                return None
            out.append(t[1])
        return tuple(out)

    def adopt(self, host_args: tuple, prov: tuple, sharding=None,
              ns=None) -> tuple:
        """Return device-resident buffers matching `host_args`, uploading
        only stale entries as ONE packed buffer. `prov` aligns with
        `host_args` (backend.host_kernel_args): a hashable content-identity
        token per entry, or None to force the digest path.

        `sharding` may be a single placement for every entry (the batched-
        consolidation universe) or a TUPLE aligned with `host_args` — the
        mesh-sharded solve places the run blocks partitioned over the
        "shards" axis and the core tables replicated. Per-entry shardings
        pack stale entries into one buffer PER DISTINCT SHARDING (≤2
        messages for a sharded solve: one partitioned, one replicated);
        partitioned groups upload only 1/Nd of their bytes to each device
        (_buffer_sharding), counted as shard bytes on the ledger."""
        import jax

        self.stats["adopts"] += 1
        key = self.bucket_key(host_args, sharding, ns=ns)
        bkt = self._buckets.pop(key, None)
        if bkt is None:
            while len(self._buckets) >= self.max_buckets:
                self._evict_bucket(next(iter(self._buckets)))
            bkt = [[None] * len(host_args), [None] * len(host_args)]
        # re-insert on EVERY adopt: dict order is the LRU order the budget
        # enforcer and the bucket cap both evict from the front of
        self._buckets[key] = bkt
        self._account(key, "args", sum(int(a.nbytes) for a in host_args))
        dev, tags = bkt
        stale: List[int] = []
        for i, a in enumerate(host_args):
            tok = prov[i]
            ent = tags[i]
            if dev[i] is not None and ent is not None:
                if tok is not None and ent[0] == tok:
                    continue  # provenance proves content identity
                dig = _digest(a)
                if ent[1] == dig:
                    # same bytes under a new token (rebuilt-but-identical
                    # core, e.g. relax-loop iterations): keep the buffer
                    tags[i] = (tok, dig)
                    continue
            else:
                dig = _digest(a)
            tags[i] = (tok, dig)
            stale.append(i)
        led = self.ledger
        self.last_stale = tuple(stale)
        if not stale:
            self.stats["exact_hits"] += 1
            led.record_adopt("exact_hit")
            self._enforce_budget(key)
            return tuple(dev)
        # pack stale entries into one contiguous byte buffer per distinct
        # sharding → one upload each → jitted unpack scatters into typed
        # device buffers (a single/None sharding keeps the one-message path)
        if isinstance(sharding, tuple):
            groups: Dict[object, List[int]] = {}
            for i in stale:
                groups.setdefault(sharding[i], []).append(i)
        else:
            groups = {sharding: list(stale)}
        total_bytes = 0
        total_shard = 0
        for shd, idxs in groups.items():
            specs = []
            parts = []
            off = 0
            for i in idxs:
                a = np.ascontiguousarray(host_args[i])
                specs.append((off, a.shape, a.dtype.str))
                parts.append(a.reshape(-1).view(np.uint8))
                off += a.nbytes
            buf = np.concatenate(parts) if len(parts) > 1 else parts[0]
            buf_shd, n_way = _buffer_sharding(shd)
            if buf_shd is not None and buf.nbytes % n_way:
                # equal byte split across the mesh axis; tail padding is
                # past every spec's range, the unpack never reads it
                pad = n_way - buf.nbytes % n_way
                buf = np.concatenate([buf, np.zeros(pad, np.uint8)])
            dev_buf = (
                jax.device_put(buf) if shd is None
                else jax.device_put(buf, buf_shd if buf_shd is not None else shd)
            )
            new = _unpack_fn(tuple(specs), shd)(dev_buf)
            for j, i in enumerate(idxs):
                dev[i] = new[j]
            total_bytes += off
            if n_way > 1:
                total_shard += off
        full = len(stale) == len(host_args)
        self.stats["full_uploads" if full else "delta_uploads"] += 1
        led.record_upload(total_bytes, len(stale), msgs=len(groups),
                          shard_bytes=total_shard)
        led.record_adopt("full_upload" if full else "delta_upload")
        self._enforce_budget(key)
        return tuple(dev)
