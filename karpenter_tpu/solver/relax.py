"""Respect-mode preferences on the DEVICE path: relax-and-redispatch.

The oracle treats preferences as required, then relaxes a failing pod's
lowest-weight preference and retries that pod in place
(scheduler._schedule_with_relaxation; scheduling.md:212-219). Re-dispatching
the WHOLE solve from scratch with one more preference dropped replays the
oracle's decision sequence exactly — pods before the relaxed one place
identically, the relaxed pod retries under the same state — so the host
drives the relaxation loop while every iteration runs on device
(VERDICT r4 next #9). In the common production case (kube's default-on
ScheduleAnyway spreads that are satisfiable), zero pods fail and ONE
dispatch serves the solve — the class that previously forced every such
surge onto the interpreter-speed oracle.

Supported preference kinds (the others return None -> whole-solve oracle):
  - ScheduleAnyway topology spread (weight 0, relaxed first) — materializes
    to DoNotSchedule;
  - weighted POSITIVE pod affinity — materializes to a required term;
  - preferred NODE affinity — active terms union into the pod's required
    node-affinity term (exactly the oracle's
    _pod_requirement_alternatives base ∪ prefs), so they narrow the device
    solve like any node selector. Pods with OR'd alternatives are already
    fallback groups, so the union targets at most one term.
Weighted ANTI terms on the zone/ct axes materialize ADMISSION-ONLY
(encode kind 3): they block and commit like a required anti for the owning
pod, but never register as owned antis — the oracle's bookkeeping records
only the ORIGINAL pod, so satisfied preferences never constrain later
members — on every topology key (zone/ct via V kind 3, hostname via Q
kind 3: the allowance treats it as an anti while the e_co/c_co owner
registrations stay kind-1-gated).

Ordering: the materialized pods are re-encoded in the ORIGINAL pods'
canonical FFD order (SolverInput.presorted) — their mutated signatures
would otherwise regroup within equal-size blocks and diverge from the
oracle's fixed processing order.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from ..api import wellknown as wk
from ..api.objects import Pod


def relax_items(pod: Pod) -> Optional[List[Tuple[int, int, str, int]]]:
    """Droppable preferences in the oracle's exact relaxation order
    ((weight, kind, idx) ascending — scheduler._schedule_with_relaxation).
    Returns None when the pod carries a preference kind the device loop
    cannot express."""
    items: List[Tuple[int, int, str, int]] = []
    for i, (w, _r) in enumerate(pod.preferred_node_affinity):
        items.append((w, 0, "na", i))
    for i, t in enumerate(pod.topology_spread):
        if t.when_unsatisfiable == "ScheduleAnyway":
            items.append((0, 1, "tsc", i))
    for i, t in enumerate(pod.affinity_terms):
        if t.weight is not None:
            if t.anti and t.topology_key not in (
                wk.ZONE_LABEL, wk.CAPACITY_TYPE_LABEL, wk.HOSTNAME_LABEL
            ):
                return None  # custom-key weighted antis: oracle
            items.append((t.weight, 2, "aff", i))
    items.sort(key=lambda it: (it[0], it[1], it[3]))
    return items


def materialize_pod(pod: Pod, items, n_dropped: int) -> Pod:
    """Pod view with the still-active preferences REQUIRED and the dropped
    ones gone — mirrors scheduler._effective_pod."""
    active = items[n_dropped:]
    act_tsc = {i for (_w, _k, tag, i) in active if tag == "tsc"}
    act_aff = {i for (_w, _k, tag, i) in active if tag == "aff"}
    act_na = [i for (_w, _k, tag, i) in active if tag == "na"]
    tscs = []
    for i, t in enumerate(pod.topology_spread):
        if t.when_unsatisfiable == "DoNotSchedule":
            tscs.append(t)
        elif i in act_tsc:
            tscs.append(dataclasses.replace(t, when_unsatisfiable="DoNotSchedule"))
    affs = []
    for i, t in enumerate(pod.affinity_terms):
        if t.weight is None:
            affs.append(t)
        elif i in act_aff:
            # active weighted ANTI terms materialize ADMISSION-ONLY (encode
            # kind 3): they block this pod like a required anti but never
            # register — matching the oracle's original-pod bookkeeping
            affs.append(
                dataclasses.replace(t, weight=None, admission_only=t.anti)
            )
    node_aff = pod.node_affinity
    prefs = []
    if act_na:
        # active preferred node affinity unions into the required term —
        # the oracle's base ∪ prefs (dropped prefs vanish, preserving its
        # ascending-weight relaxation); the materialized pod carries NO
        # preferred terms so encode keeps it on device
        base = pod.preferred_node_affinity[act_na[0]][1]
        for i in act_na[1:]:
            base = base.union(pod.preferred_node_affinity[i][1])
        node_aff = (
            [term.union(base) for term in pod.node_affinity]
            if pod.node_affinity
            else [base]
        )
    return dataclasses.replace(
        pod,
        topology_spread=tscs,
        affinity_terms=affs,
        node_affinity=node_aff,
        preferred_node_affinity=prefs,
    )


def plan(qinp) -> Optional[Dict[str, list]]:
    """uid -> relax item list for every preference-carrying pod, or None
    when any pod carries an unsupported kind (or there is nothing to relax).
    An empty dict is never returned — callers take the plain path then."""
    if qinp.preference_policy == "Ignore":
        return None
    items_map: Dict[str, list] = {}
    for pod in qinp.pods:
        items = relax_items(pod)
        if items is None:
            return None
        if items:
            items_map[pod.meta.uid] = items
    return items_map or None
